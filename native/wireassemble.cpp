// One-pass wire assembly (r17) — the fused pack+delta+codec emitter.
//
// The numpy pack pipeline (twtml_tpu/features/batch.py pack_batch /
// pack_ragged_sharded / pack_ragged_group — the byte-identical ground
// truth) touches the wire bytes 3-5 times on the ONE usable host core:
// per-field np.stack + np.ascontiguousarray copies, the offsets→deltas
// pass, the digram-encode pass into a fresh buffer, and the final
// np.concatenate into yet another fresh buffer. This emitter lays the
// FINAL PackedBatch buffer down in one sweep: for every (shard, k)
// segment it memcpys the units (digram-encoding them via the shared LUT
// when the codec applies — reusing wirecodec.cpp's digram_encode, so the
// dictionary has exactly one definition), emits the offsets as uint16
// length deltas under the caller's static row_len gate, and lays the
// numeric/label/mask sideband behind them. k=1 degenerates to the flat
// and per-shard wires, so all three Python packers ride this one entry.
//
// Destination and scratch are CALLER-OWNED (the pooled buffer arena,
// twtml_tpu/features/arena.py): this pass allocates nothing — per-tick
// fresh wire buffers are both CPU churn and the fuel for the measured
// axon-client RSS retention (BENCHMARKS.md r3 soak).
//
// Layout contract (must stay byte-identical to features/batch.py —
// tests/test_wireassemble.py is the differential):
//   out = [S, K, per-segment], segment (si, ki) at (si*K + ki)*per_seg:
//     units   enc_bucket bytes (codes, zero-padded) | n_sb*unit_size raw
//     offsets bl uint16 deltas | (bl+1) int32 raw
//     numeric bl*4 float32, label bl float32, mask bl float32
//
// Codec decision (mirrors _encode_units_segments/_encode_units_codec):
// all segments encode into scratch; auto mode picks the shared bucket
// max(1024, ceil(max_len/1024)*1024) and falls back to the raw wire when
// the bucket is not strictly smaller than the raw segment; a forced
// bucket (the multi-host cross-agreed value) that under-covers a segment
// is an error, never silent truncation.

#include <cstdint>
#include <cstring>

extern "C" {

// native/wirecodec.cpp — the one greedy digram encoder both wire forms use
int64_t digram_encode(const uint8_t* in, int64_t n, const uint8_t* lut,
                      uint8_t* out, int64_t cap);

// Mirrors features/wirecodec.encoded_bucket: max(1024, round up to 1024).
static int64_t enc_bucket_of(int64_t m) {
  const int64_t kMultiple = 1024;  // wirecodec.CODEC_UNIT_MULTIPLE
  int64_t b = ((m + kMultiple - 1) / kMultiple) * kMultiple;
  return b < kMultiple ? kMultiple : b;
}

// Returns total bytes written, or:
//   -1  destination capacity exceeded (caller sized it wrong)
//   -2  offsets not uint16-delta encodable (negative or > 65535 length)
//   -3  forced codec bucket under-covers a segment encoding
// out_enc_bucket receives the chosen per-segment codec bucket (0 = the
// raw units wire — codec off, or the incompressible fallback).
int64_t wire_assemble(
    const void* const* units_ptrs,   // [k] per-batch units, s*n_sb units
    const int32_t* const* offs_ptrs, // [k] per-batch offsets, s*(bl+1)
    const float* const* num_ptrs,    // [k] numeric, s*bl*4
    const float* const* lab_ptrs,    // [k] label, s*bl
    const float* const* mask_ptrs,   // [k] mask, s*bl
    int64_t k, int64_t s, int64_t n_sb, int64_t bl,
    int64_t unit_size,               // 1 (uint8) or 2 (uint16)
    int64_t narrow_offsets,          // 1 = uint16 deltas, 0 = raw int32
    const uint8_t* lut,              // pair LUT, NULL = codec off
    int64_t forced_bucket,           // > 0: cross-host agreed bucket
    uint8_t* scratch,                // s*k*n_sb bytes iff lut != NULL
    int64_t* enc_lens,               // [s*k] iff lut != NULL
    uint8_t* out, int64_t cap,
    int64_t* out_enc_bucket) {
  int64_t enc_bucket = 0;
  if (lut != nullptr && unit_size == 1) {
    int64_t max_len = 0;
    for (int64_t si = 0; si < s; ++si) {
      for (int64_t ki = 0; ki < k; ++ki) {
        const int64_t seg = si * k + ki;
        const uint8_t* src =
            (const uint8_t*)units_ptrs[ki] + si * n_sb;
        // encode can never exceed its input length (a pair shrinks, a
        // literal copies), so cap = n_sb always fits
        const int64_t m =
            digram_encode(src, n_sb, lut, scratch + seg * n_sb, n_sb);
        enc_lens[seg] = m;
        if (m > max_len) max_len = m;
      }
    }
    if (forced_bucket > 0) {
      if (max_len > forced_bucket) return -3;
      enc_bucket = forced_bucket;
    } else {
      const int64_t b = enc_bucket_of(max_len);
      // not strictly smaller than raw: the raw wire is the smaller wire
      enc_bucket = (b >= n_sb) ? 0 : b;
    }
  }
  const int64_t per_units =
      enc_bucket ? enc_bucket : n_sb * unit_size;
  const int64_t per_offs =
      narrow_offsets ? bl * 2 : (bl + 1) * 4;
  const int64_t per_side = bl * 4 * 4 + bl * 4 + bl * 4;
  const int64_t per_seg = per_units + per_offs + per_side;
  const int64_t total = s * k * per_seg;
  if (total > cap) return -1;
  for (int64_t si = 0; si < s; ++si) {
    for (int64_t ki = 0; ki < k; ++ki) {
      const int64_t seg = si * k + ki;
      uint8_t* p = out + seg * per_seg;
      if (enc_bucket) {
        const int64_t m = enc_lens[seg];
        std::memcpy(p, scratch + seg * n_sb, (size_t)m);
        std::memset(p + m, 0, (size_t)(enc_bucket - m));
      } else {
        std::memcpy(p, (const uint8_t*)units_ptrs[ki] +
                           si * n_sb * unit_size,
                    (size_t)(n_sb * unit_size));
      }
      p += per_units;
      const int32_t* offs = offs_ptrs[ki] + si * (bl + 1);
      if (narrow_offsets) {
        for (int64_t r = 0; r < bl; ++r) {
          const int64_t d = (int64_t)offs[r + 1] - (int64_t)offs[r];
          if (d < 0 || d > 0xFFFF) return -2;
          const uint16_t d16 = (uint16_t)d;
          std::memcpy(p + r * 2, &d16, 2);
        }
      } else {
        std::memcpy(p, offs, (size_t)((bl + 1) * 4));
      }
      p += per_offs;
      std::memcpy(p, num_ptrs[ki] + si * bl * 4, (size_t)(bl * 4 * 4));
      p += bl * 4 * 4;
      std::memcpy(p, lab_ptrs[ki] + si * bl, (size_t)(bl * 4));
      p += bl * 4;
      std::memcpy(p, mask_ptrs[ki] + si * bl, (size_t)(bl * 4));
    }
  }
  *out_enc_bucket = enc_bucket;
  return total;
}

}  // extern "C"
