// One-pass host featurize (r18) — the fused numeric+label+mask+wire
// emitter behind --featurizeNative.
//
// BENCHMARKS r17 left the host chain featurize-dominated: between the
// native parse (PR 6) and the native pack (PR 14), the featurize stage
// still ran several separate numpy passes (float64 scale + f32 cast,
// label/mask fills, the ragged-wire zero+copy) plus — on object ingest —
// four per-tweet Python traversals. This entry collapses the array half
// of that stage into ONE C sweep: given the batch's encoded units +
// offsets and its numeric columns (float64 straight from the Python
// Status traversal, or int64 straight from the block parser), it emits
// the final ragged-wire arrays — flat units buffer (narrow uint8 when
// every row is ASCII), padded int32 offsets, scaled float32
// numeric/label/mask — into CALLER-OWNED destinations (one pooled arena
// lease, twtml_tpu/features/arena.py; this pass allocates nothing).
//
// Parity law (twtml_tpu/features/featurizer.py is the ground truth;
// tests/test_featurize_native.py is the differential):
//   numeric[:, 0..2] = (float)((double)col * 1e-12)
//   numeric[:, 3]    = (float)(((double)now_ms - (double)created) * 1e-14)
//   label            = (float)(double)label_col   (Python may overwrite
//                      label[:n] afterwards for label_fn variants)
//   units/offsets    = features/batch.ragged_wire_arrays, byte for byte
// float64-multiply-then-f32-cast matches numpy's astype(float64) * scale
// stored into a float32 array exactly (same IEEE ops, same order).
// int64→double conversion is the same correctly-rounded conversion
// numpy's astype performs. col_order maps the two callers' column
// layouts onto one loop, so the scaling code exists exactly once.

#include <cstdint>
#include <cstring>

namespace {

// hand-scaling constants of the reference (MllibHelper.scala:64-67),
// duplicated from featurizer.py COUNT_SCALE/AGE_SCALE — a differential
// test pins the two definitions together.
constexpr double kCountScale = 1e-12;
constexpr double kAgeScale = 1e-14;

}  // namespace

extern "C" {

// Returns the maximum row length seen (>= 0) for the caller's row_len
// bucket policy, or -1 when offsets overrun n_bucket (caller sized the
// destination from these offsets; never expected — the caller falls back
// to the numpy ground truth, which cannot hit it).
//
//   units:       source code units (unit_size bytes each; uint16 from the
//                object path's UTF-16 encode, uint8|uint16 from blocks)
//   offsets:     [n+1] int64 row offsets into units
//   cols_f64 /   exactly one non-NULL: [n, 5] numeric columns (float64
//   cols_i64     from the Status traversal / int64 from the block parser)
//   col_order:   [5] source-column indices of followers, favourites,
//                friends, created_ms, label
//   n:           kept rows;  b: padded rows;  n_bucket: flat units
//                capacity (RAGGED_UNIT_MULTIPLE-rounded)
//   narrow:      1 = emit uint8 units (every row ASCII — metadata-gated
//                by the caller, never sniffed), 0 = emit uint16
//   out_units:   [n_bucket] uint8|uint16 — zero-padded past the total
//   out_offsets: [b+1] int32 — rows past n hold the total (length 0)
//   out_numeric: [b, 4] float32;  out_label/out_mask: [b] float32 —
//                all fully written (the lease buffer arrives dirty)
int64_t featurize_wire(
    const void* units, int64_t unit_size,
    const int64_t* offsets,
    const double* cols_f64, const int64_t* cols_i64,
    const int64_t* col_order,
    int64_t n, int64_t b, int64_t n_bucket,
    int64_t now_ms, int64_t narrow,
    void* out_units, int32_t* out_offsets,
    float* out_numeric, float* out_label, float* out_mask) {
  const int64_t total = n ? offsets[n] : 0;
  if (total > n_bucket || total < 0) return -1;

  // -- units: one copy (narrowing or widening folded in), zeroed tail ---
  if (narrow) {
    uint8_t* out8 = static_cast<uint8_t*>(out_units);
    if (unit_size == 1) {
      std::memcpy(out8, units, static_cast<size_t>(total));
    } else {
      const uint16_t* in16 = static_cast<const uint16_t*>(units);
      for (int64_t i = 0; i < total; ++i)
        out8[i] = static_cast<uint8_t>(in16[i]);  // values < 128 by gate
    }
    std::memset(out8 + total, 0, static_cast<size_t>(n_bucket - total));
  } else {
    uint16_t* out16 = static_cast<uint16_t*>(out_units);
    if (unit_size == 2) {
      std::memcpy(out16, units, static_cast<size_t>(total) * 2);
    } else {
      const uint8_t* in8 = static_cast<const uint8_t*>(units);
      for (int64_t i = 0; i < total; ++i) out16[i] = in8[i];
    }
    std::memset(out16 + total, 0,
                static_cast<size_t>(n_bucket - total) * 2);
  }

  // -- offsets: [b+1] int32, pad rows pinned at total (length 0) --------
  int64_t max_len = 0;
  for (int64_t i = 0; i < n; ++i) {
    out_offsets[i] = static_cast<int32_t>(offsets[i]);
    const int64_t len = offsets[i + 1] - offsets[i];
    if (len > max_len) max_len = len;
  }
  const int32_t total32 = static_cast<int32_t>(total);
  for (int64_t i = n; i <= b; ++i) out_offsets[i] = total32;

  // -- scaled numeric + label + mask, one pass over the columns ---------
  const int64_t cf = col_order[0], cv = col_order[1], cr = col_order[2],
                cc = col_order[3], cl = col_order[4];
  const double now = static_cast<double>(now_ms);
  for (int64_t i = 0; i < n; ++i) {
    double followers, favourites, friends, created, labelv;
    if (cols_f64 != nullptr) {
      const double* row = cols_f64 + i * 5;
      followers = row[cf]; favourites = row[cv]; friends = row[cr];
      created = row[cc]; labelv = row[cl];
    } else {
      const int64_t* row = cols_i64 + i * 5;
      followers = static_cast<double>(row[cf]);
      favourites = static_cast<double>(row[cv]);
      friends = static_cast<double>(row[cr]);
      created = static_cast<double>(row[cc]);
      labelv = static_cast<double>(row[cl]);
    }
    float* num = out_numeric + i * 4;
    num[0] = static_cast<float>(followers * kCountScale);
    num[1] = static_cast<float>(favourites * kCountScale);
    num[2] = static_cast<float>(friends * kCountScale);
    num[3] = static_cast<float>((now - created) * kAgeScale);
    out_label[i] = static_cast<float>(labelv);
    out_mask[i] = 1.0f;
  }
  if (b > n) {
    std::memset(out_numeric + n * 4, 0,
                static_cast<size_t>(b - n) * 4 * sizeof(float));
    std::memset(out_label + n, 0,
                static_cast<size_t>(b - n) * sizeof(float));
    std::memset(out_mask + n, 0,
                static_cast<size_t>(b - n) * sizeof(float));
  }
  return max_len;
}

}  // extern "C"
