// Fast char-bigram HashingTF featurizer — the host-side hot loop in C++.
//
// Semantics are identical to twtml_tpu/features/hashing.py (the ground
// truth): Java String.hashCode over UTF-16 code units per bigram
// (h = 31*cu0 + cu1 in int32 arithmetic), nonNegativeMod into num_features,
// term-frequency counts deduplicated per tweet. The Python caller lowercases
// and encodes to UTF-16-LE (locale-correct, cheap CPython fast paths); this
// code consumes raw code units — surrogate pairs therefore contribute their
// two units exactly like the JVM, matching MllibHelper.scala:42-56 /
// MLlib HashingTF.
//
// Build: g++ -O3 -shared -fPIC -pthread -o libfasthash.so fasthash.cpp
// Loaded via ctypes (twtml_tpu/features/native.py); pure-Python fallback
// remains authoritative for parity tests.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Open-addressing scratch table for per-tweet term-frequency dedup.
// Tweets cap at 280 chars -> <=279 bigrams; 1024 slots keep load < 0.28.
constexpr int kTableSize = 1024;  // power of two
constexpr int kTableMask = kTableSize - 1;

struct Slot {
  int32_t idx;   // hashed feature index, -1 = empty
  float count;
};

inline int32_t non_negative_mod(int32_t x, int32_t m) {
  int32_t r = x % m;           // C++ % truncates toward zero, like Java
  return r < 0 ? r + m : r;
}

}  // namespace

extern "C" {

// Featurize one micro-batch of lowercased UTF-16-LE texts.
//
//   units:        concatenated code units of all texts
//   offsets:      B+1 prefix offsets into `units` (in code units)
//   batch:        number of texts B
//   num_features: HashingTF dimensionality
//   l_max:        token capacity per row in the padded output
//   out_idx:      [B, l_max] int32, caller-zeroed
//   out_val:      [B, l_max] float32, caller-zeroed
//   out_ntok:     [B] int32 — distinct hashed terms per tweet (may exceed
//                 l_max; caller re-buckets and retries in that case)
//
// Returns the maximum distinct-term count seen (for bucket sizing).
static int32_t fasthash_rows(const uint16_t* units, const int64_t* offsets,
                             int32_t row_begin, int32_t row_end,
                             int32_t num_features, int32_t l_max,
                             int32_t* out_idx, float* out_val,
                             int32_t* out_ntok) {
  Slot table[kTableSize];
  for (int32_t i = 0; i < kTableSize; ++i) table[i].idx = -1;
  int32_t max_terms = 0;

  for (int32_t b = row_begin; b < row_end; ++b) {
    const int64_t start = offsets[b];
    const int64_t end = offsets[b + 1];
    const int64_t len = end - start;

    // collect this tweet's distinct (index, count) pairs
    int32_t used[kTableSize];
    int32_t n_used = 0;

    bool overflowed = false;
    auto add_term = [&](int32_t h) {
      // A full table has no empty slot to terminate the probe loop, and a
      // new distinct term couldn't be inserted anyway — bail to the exact
      // Python path before probing.
      if (n_used == kTableSize) {
        overflowed = true;
        return;
      }
      const int32_t idx = non_negative_mod(h, num_features);
      uint32_t probe = static_cast<uint32_t>(idx) & kTableMask;
      while (true) {
        Slot& s = table[probe];
        if (s.idx == idx) {
          s.count += 1.0f;
          return;
        }
        if (s.idx < 0) {
          s.idx = idx;
          s.count = 1.0f;
          used[n_used++] = static_cast<int32_t>(probe);
          return;
        }
        probe = (probe + 1) & kTableMask;
      }
    };

    if (len == 1) {
      // sliding(2) on a 1-unit string yields the string itself
      add_term(static_cast<int32_t>(units[start]));
    } else {
      for (int64_t i = start; i + 1 < end && !overflowed; ++i) {
        // Java hashCode of the 2-unit string: 31*cu0 + cu1 (int32 wrap)
        const int32_t h = static_cast<int32_t>(
            31u * static_cast<uint32_t>(units[i]) +
            static_cast<uint32_t>(units[i + 1]));
        add_term(h);
      }
    }

    if (overflowed) {
      // >kTableSize distinct terms in one tweet: unambiguous sentinel so the
      // Python caller falls back to the exact path
      out_ntok[b] = -1;
      for (int32_t j = 0; j < n_used; ++j) table[used[j]].idx = -1;
      continue;
    }
    out_ntok[b] = n_used;
    if (n_used > max_terms) max_terms = n_used;
    const int32_t n_emit = n_used < l_max ? n_used : l_max;
    int32_t* row_idx = out_idx + static_cast<int64_t>(b) * l_max;
    float* row_val = out_val + static_cast<int64_t>(b) * l_max;
    for (int32_t j = 0; j < n_emit; ++j) {
      const Slot& s = table[used[j]];
      row_idx[j] = s.idx;
      row_val[j] = s.count;
    }
    // reset only the touched slots for the next row (the full table is
    // cleared once per thread above)
    for (int32_t j = 0; j < n_used; ++j) table[used[j]].idx = -1;
  }
  return max_terms;
}

// Featurize one micro-batch, row-parallel across up to n_threads OS threads
// (rows are independent; each thread owns a contiguous row range and its own
// scratch table). n_threads <= 0 means auto (hardware concurrency, capped).
// The ctypes caller releases the GIL for the duration of this call.
int32_t fasthash_batch(const uint16_t* units, const int64_t* offsets,
                       int32_t batch, int32_t num_features, int32_t l_max,
                       int32_t* out_idx, float* out_val, int32_t* out_ntok,
                       int32_t n_threads) {
  constexpr int32_t kMinRowsPerThread = 256;
  if (n_threads <= 0) {
    const unsigned hw = std::thread::hardware_concurrency();
    n_threads = static_cast<int32_t>(hw ? std::min(hw, 8u) : 1u);
  }
  n_threads = std::max(
      1, std::min(n_threads, batch / kMinRowsPerThread));

  if (n_threads == 1) {
    return fasthash_rows(units, offsets, 0, batch, num_features, l_max,
                         out_idx, out_val, out_ntok);
  }

  std::vector<int32_t> maxes(n_threads, 0);
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const int32_t rows_per = (batch + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    const int32_t b0 = t * rows_per;
    const int32_t b1 = std::min(batch, b0 + rows_per);
    workers.emplace_back([=, &maxes] {
      maxes[t] = fasthash_rows(units, offsets, b0, b1, num_features, l_max,
                               out_idx, out_val, out_ntok);
    });
  }
  int32_t max_terms = 0;
  for (int32_t t = 0; t < n_threads; ++t) {
    workers[t].join();
    max_terms = std::max(max_terms, maxes[t]);
  }
  return max_terms;
}

// Ragged→padded copy for the on-device featurization wire format
// (UnitBatch): concatenated code units → [padded_rows, l_max] uint16 with
// zero padding, plus per-row unit counts. Row-sliced memcpys beat numpy's
// vectorized gather ~10x at tweet sizes. Rows in [batch, padded_rows) are
// zeroed here too, so the caller can hand in uninitialized buffers.
// ascii_lower != 0 folds 'A'-'Z' to lowercase during the copy: the Python
// caller then only pays str.lower() for texts containing non-ASCII chars
// (those are pre-lowered, and re-folding their ASCII range is idempotent).
// Returns the maximum row length seen; the caller sized l_max from the same
// offsets, so a return value > l_max means caller error (nothing truncated
// silently — the rows are copied clamped but flagged by the return).
int32_t pad_units_batch(const uint16_t* units, const int64_t* offsets,
                        int32_t batch, int32_t padded_rows, int32_t l_max,
                        int32_t ascii_lower, uint16_t* out_units,
                        int32_t* out_len) {
  int32_t max_len = 0;
  for (int32_t b = 0; b < batch; ++b) {
    const int64_t start = offsets[b];
    const int64_t len = offsets[b + 1] - start;
    max_len = std::max(max_len, static_cast<int32_t>(len));
    const int64_t n = std::min<int64_t>(len, l_max);
    uint16_t* row = out_units + static_cast<int64_t>(b) * l_max;
    if (ascii_lower) {
      for (int64_t i = 0; i < n; ++i) {
        const uint16_t u = units[start + i];
        row[i] = (u >= 'A' && u <= 'Z') ? u + 32 : u;
      }
    } else {
      std::memcpy(row, units + start, n * sizeof(uint16_t));
    }
    std::memset(row + n, 0, (l_max - n) * sizeof(uint16_t));
    out_len[b] = static_cast<int32_t>(n);
  }
  if (padded_rows > batch) {
    std::memset(out_units + static_cast<int64_t>(batch) * l_max, 0,
                static_cast<int64_t>(padded_rows - batch) * l_max *
                    sizeof(uint16_t));
    std::memset(out_len + batch, 0,
                (padded_rows - batch) * sizeof(int32_t));
  }
  return max_len;
}

// uint8 variant of pad_units_batch: the narrow wire format for batches the
// caller KNOWS are byte-ranged (every row ASCII-flagged by the parser /
// isascii() on the host path) — host→device transfer is the streaming hot
// loop's bottleneck and the units buffer is its largest tensor, so the
// narrow pad halves it with zero extra scans. Units >= 256 must not reach
// this function (the caller's ascii gate guarantees < 128).
int32_t pad_units_batch_u8(const uint16_t* units, const int64_t* offsets,
                           int32_t batch, int32_t padded_rows, int32_t l_max,
                           int32_t ascii_lower, uint8_t* out_units,
                           int32_t* out_len) {
  int32_t max_len = 0;
  for (int32_t b = 0; b < batch; ++b) {
    const int64_t start = offsets[b];
    const int64_t len = offsets[b + 1] - start;
    max_len = std::max(max_len, static_cast<int32_t>(len));
    const int64_t n = std::min<int64_t>(len, l_max);
    uint8_t* row = out_units + static_cast<int64_t>(b) * l_max;
    if (ascii_lower) {
      for (int64_t i = 0; i < n; ++i) {
        const uint16_t u = units[start + i];
        row[i] = static_cast<uint8_t>((u >= 'A' && u <= 'Z') ? u + 32 : u);
      }
    } else {
      for (int64_t i = 0; i < n; ++i)
        row[i] = static_cast<uint8_t>(units[start + i]);
    }
    std::memset(row + n, 0, l_max - n);
    out_len[b] = static_cast<int32_t>(n);
  }
  if (padded_rows > batch) {
    std::memset(out_units + static_cast<int64_t>(batch) * l_max, 0,
                static_cast<int64_t>(padded_rows - batch) * l_max);
    std::memset(out_len + batch, 0,
                (padded_rows - batch) * sizeof(int32_t));
  }
  return max_len;
}

// Lexicon sentiment scorer over raw UTF-16 units (features/sentiment.py's
// C hot path). Tokenization matches the Python `[a-z']+` regex over
// lowercased text for ASCII rows: A-Z fold inline, every other unit is a
// separator. Rows containing units >= 128 are flagged not-ok (out_ok = 0)
// and the caller scores them in Python — Unicode lowercasing can change
// token boundaries, so exact parity demands the Python path there.
// Lexicon words arrive as concatenated units + offsets with precomputed
// Java-hashCode values; a hash hit verifies the actual units, so a
// colliding non-lexicon token can never flip a label vs the Python set.
namespace {
int32_t lexicon_find(const uint16_t* tok, int32_t tok_len, int32_t tok_hash,
                     const uint16_t* words, const int64_t* word_off,
                     const int32_t* word_hash, int32_t n_words) {
  for (int32_t w = 0; w < n_words; ++w) {
    if (word_hash[w] != tok_hash) continue;
    const int64_t len = word_off[w + 1] - word_off[w];
    if (len != tok_len) continue;
    if (std::memcmp(words + word_off[w], tok,
                    tok_len * sizeof(uint16_t)) == 0)
      return w;
  }
  return -1;
}
}  // namespace

void lexicon_score_batch(const uint16_t* units, const int64_t* offsets,
                         int32_t batch,
                         const uint16_t* pos_words, const int64_t* pos_off,
                         const int32_t* pos_hash, int32_t n_pos,
                         const uint16_t* neg_words, const int64_t* neg_off,
                         const int32_t* neg_hash, int32_t n_neg,
                         int32_t* out_score, uint8_t* out_ok) {
  for (int32_t b = 0; b < batch; ++b) {
    const int64_t start = offsets[b];
    const int64_t end = offsets[b + 1];
    bool ascii = true;
    for (int64_t i = start; i < end; ++i)
      if (units[i] >= 128) { ascii = false; break; }
    if (!ascii) {
      out_ok[b] = 0;
      out_score[b] = 0;
      continue;
    }
    int32_t score = 0;
    uint16_t tok[64];
    int32_t tok_len = 0;
    int32_t tok_hash = 0;
    bool overflow = false;
    auto flush = [&]() {
      if (tok_len > 0 && !overflow) {
        if (lexicon_find(tok, tok_len, tok_hash, pos_words, pos_off,
                         pos_hash, n_pos) >= 0)
          ++score;
        else if (lexicon_find(tok, tok_len, tok_hash, neg_words, neg_off,
                              neg_hash, n_neg) >= 0)
          --score;
      }
      tok_len = 0;
      tok_hash = 0;
      overflow = false;
    };
    for (int64_t i = start; i < end; ++i) {
      uint16_t u = units[i];
      if (u >= 'A' && u <= 'Z') u += 32;
      if ((u >= 'a' && u <= 'z') || u == '\'') {
        if (tok_len < 64) {
          tok[tok_len++] = u;
          tok_hash = static_cast<int32_t>(31u * static_cast<uint32_t>(tok_hash) +
                                          static_cast<uint32_t>(u));
        } else {
          overflow = true;  // longer than any lexicon word: never matches
        }
      } else {
        flush();
      }
    }
    flush();
    out_score[b] = score;
    out_ok[b] = 1;
  }
}

}  // extern "C"
