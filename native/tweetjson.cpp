// Native tweet-JSON block ingest — the framework's data-loader hot loop.
//
// The reference delegates ingestion to Twitter4j/Spark receivers (external
// JVM dependencies, SURVEY.md §2.4); our replay/stream sources parse
// newline-delimited tweet JSON. CPython json.loads + object assembly tops
// out near ~90k tweets/s on one core — an order of magnitude below the
// compute pipeline — so this parser extracts exactly the fields the
// featurizer reads (MllibHelper.scala:42-95: the retweeted status' text,
// retweet_count, user counts, timestamp) straight into columnar buffers,
// applying the isRetweet + retweet-count-interval filter in-line
// (MllibHelper.scala:89-95). Text is emitted as UTF-16-LE code units with
// JSON escapes resolved (\uXXXX surrogate halves pass through exactly like
// the JVM sees them), ready for the UnitBatch wire format (the device
// hashes bigrams over these units — ops/text_hash.py).
//
// Only well-formed JSON is expected; a malformed line is skipped and
// counted (callers surface the count). Semantic ground truth remains the
// Python path (features/featurizer.py Status.from_json + filtrate +
// featurize) — differential tests assert unit-for-unit equality.
//
// Build: compiled into libfasthash.so together with fasthash.cpp.

#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

struct Cursor {
  const char* p;
  const char* end;
  bool ok = true;

  bool at_end() const { return p >= end; }
  char peek() const { return at_end() ? '\0' : *p; }
  void skip_ws() {
    while (!at_end() && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool eat(char c) {
    skip_ws();
    if (at_end() || *p != c) return false;
    ++p;
    return true;
  }
};

// ---- string scanning ------------------------------------------------------

inline int hex_val(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

// Scan a JSON string (cursor at opening quote). If out != nullptr, write
// UTF-16 code units (escapes resolved, UTF-8 decoded) and return the unit
// count via *n_units (buffer has cap units; overflow sets cur.ok = false).
bool scan_string(Cursor& cur, uint16_t* out, int64_t cap, int64_t* n_units) {
  if (!cur.eat('"')) return false;
  int64_t n = 0;
  auto emit = [&](uint32_t cp) {
    if (out == nullptr) {
      n += cp >= 0x10000 ? 2 : 1;
      return;
    }
    if (cp >= 0x10000) {
      if (n + 2 > cap) { cur.ok = false; return; }
      cp -= 0x10000;
      out[n++] = static_cast<uint16_t>(0xD800 + (cp >> 10));
      out[n++] = static_cast<uint16_t>(0xDC00 + (cp & 0x3FF));
    } else {
      if (n + 1 > cap) { cur.ok = false; return; }
      out[n++] = static_cast<uint16_t>(cp);
    }
  };
  while (!cur.at_end() && cur.ok) {
    // bulk fast path: plain-ASCII runs (the overwhelming majority of tweet
    // bytes) copy/count without per-byte dispatch — SWAR scans 8 bytes per
    // iteration for the next special byte (quote/escape/UTF-8 lead); the
    // scalar loop below handles only that byte
    {
      const char* q = cur.p;
      while (cur.end - q >= 8) {
        uint64_t v;
        std::memcpy(&v, q, 8);
        uint64_t hi = v & 0x8080808080808080ULL;           // >= 0x80
        uint64_t xq = v ^ 0x2222222222222222ULL;           // '"'
        uint64_t xb = v ^ 0x5C5C5C5C5C5C5C5CULL;           // '\\'
        uint64_t sq = (xq - 0x0101010101010101ULL) & ~xq;
        uint64_t sb = (xb - 0x0101010101010101ULL) & ~xb;
        uint64_t special = (hi | sq | sb) & 0x8080808080808080ULL;
        if (special) {
          q += __builtin_ctzll(special) >> 3;
          break;
        }
        q += 8;
      }
      while (q < cur.end) {
        unsigned char cc = static_cast<unsigned char>(*q);
        if (cc == '"' || cc == '\\' || cc >= 0x80) break;
        ++q;
      }
      int64_t run = q - cur.p;
      if (run > 0) {
        if (out != nullptr) {
          if (n + run > cap) { cur.ok = false; return false; }
          for (int64_t i = 0; i < run; ++i)
            out[n + i] = static_cast<uint16_t>(
                static_cast<unsigned char>(cur.p[i]));
        }
        n += run;
        cur.p = q;
        if (cur.at_end()) break;
      }
    }
    unsigned char c = static_cast<unsigned char>(*cur.p);
    if (c == '"') {
      ++cur.p;
      if (n_units) *n_units = n;
      return true;
    }
    if (c == '\\') {
      ++cur.p;
      if (cur.at_end()) break;
      char e = *cur.p++;
      switch (e) {
        case '"': emit('"'); break;
        case '\\': emit('\\'); break;
        case '/': emit('/'); break;
        case 'b': emit('\b'); break;
        case 'f': emit('\f'); break;
        case 'n': emit('\n'); break;
        case 'r': emit('\r'); break;
        case 't': emit('\t'); break;
        case 'u': {
          if (cur.end - cur.p < 4) return false;
          int v = 0;
          for (int i = 0; i < 4; ++i) {
            int h = hex_val(cur.p[i]);
            if (h < 0) return false;
            v = (v << 4) | h;
          }
          cur.p += 4;
          // emit the unit as-is: surrogate halves stay halves, exactly the
          // JVM's view of the string (features/hashing.py utf16_units)
          if (out != nullptr) {
            if (n + 1 > cap) { cur.ok = false; break; }
            out[n++] = static_cast<uint16_t>(v);
          } else {
            n += 1;
          }
          break;
        }
        default: return false;
      }
      continue;
    }
    // UTF-8 decode (1-4 bytes) -> code point, matching what the Python
    // fallback's json.loads(bytes) accepts: overlong encodings and values
    // past U+10FFFF are malformed (CPython utf-8 is strict about those),
    // but UTF-8-encoded SURROGATE code points are kept as lone UTF-16
    // units — json decodes bytes with errors='surrogatepass', and the
    // hashing ground truth handles lone surrogates by design
    // (features/hashing.py utf16_units)
    uint32_t cp;
    int extra;
    if (c < 0x80) { cp = c; extra = 0; }
    else if ((c >> 5) == 0x6) { cp = c & 0x1F; extra = 1; }
    else if ((c >> 4) == 0xE) { cp = c & 0x0F; extra = 2; }
    else if ((c >> 3) == 0x1E) { cp = c & 0x07; extra = 3; }
    else return false;
    if (cur.end - cur.p < extra + 1) return false;
    for (int i = 1; i <= extra; ++i) {
      unsigned char cc = static_cast<unsigned char>(cur.p[i]);
      if ((cc >> 6) != 0x2) return false;
      cp = (cp << 6) | (cc & 0x3F);
    }
    if (extra == 1 && cp < 0x80) return false;          // overlong
    if (extra == 2 && cp < 0x800) return false;         // overlong
    if (extra == 3 && (cp < 0x10000 || cp > 0x10FFFF)) return false;
    cur.p += extra + 1;
    emit(cp);
  }
  return false;
}

// ---- generic value skipping ----------------------------------------------

// Depth cap: a well-formed line with ~100k nested brackets would otherwise
// recurse once per level and smash the C stack; past the cap the line is a
// counted bad line, like the Python fallback's caught RecursionError.
constexpr int kMaxSkipDepth = 256;

bool skip_value(Cursor& cur, int depth = 0);

bool skip_container(Cursor& cur, char open, char close, int depth) {
  if (depth >= kMaxSkipDepth) return false;
  if (!cur.eat(open)) return false;
  cur.skip_ws();
  if (cur.peek() == close) { ++cur.p; return true; }
  while (true) {
    if (open == '{') {
      if (!scan_string(cur, nullptr, 0, nullptr)) return false;
      if (!cur.eat(':')) return false;
    }
    if (!skip_value(cur, depth + 1)) return false;
    cur.skip_ws();
    if (cur.peek() == ',') { ++cur.p; cur.skip_ws(); continue; }
    if (cur.peek() == close) { ++cur.p; return true; }
    return false;
  }
}

bool skip_value(Cursor& cur, int depth) {
  cur.skip_ws();
  char c = cur.peek();
  if (c == '"') return scan_string(cur, nullptr, 0, nullptr);
  if (c == '{') return skip_container(cur, '{', '}', depth);
  if (c == '[') return skip_container(cur, '[', ']', depth);
  // number / true / false / null: scan to a structural delimiter
  const char* start = cur.p;
  while (!cur.at_end() && *cur.p != ',' && *cur.p != '}' && *cur.p != ']' &&
         *cur.p != ' ' && *cur.p != '\t' && *cur.p != '\n' && *cur.p != '\r')
    ++cur.p;
  return cur.p > start;
}

// Parse an integer-valued JSON number (or a string wrapping one, Twitter's
// "timestamp_ms"); fractional digits are truncated. Returns false on
// non-numeric values with the cursor UNTOUCHED (parsing happens on a probe
// copy), so the caller's skip_value fallback starts from a clean position —
// e.g. a non-numeric quoted value is then skipped as a string, matching the
// Python path's keep-the-row-with-default behavior.
bool parse_int(Cursor& cur, int64_t* out) {
  Cursor probe = cur;
  probe.skip_ws();
  bool quoted = probe.peek() == '"';
  if (quoted) ++probe.p;
  bool neg = false;
  if (probe.peek() == '-') { neg = true; ++probe.p; }
  if (probe.at_end() || *probe.p < '0' || *probe.p > '9') return false;
  int64_t v = 0;
  while (!probe.at_end() && *probe.p >= '0' && *probe.p <= '9')
    v = v * 10 + (*probe.p++ - '0');
  if (!probe.at_end() && *probe.p == '.') {  // truncate fraction
    ++probe.p;
    while (!probe.at_end() && *probe.p >= '0' && *probe.p <= '9') ++probe.p;
  }
  if (quoted && !probe.eat('"')) return false;
  *out = neg ? -v : v;
  cur = probe;
  return true;
}

// "Wed Aug 27 13:08:45 +0000 2008" -> epoch millis (0 on mismatch).
int64_t parse_created_at(const uint16_t* u, int64_t n) {
  if (n != 30) return 0;
  char s[31];
  for (int i = 0; i < 30; ++i) {
    if (u[i] > 127) return 0;
    s[i] = static_cast<char>(u[i]);
  }
  s[30] = '\0';
  static const char* months = "JanFebMarAprMayJunJulAugSepOctNovDec";
  int mon = -1;
  for (int m = 0; m < 12; ++m)
    if (std::memcmp(s + 4, months + m * 3, 3) == 0) { mon = m; break; }
  if (mon < 0) return 0;
  auto num = [&](int off, int len) {
    int v = 0;
    for (int i = 0; i < len; ++i) {
      if (s[off + i] < '0' || s[off + i] > '9') return -1;
      v = v * 10 + (s[off + i] - '0');
    }
    return v;
  };
  int day = num(8, 2), hh = num(11, 2), mm = num(14, 2), ss = num(17, 2);
  int tz_h = num(21, 2), tz_m = num(23, 2), year = num(26, 4);
  if (day < 0 || hh < 0 || mm < 0 || ss < 0 || tz_h < 0 || tz_m < 0 ||
      year < 0 || (s[20] != '+' && s[20] != '-'))
    return 0;
  // days since epoch (civil calendar, Howard Hinnant's algorithm)
  int y = year - (mon < 2 ? 1 : 0);
  int era = (y >= 0 ? y : y - 399) / 400;
  unsigned yoe = static_cast<unsigned>(y - era * 400);
  unsigned m2 = static_cast<unsigned>(mon >= 2 ? mon - 2 : mon + 10);
  unsigned doy = (153 * m2 + 2) / 5 + static_cast<unsigned>(day) - 1;
  unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  int64_t days = static_cast<int64_t>(era) * 146097 +
                 static_cast<int64_t>(doe) - 719468;
  int64_t secs = days * 86400 + hh * 3600 + mm * 60 + ss;
  int64_t tz = (tz_h * 3600 + tz_m * 60);
  secs -= (s[20] == '+') ? tz : -tz;
  return secs * 1000;
}

struct RtFields {
  // absent numeric fields default to 0, exactly like Status.from_json
  int64_t retweet_count = 0;
  int64_t followers = 0, favourites = 0, friends = 0, created_ms = 0;
  int64_t text_units = 0;       // units written to the text buffer
  int64_t full_text_units = 0;  // units written to the full_text buffer
  bool present = false;
};

// Documented bound of the columnar wire format: a retweeted status whose
// "text"/"full_text" exceeds this many UTF-16 units makes the LINE a counted
// bad line (cur.ok = false on buffer overflow below). Real tweets cap well
// below this; the Python block fallback (_py_parse) pins the identical drop,
// and the object-ingest Status path (the semantic ground truth) has no such
// bound — a flagged, tested divergence on adversarial input only
// (tests/test_block_ingest.py::test_oversized_text_drops_line_both_paths).
constexpr int64_t kMaxTextUnits = 4096;

// Parse the retweeted_status object, extracting our fields. ``text_buf``
// and ``full_buf`` each hold kMaxTextUnits; the caller picks text-or-
// full_text afterwards (Status.from_json semantics: "text" wins unless
// empty — extended-tweet archives store the body in "full_text").
bool parse_rt_object(Cursor& cur, RtFields* rt, uint16_t* text_buf,
                     uint16_t* full_buf) {
  if (!cur.eat('{')) return false;
  rt->present = true;
  cur.skip_ws();
  if (cur.peek() == '}') { ++cur.p; return true; }
  uint16_t key[32];
  while (true) {
    int64_t klen = 0;
    {
      Cursor probe = cur;
      if (!scan_string(probe, key, 32, &klen)) {
        // long/unsupported key: skip it generically
        if (!scan_string(cur, nullptr, 0, nullptr)) return false;
        klen = -1;
      } else {
        cur = probe;
      }
    }
    if (!cur.eat(':')) return false;
    auto is_key = [&](const char* name) {
      int64_t len = static_cast<int64_t>(std::strlen(name));
      if (klen != len) return false;
      for (int64_t i = 0; i < len; ++i)
        if (key[i] != static_cast<uint16_t>(name[i])) return false;
      return true;
    };
    if (klen > 0 && is_key("text")) {
      cur.skip_ws();
      if (cur.peek() == '"') {
        if (!scan_string(cur, text_buf, kMaxTextUnits, &rt->text_units))
          return false;
      } else if (!skip_value(cur)) {
        return false;
      }
    } else if (klen > 0 && is_key("full_text")) {
      cur.skip_ws();
      if (cur.peek() == '"') {
        if (!scan_string(cur, full_buf, kMaxTextUnits, &rt->full_text_units))
          return false;
      } else if (!skip_value(cur)) {
        return false;
      }
    } else if (klen > 0 && is_key("retweet_count")) {
      if (!parse_int(cur, &rt->retweet_count)) {
        if (!skip_value(cur)) return false;
      }
    } else if (klen > 0 && is_key("timestamp_ms")) {
      int64_t v;
      if (parse_int(cur, &v)) rt->created_ms = v;
      else if (!skip_value(cur)) return false;
    } else if (klen > 0 && is_key("created_at")) {
      cur.skip_ws();
      if (cur.peek() == '"') {
        uint16_t date[40];
        int64_t dn = 0;
        if (!scan_string(cur, date, 40, &dn)) return false;
        if (rt->created_ms == 0) rt->created_ms = parse_created_at(date, dn);
      } else if (!skip_value(cur)) {
        return false;
      }
    } else if (klen > 0 && is_key("user")) {
      cur.skip_ws();
      if (cur.peek() != '{') {
        if (!skip_value(cur)) return false;
      } else {
        ++cur.p;
        cur.skip_ws();
        if (cur.peek() == '}') { ++cur.p; }
        else while (true) {
          int64_t uklen = 0;
          uint16_t ukey[32];
          Cursor probe = cur;
          if (!scan_string(probe, ukey, 32, &uklen)) {
            if (!scan_string(cur, nullptr, 0, nullptr)) return false;
            uklen = -1;
          } else {
            cur = probe;
          }
          if (!cur.eat(':')) return false;
          auto is_ukey = [&](const char* name) {
            int64_t len = static_cast<int64_t>(std::strlen(name));
            if (uklen != len) return false;
            for (int64_t i = 0; i < len; ++i)
              if (ukey[i] != static_cast<uint16_t>(name[i])) return false;
            return true;
          };
          int64_t* dst = nullptr;
          if (uklen > 0 && is_ukey("followers_count")) dst = &rt->followers;
          else if (uklen > 0 && is_ukey("favourites_count")) dst = &rt->favourites;
          else if (uklen > 0 && is_ukey("friends_count")) dst = &rt->friends;
          if (dst != nullptr) {
            if (!parse_int(cur, dst)) {
              if (!skip_value(cur)) return false;
            }
          } else if (!skip_value(cur)) {
            return false;
          }
          cur.skip_ws();
          if (cur.peek() == ',') { ++cur.p; continue; }
          if (cur.peek() == '}') { ++cur.p; break; }
          return false;
        }
      }
    } else if (!skip_value(cur)) {
      return false;
    }
    cur.skip_ws();
    if (cur.peek() == ',') { ++cur.p; cur.skip_ws(); continue; }
    if (cur.peek() == '}') { ++cur.p; return true; }
    return false;
  }
}

}  // namespace

extern "C" {

// Parse a block of newline-delimited tweet JSON, keeping only rows that
// pass the reference filter (isRetweet && begin <= rt.retweet_count <= end,
// MllibHelper.scala:89-95). Outputs, per kept row i:
//   out_numeric[i*5 .. i*5+4] = {retweet_count (label), followers,
//                                favourites, friends, created_ms}
//   out_units[out_offsets[i] .. out_offsets[i+1]) = the original tweet's
//     text as UTF-16 code units (escapes resolved; NOT lowercased — callers
//     use the pad-time ASCII fold + Python lower for non-ASCII rows)
//   out_ascii[i] = 1 when every unit < 128 (row skips Python lower())
//
// buf/len: UTF-8 bytes; rows split on '\n'. cap_rows/cap_units bound the
// outputs; parsing stops early (cleanly) when either would overflow, and
// *consumed reports how many input bytes were processed so the caller can
// continue from there. Malformed lines are skipped and counted in
// *bad_lines. Returns the number of kept rows.
int64_t parse_tweet_block(const char* buf, int64_t len,
                          int64_t begin, int64_t end,
                          int64_t cap_rows, int64_t cap_units,
                          int64_t* out_numeric, uint16_t* out_units,
                          int64_t* out_offsets, uint8_t* out_ascii,
                          int64_t* consumed, int64_t* bad_lines) {
  int64_t rows = 0, unit_pos = 0, bad = 0;
  const char* p = buf;
  const char* block_end = buf + len;
  out_offsets[0] = 0;
  uint16_t text[kMaxTextUnits];
  uint16_t full_text[kMaxTextUnits];
  while (p < block_end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', block_end - p));
    if (nl == nullptr) break;  // incomplete trailing line: leave for carry
    const char* line_end = nl;
    if (rows >= cap_rows || unit_pos + kMaxTextUnits > cap_units) break;
    Cursor cur{p, line_end};
    cur.skip_ws();
    if (!cur.at_end()) {
      RtFields rt;
      bool parsed = false;
      if (cur.eat('{')) {
        cur.skip_ws();
        parsed = true;
        if (cur.peek() == '}') { ++cur.p; }
        else while (true) {
          uint16_t key[32];
          int64_t klen = 0;
          Cursor probe = cur;
          if (!scan_string(probe, key, 32, &klen)) {
            if (!scan_string(cur, nullptr, 0, nullptr)) { parsed = false; break; }
            klen = -1;
          } else {
            cur = probe;
          }
          if (!cur.eat(':')) { parsed = false; break; }
          bool is_rt_key = false;
          if (klen == 16) {
            static const char* name = "retweeted_status";
            is_rt_key = true;
            for (int i = 0; i < 16; ++i)
              if (key[i] != static_cast<uint16_t>(name[i])) {
                is_rt_key = false;
                break;
              }
          }
          if (is_rt_key) {
            cur.skip_ws();
            if (cur.peek() == '{') {
              if (!parse_rt_object(cur, &rt, text, full_text)) {
                parsed = false;
                break;
              }
            } else if (!skip_value(cur)) {  // null and friends
              parsed = false;
              break;
            }
          } else if (!skip_value(cur)) {
            parsed = false;
            break;
          }
          cur.skip_ws();
          if (cur.peek() == ',') { ++cur.p; cur.skip_ws(); continue; }
          if (cur.peek() == '}') { ++cur.p; break; }
          parsed = false;
          break;
        }
      }
      if (!parsed || !cur.ok) {
        ++bad;
      } else if (rt.present && rt.retweet_count >= begin &&
                 rt.retweet_count <= end) {
        int64_t* num = out_numeric + rows * 5;
        num[0] = rt.retweet_count;
        num[1] = rt.followers;
        num[2] = rt.favourites;
        num[3] = rt.friends;
        num[4] = rt.created_ms;
        // "text" wins unless empty, else "full_text" (Status.from_json)
        const uint16_t* body = rt.text_units > 0 ? text : full_text;
        const int64_t body_units =
            rt.text_units > 0 ? rt.text_units : rt.full_text_units;
        bool ascii = true;
        for (int64_t i = 0; i < body_units; ++i) {
          out_units[unit_pos + i] = body[i];
          if (body[i] >= 128) ascii = false;
        }
        out_ascii[rows] = ascii ? 1 : 0;
        unit_pos += body_units;
        ++rows;
        out_offsets[rows] = unit_pos;
      }
    }
    p = nl + 1;
  }
  *consumed = p - buf;
  *bad_lines = bad;
  return rows;
}

}  // extern "C"

// ===== zero-copy wire emitter ==============================================
//
// parse_tweet_block_wire: the same tweet semantics as parse_tweet_block
// (same kept rows, units, numeric columns, ascii flags — differential-tested
// line for line), emitted straight in the RAGGED WIRE's representation:
//
//  - units land in the caller's uint8 buffer while every kept row is ASCII
//    (the narrow wire the featurizer would otherwise downcast to in a
//    separate pass) and widen ONCE into the uint16 buffer when the first
//    non-ASCII row commits — the committed prefix is converted in place,
//    never re-parsed;
//  - scanning classifies 32-byte chunks ONCE into special-byte masks
//    (quote/backslash/non-ASCII; AVX2 movemask, SWAR fallback) cached in a
//    monotonic stream cursor, so the per-token cost is a shift + tzcnt
//    instead of re-scanning bytes — short tokens (keys, ": " gaps) are
//    where the old per-call scanner burned its cycles;
//  - keys classify as raw bytes (length switch + one memcmp) in the
//    overwhelmingly common unescaped-ASCII case; escaped keys still decode
//    through scan_string, so "text" keeps matching "text";
//  - a rolling memmem prescreen skips lines that contain neither the
//    literal "retweeted_status" key nor any backslash (which could spell
//    the key via \u escapes): such a line can never produce a row, so it
//    skips at memchr speed. A prescreen-skipped line counts as a bad line
//    only when it does not even start with '{' — torn/garbled buffers stay
//    visible to the skip-and-count contract, while well-formed non-retweet
//    objects skip silently. (Full-parsed lines keep parse_tweet_block's
//    exact bad-line rules; whole-line JSON+UTF-8 validation is exactly what
//    the prescreen saves, so bad-line COUNTS — never kept rows — may
//    undercount the Python fallback's on keyless malformed lines.)

namespace {

// Monotonic special-byte stream over the block: aligned chunks (64 bytes
// with AVX-512BW, else 32) classify once into a bitmask of bytes that are
// '"', '\\' or >= 0x80; the cursor caches the current chunk's mask, so
// repeated next() calls inside one chunk cost a shift + tzcnt. Aligned
// loads never cross a page boundary, so reading the partial chunks at the
// block's edges is safe; bits outside [block start, hard_end) are masked
// off.
#if defined(__AVX512BW__)
constexpr int kStreamChunk = 64;
#else
constexpr int kStreamChunk = 32;
#endif

struct SpecialStream {
  const char* cur_base = nullptr;
  uint64_t cur_mask = 0;
  const char* hard_end = nullptr;

  inline uint64_t compute(const char* base) const {
    uint64_t m;
#if defined(__AVX512BW__)
    __m512i v = _mm512_load_si512(reinterpret_cast<const void*>(base));
    m = _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8('"')) |
        _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8('\\')) |
        _mm512_movepi8_mask(v);
#elif defined(__AVX2__)
    __m256i v = _mm256_load_si256(reinterpret_cast<const __m256i*>(base));
    m = static_cast<uint32_t>(_mm256_movemask_epi8(_mm256_or_si256(
            _mm256_cmpeq_epi8(v, _mm256_set1_epi8('"')),
            _mm256_cmpeq_epi8(v, _mm256_set1_epi8('\\'))))) |
        static_cast<uint32_t>(_mm256_movemask_epi8(v));
#else
    m = 0;
    for (int i = 0; i < 32; i += 8) {
      uint64_t v;
      std::memcpy(&v, base + i, 8);
      uint64_t hi = v & 0x8080808080808080ULL;
      uint64_t xq = v ^ 0x2222222222222222ULL;
      uint64_t xb = v ^ 0x5C5C5C5C5C5C5C5CULL;
      uint64_t sq = (xq - 0x0101010101010101ULL) & ~xq;
      uint64_t sb = (xb - 0x0101010101010101ULL) & ~xb;
      uint64_t special = (hi | sq | sb) & 0x8080808080808080ULL;
      // pack the per-byte high bits into 8 mask bits (movemask emulation)
      m |= ((special * 0x0002040810204081ULL) >> 56) << i;
    }
#endif
    if (base + kStreamChunk > hard_end) {
      int64_t valid = hard_end - base;
      m &= valid >= 64 ? ~0ull : ((1ull << valid) - 1);
    }
    return m;
  }

  // first special byte in [p, end); end when none.
  inline const char* next(const char* p, const char* end) {
    const char* base = reinterpret_cast<const char*>(
        reinterpret_cast<uintptr_t>(p) &
        ~static_cast<uintptr_t>(kStreamChunk - 1));
    uint64_t mask = base == cur_base ? cur_mask : compute(base);
    cur_base = base;
    cur_mask = mask;
    uint64_t live = mask & (~0ull << (p - base));
    while (live == 0) {
      base += kStreamChunk;
      if (base >= end) return end;
      mask = compute(base);
      cur_base = base;
      cur_mask = mask;
      live = mask;
    }
    const char* r = base + __builtin_ctzll(live);
    return r < end ? r : end;
  }
};

// validate/decode one UTF-8 sequence at p (first byte >= 0x80): writes the
// code point and returns the byte length, 0 on malformed. Identical accept
// set to scan_string: overlong and > U+10FFFF malformed, encoded SURROGATE
// code points pass (json.loads' errors='surrogatepass' view of the bytes).
inline int utf8_decode(const char* p, const char* end, uint32_t* cp_out) {
  unsigned char c = static_cast<unsigned char>(*p);
  uint32_t cp;
  int extra;
  if ((c >> 5) == 0x6) { cp = c & 0x1F; extra = 1; }
  else if ((c >> 4) == 0xE) { cp = c & 0x0F; extra = 2; }
  else if ((c >> 3) == 0x1E) { cp = c & 0x07; extra = 3; }
  else return 0;
  if (end - p < extra + 1) return 0;
  for (int i = 1; i <= extra; ++i) {
    unsigned char cc = static_cast<unsigned char>(p[i]);
    if ((cc >> 6) != 0x2) return 0;
    cp = (cp << 6) | (cc & 0x3F);
  }
  if (extra == 1 && cp < 0x80) return 0;
  if (extra == 2 && cp < 0x800) return 0;
  if (extra == 3 && (cp < 0x10000 || cp > 0x10FFFF)) return 0;
  *cp_out = cp;
  return extra + 1;
}

// ASCII run widen-copy: input bytes -> UTF-16 units.
inline void widen_copy(uint16_t* dst, const char* src, int64_t n) {
  int64_t i = 0;
#if defined(__AVX2__)
  for (; i + 16 <= n; i += 16) {
    __m128i b = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + i));
    _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + i),
                        _mm256_cvtepu8_epi16(b));
  }
#endif
  for (; i < n; ++i)
    dst[i] = static_cast<uint16_t>(static_cast<unsigned char>(src[i]));
}

// ASCII unit narrow-copy (every unit < 128 by the caller's row_ascii gate).
inline void narrow_copy(uint8_t* dst, const uint16_t* src, int64_t n) {
  int64_t i = 0;
#if defined(__AVX2__)
  for (; i + 16 <= n; i += 16) {
    __m128i lo = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + i));
    __m128i hi =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + i + 8));
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                     _mm_packus_epi16(lo, hi));
  }
#endif
  for (; i < n; ++i) dst[i] = static_cast<uint8_t>(src[i]);
}

inline const char* wire_ws(const char* p, const char* end) {
  while (p < end &&
         (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
    ++p;
  return p;
}

// number / true / false / null (and, as in skip_value, any garbage token):
// scan to a structural delimiter, non-empty.
inline const char* skip_token_fast(const char* p, const char* end) {
  const char* start = p;
  while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
         *p != '\t' && *p != '\n' && *p != '\r')
    ++p;
  return p > start ? p : nullptr;
}

// skip a string (p at the opening quote) validating escapes and UTF-8 —
// the accept set of scan_string(out=nullptr). Returns past the closing
// quote, nullptr on malformed/unterminated.
const char* skip_string_fast(SpecialStream& ss, const char* p,
                             const char* end) {
  ++p;
  for (;;) {
    p = ss.next(p, end);
    if (p >= end) return nullptr;
    unsigned char c = static_cast<unsigned char>(*p);
    if (c == '"') return p + 1;
    if (c == '\\') {
      if (end - p < 2) return nullptr;
      char e = p[1];
      if (e == 'u') {
        if (end - p < 6) return nullptr;
        if (hex_val(p[2]) < 0 || hex_val(p[3]) < 0 || hex_val(p[4]) < 0 ||
            hex_val(p[5]) < 0)
          return nullptr;
        p += 6;
      } else if (e == '"' || e == '\\' || e == '/' || e == 'b' ||
                 e == 'f' || e == 'n' || e == 'r' || e == 't') {
        p += 2;
      } else {
        return nullptr;
      }
      continue;
    }
    uint32_t cp;
    int adv = utf8_decode(p, end, &cp);
    if (adv == 0) return nullptr;
    p += adv;
  }
}

// grammar-following iterative value skip — the accept set of skip_value
// (including its kMaxSkipDepth container cap and its tolerance for garbage
// primitive tokens), with the per-byte recursion replaced by the masked
// string scanner and an explicit container stack.
const char* skip_value_fast(SpecialStream& ss, const char* p,
                            const char* end) {
  p = wire_ws(p, end);
  if (p >= end) return nullptr;
  char c = *p;
  if (c == '"') return skip_string_fast(ss, p, end);
  if (c != '{' && c != '[') return skip_token_fast(p, end);
  bool isobj[kMaxSkipDepth];
  int depth = 0;
  for (;;) {
    // p at '{' or '[' — push
    if (depth >= kMaxSkipDepth) return nullptr;
    isobj[depth++] = (*p == '{');
    ++p;
    p = wire_ws(p, end);
    if (p >= end) return nullptr;
    if ((*p == '}' && isobj[depth - 1]) ||
        (*p == ']' && !isobj[depth - 1]))
      goto close_one;
  element:
    if (isobj[depth - 1]) {
      if (*p != '"') return nullptr;
      p = skip_string_fast(ss, p, end);
      if (p == nullptr) return nullptr;
      p = wire_ws(p, end);
      if (p >= end || *p != ':') return nullptr;
      ++p;
      p = wire_ws(p, end);
      if (p >= end) return nullptr;
    }
    if (*p == '{' || *p == '[') continue;  // push the nested container
    if (*p == '"') {
      p = skip_string_fast(ss, p, end);
    } else {
      p = skip_token_fast(p, end);
    }
    if (p == nullptr) return nullptr;
  after_value:
    p = wire_ws(p, end);
    if (p >= end) return nullptr;
    if (*p == ',') {
      ++p;
      p = wire_ws(p, end);
      if (p >= end) return nullptr;
      goto element;
    }
    if ((*p == '}' && isobj[depth - 1]) ||
        (*p == ']' && !isobj[depth - 1])) {
    close_one:
      ++p;
      --depth;
      if (depth == 0) return p;
      goto after_value;
    }
    return nullptr;
  }
}

// parse_int's accept set without the probe-Cursor copies: optional quotes
// (Twitter's "timestamp_ms"), optional '-', >= 1 digit, truncated fraction;
// nullptr (out untouched) on non-numeric so the caller can skip generically.
inline const char* parse_int_fast(const char* p, const char* end,
                                  int64_t* out) {
  p = wire_ws(p, end);
  bool quoted = p < end && *p == '"';
  if (quoted) ++p;
  bool neg = false;
  if (p < end && *p == '-') { neg = true; ++p; }
  if (p >= end || *p < '0' || *p > '9') return nullptr;
  int64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
  if (p < end && *p == '.') {  // truncate fraction
    ++p;
    while (p < end && *p >= '0' && *p <= '9') ++p;
  }
  if (quoted) {
    p = wire_ws(p, end);
    if (p >= end || *p != '"') return nullptr;
    ++p;
  }
  *out = neg ? -v : v;
  return p;
}

// decode a string VALUE into UTF-16 units (p at the opening quote) with
// scan_string's exact emit rules (escapes resolved, \uXXXX kept as-is so
// surrogate halves pass through, UTF-8 decoded to units/pairs), tracking
// the max unit for the narrow-wire/ascii decisions. nullptr on malformed
// OR on overflowing cap — the line becomes a counted bad line, exactly the
// kMaxTextUnits wire bound of parse_tweet_block.
const char* scan_units_fast(SpecialStream& ss, const char* p,
                            const char* end, uint16_t* out, int64_t cap,
                            int64_t* n_out, uint32_t* max_unit) {
  ++p;
  int64_t n = 0;
  uint32_t mx = 0;
  for (;;) {
    const char* q = ss.next(p, end);
    int64_t run = q - p;
    if (run > 0) {
      if (n + run > cap) return nullptr;
      widen_copy(out + n, p, run);
      n += run;
      p = q;
    }
    if (p >= end) return nullptr;  // unterminated
    unsigned char c = static_cast<unsigned char>(*p);
    if (c == '"') {
      *n_out = n;
      *max_unit = mx;
      return p + 1;
    }
    if (c == '\\') {
      if (end - p < 2) return nullptr;
      char e = p[1];
      uint32_t cp;
      switch (e) {
        case '"': cp = '"'; p += 2; break;
        case '\\': cp = '\\'; p += 2; break;
        case '/': cp = '/'; p += 2; break;
        case 'b': cp = '\b'; p += 2; break;
        case 'f': cp = '\f'; p += 2; break;
        case 'n': cp = '\n'; p += 2; break;
        case 'r': cp = '\r'; p += 2; break;
        case 't': cp = '\t'; p += 2; break;
        case 'u': {
          if (end - p < 6) return nullptr;
          int v = 0;
          for (int i = 2; i < 6; ++i) {
            int h = hex_val(p[i]);
            if (h < 0) return nullptr;
            v = (v << 4) | h;
          }
          p += 6;
          cp = static_cast<uint32_t>(v);  // the unit as-is (JVM view)
          break;
        }
        default:
          return nullptr;
      }
      if (n + 1 > cap) return nullptr;
      out[n++] = static_cast<uint16_t>(cp);
      if (cp > mx) mx = cp;
      continue;
    }
    uint32_t cp;
    int adv = utf8_decode(p, end, &cp);
    if (adv == 0) return nullptr;
    p += adv;
    if (cp >= 0x10000) {
      if (n + 2 > cap) return nullptr;
      cp -= 0x10000;
      out[n++] = static_cast<uint16_t>(0xD800 + (cp >> 10));
      out[n++] = static_cast<uint16_t>(0xDC00 + (cp & 0x3FF));
      if (0xDC00u > mx) mx = 0xDC00u;
    } else {
      if (n + 1 > cap) return nullptr;
      out[n++] = static_cast<uint16_t>(cp);
      if (cp > mx) mx = cp;
    }
  }
}

// key ids for the fused scan+classify (context decides which ids it acts
// on; an id the context ignores behaves exactly like K_UNKNOWN)
enum KeyId : int {
  K_UNKNOWN = 0,
  K_RT,
  K_TEXT,
  K_FULL_TEXT,
  K_RETWEET_COUNT,
  K_TIMESTAMP_MS,
  K_CREATED_AT,
  K_USER,
  K_FOLLOWERS,
  K_FAVOURITES,
  K_FRIENDS,
};

inline int classify_key(const char* k, int64_t len) {
  switch (len) {
    case 4:
      if (std::memcmp(k, "text", 4) == 0) return K_TEXT;
      if (std::memcmp(k, "user", 4) == 0) return K_USER;
      return K_UNKNOWN;
    case 9:
      return std::memcmp(k, "full_text", 9) == 0 ? K_FULL_TEXT : K_UNKNOWN;
    case 10:
      return std::memcmp(k, "created_at", 10) == 0 ? K_CREATED_AT
                                                   : K_UNKNOWN;
    case 12:
      return std::memcmp(k, "timestamp_ms", 12) == 0 ? K_TIMESTAMP_MS
                                                     : K_UNKNOWN;
    case 13:
      if (std::memcmp(k, "retweet_count", 13) == 0) return K_RETWEET_COUNT;
      if (std::memcmp(k, "friends_count", 13) == 0) return K_FRIENDS;
      return K_UNKNOWN;
    case 15:
      return std::memcmp(k, "followers_count", 15) == 0 ? K_FOLLOWERS
                                                        : K_UNKNOWN;
    case 16:
      if (std::memcmp(k, "retweeted_status", 16) == 0) return K_RT;
      if (std::memcmp(k, "favourites_count", 16) == 0) return K_FAVOURITES;
      return K_UNKNOWN;
    default:
      return K_UNKNOWN;
  }
}

// scan a KEY string at p (opening quote) and classify it. Fast path: raw
// unescaped-ASCII bytes classify in place. Keys containing escapes or
// non-ASCII decode through scan_string (32-unit cap, as in
// parse_tweet_block — "text" still matches "text"); longer or
// unsupported keys skip generically and come back K_UNKNOWN. nullptr on
// malformed.
const char* scan_key_id(SpecialStream& ss, const char* p, const char* end,
                        int* id) {
  const char* q = ss.next(p + 1, end);
  if (q >= end) return nullptr;
  if (*q == '"') {
    *id = classify_key(p + 1, q - (p + 1));
    return q + 1;
  }
  Cursor probe{p, end};
  uint16_t k16[32];
  int64_t n = 0;
  if (scan_string(probe, k16, 32, &n) && probe.ok) {
    char kb[32];
    bool ascii = true;
    for (int64_t i = 0; i < n; ++i) {
      if (k16[i] > 127) { ascii = false; break; }
      kb[i] = static_cast<char>(k16[i]);
    }
    *id = ascii ? classify_key(kb, n) : K_UNKNOWN;
    return probe.p;
  }
  Cursor c{p, end};
  if (!scan_string(c, nullptr, 0, nullptr)) return nullptr;
  *id = K_UNKNOWN;
  return c.p;
}

struct RtWire {
  int64_t retweet_count = 0;
  int64_t followers = 0, favourites = 0, friends = 0, created_ms = 0;
  int64_t text_units = 0, full_units = 0;
  uint32_t text_max = 0, full_max = 0;
  bool present = false;
};

// parse_rt_object's semantics on the fast primitives: field staging, the
// duplicate-key/occurrence rules, and the text/full_text wire bound all
// mirror the reference implementation above.
const char* parse_rt_wire(SpecialStream& ss, const char* p, const char* end,
                          RtWire* rt, uint16_t* text, uint16_t* full) {
  rt->present = true;
  ++p;  // '{'
  p = wire_ws(p, end);
  if (p < end && *p == '}') return p + 1;
  for (;;) {
    if (p >= end || *p != '"') return nullptr;
    int key;
    p = scan_key_id(ss, p, end, &key);
    if (p == nullptr) return nullptr;
    p = wire_ws(p, end);
    if (p >= end || *p != ':') return nullptr;
    ++p;
    switch (key) {
      case K_TEXT:
      case K_FULL_TEXT: {
        p = wire_ws(p, end);
        if (p < end && *p == '"') {
          p = key == K_TEXT
                  ? scan_units_fast(ss, p, end, text, kMaxTextUnits,
                                    &rt->text_units, &rt->text_max)
                  : scan_units_fast(ss, p, end, full, kMaxTextUnits,
                                    &rt->full_units, &rt->full_max);
        } else {
          p = skip_value_fast(ss, p, end);
        }
        break;
      }
      case K_RETWEET_COUNT: {
        const char* r = parse_int_fast(p, end, &rt->retweet_count);
        p = r != nullptr ? r : skip_value_fast(ss, p, end);
        break;
      }
      case K_TIMESTAMP_MS: {
        int64_t v;
        const char* r = parse_int_fast(p, end, &v);
        if (r != nullptr) {
          rt->created_ms = v;
          p = r;
        } else {
          p = skip_value_fast(ss, p, end);
        }
        break;
      }
      case K_CREATED_AT: {
        p = wire_ws(p, end);
        if (p < end && *p == '"') {
          uint16_t date[40];
          int64_t dn = 0;
          uint32_t dmax = 0;
          p = scan_units_fast(ss, p, end, date, 40, &dn, &dmax);
          if (p != nullptr && rt->created_ms == 0)
            rt->created_ms = parse_created_at(date, dn);
        } else {
          p = skip_value_fast(ss, p, end);
        }
        break;
      }
      case K_USER: {
        p = wire_ws(p, end);
        if (p >= end || *p != '{') {
          p = skip_value_fast(ss, p, end);
          break;
        }
        ++p;
        p = wire_ws(p, end);
        if (p < end && *p == '}') {
          ++p;
          break;
        }
        for (;;) {
          if (p >= end || *p != '"') return nullptr;
          int ukey;
          p = scan_key_id(ss, p, end, &ukey);
          if (p == nullptr) return nullptr;
          p = wire_ws(p, end);
          if (p >= end || *p != ':') return nullptr;
          ++p;
          int64_t* dst = nullptr;
          if (ukey == K_FOLLOWERS) dst = &rt->followers;
          else if (ukey == K_FAVOURITES) dst = &rt->favourites;
          else if (ukey == K_FRIENDS) dst = &rt->friends;
          if (dst != nullptr) {
            const char* r = parse_int_fast(p, end, dst);
            p = r != nullptr ? r : skip_value_fast(ss, p, end);
          } else {
            p = skip_value_fast(ss, p, end);
          }
          if (p == nullptr) return nullptr;
          p = wire_ws(p, end);
          if (p < end && *p == ',') {
            ++p;
            p = wire_ws(p, end);
            continue;
          }
          if (p < end && *p == '}') {
            ++p;
            break;
          }
          return nullptr;
        }
        break;
      }
      default:
        p = skip_value_fast(ss, p, end);
        break;
    }
    if (p == nullptr) return nullptr;
    p = wire_ws(p, end);
    if (p < end && *p == ',') {
      ++p;
      p = wire_ws(p, end);
      continue;
    }
    if (p < end && *p == '}') return p + 1;
    return nullptr;
  }
}

}  // namespace

extern "C" {

// Parse newline-delimited tweet JSON straight into the ragged-wire unit
// representation (see the banner comment above). Outputs per kept row i:
//   out_numeric[i*5 .. i*5+4], out_offsets[i]/[i+1], out_ascii[i] — as in
//   parse_tweet_block;
//   units: out_units_u8[...] while *narrow_out (every kept row ASCII so
//   far), else out_units_u16[...] — on the first non-ASCII commit the
//   already-written u8 prefix widens into out_units_u16 and the parse
//   continues wide. out_units_u16 may be NULL: a parse that then needs to
//   widen stops cleanly BEFORE the offending line (*needs_wide = 1,
//   *consumed excludes it) so the caller can retry the remainder with a
//   wide buffer.
// cap_rows/cap_units/consumed/bad_lines behave as in parse_tweet_block.
int64_t parse_tweet_block_wire(const char* buf, int64_t len,
                               int64_t begin, int64_t end_count,
                               int64_t cap_rows, int64_t cap_units,
                               int64_t* out_numeric, uint8_t* out_units_u8,
                               uint16_t* out_units_u16, int64_t* out_offsets,
                               uint8_t* out_ascii, int64_t* consumed,
                               int64_t* bad_lines, int64_t* narrow_out,
                               int64_t* needs_wide_out) {
  int64_t rows = 0, unit_pos = 0, bad = 0;
  bool narrow = true;
  *needs_wide_out = 0;
  const char* p = buf;
  const char* hard_end = buf + len;
  out_offsets[0] = 0;
  uint16_t text[kMaxTextUnits];
  uint16_t full[kMaxTextUnits];
  SpecialStream ss;
  ss.hard_end = hard_end;
  static const char kNeedle[] = "\"retweeted_status\"";
  const size_t kNeedleLen = 18;
  const char* next_key = nullptr;
  bool key_stale = true;
  // adaptive prescreen: while the previous full-parsed line carried the rt
  // key (retweet-dense corpora — the replay/bench regime), the memmem is
  // pure overhead, so it stands down until a keyless line reappears. Purely
  // an optimization: which lines full-parse is a deterministic function of
  // the input bytes either way.
  bool assume_key = false;
  while (p < hard_end) {
    const char* nl =
        static_cast<const char*>(std::memchr(p, '\n', hard_end - p));
    if (nl == nullptr) break;  // incomplete trailing line: leave for carry
    if (rows >= cap_rows || unit_pos + kMaxTextUnits > cap_units) break;
    const char* line_end = nl;
    // ---- prescreen ------------------------------------------------------
    if (!assume_key) {
      if (key_stale || (next_key != nullptr && next_key < p)) {
        next_key = static_cast<const char*>(
            memmem(p, hard_end - p, kNeedle, kNeedleLen));
        key_stale = false;
      }
      bool has_key = next_key != nullptr && next_key < line_end;
      if (!has_key && std::memchr(p, '\\', line_end - p) == nullptr) {
        const char* q = wire_ws(p, line_end);
        if (q != line_end && *q != '{') ++bad;  // garbage stays visible
        p = nl + 1;
        continue;
      }
    } else {
      key_stale = true;  // the rolling memmem restarts when it re-engages
    }
    // ---- full parse (parse_tweet_block's line semantics) ----------------
    const char* q = wire_ws(p, line_end);
    if (q == line_end) {  // blank line
      p = nl + 1;
      continue;
    }
    bool parsed = false;
    bool saw_rt = false;
    RtWire rt;
    if (*q == '{') {
      parsed = true;
      ++q;
      q = wire_ws(q, line_end);
      if (q < line_end && *q == '}') {
        ++q;
      } else {
        for (;;) {
          if (q >= line_end || *q != '"') { parsed = false; break; }
          int key;
          q = scan_key_id(ss, q, line_end, &key);
          if (q == nullptr) { parsed = false; break; }
          q = wire_ws(q, line_end);
          if (q >= line_end || *q != ':') { parsed = false; break; }
          ++q;
          if (key == K_RT) {
            saw_rt = true;
            q = wire_ws(q, line_end);
            if (q < line_end && *q == '{') {
              q = parse_rt_wire(ss, q, line_end, &rt, text, full);
            } else {  // null and friends
              q = skip_value_fast(ss, q, line_end);
            }
          } else {
            q = skip_value_fast(ss, q, line_end);
          }
          if (q == nullptr) { parsed = false; break; }
          q = wire_ws(q, line_end);
          if (q < line_end && *q == ',') {
            ++q;
            q = wire_ws(q, line_end);
            continue;
          }
          if (q < line_end && *q == '}') { ++q; break; }
          parsed = false;
          break;
        }
      }
    }
    assume_key = saw_rt;
    if (!parsed) {
      ++bad;
    } else if (rt.present && rt.retweet_count >= begin &&
               rt.retweet_count <= end_count) {
      // "text" wins unless empty, else "full_text" (Status.from_json)
      const uint16_t* body = rt.text_units > 0 ? text : full;
      const int64_t body_units =
          rt.text_units > 0 ? rt.text_units : rt.full_units;
      const uint32_t body_max =
          rt.text_units > 0 ? rt.text_max : rt.full_max;
      bool row_ascii = body_max < 128;
      if (!row_ascii && narrow) {
        if (out_units_u16 == nullptr) {
          // no wide buffer: stop cleanly before this line (caller retries)
          *needs_wide_out = 1;
          break;
        }
        widen_copy(out_units_u16,
                   reinterpret_cast<const char*>(out_units_u8), unit_pos);
        narrow = false;
      }
      if (narrow) {
        narrow_copy(out_units_u8 + unit_pos, body, body_units);
      } else {
        std::memcpy(out_units_u16 + unit_pos, body,
                    static_cast<size_t>(body_units) * 2);
      }
      int64_t* num = out_numeric + rows * 5;
      num[0] = rt.retweet_count;
      num[1] = rt.followers;
      num[2] = rt.favourites;
      num[3] = rt.friends;
      num[4] = rt.created_ms;
      out_ascii[rows] = row_ascii ? 1 : 0;
      unit_pos += body_units;
      ++rows;
      out_offsets[rows] = unit_pos;
    }
    p = nl + 1;
  }
  *consumed = p - buf;
  *bad_lines = bad;
  *narrow_out = narrow ? 1 : 0;
  return rows;
}

}  // extern "C"
