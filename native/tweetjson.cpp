// Native tweet-JSON block ingest — the framework's data-loader hot loop.
//
// The reference delegates ingestion to Twitter4j/Spark receivers (external
// JVM dependencies, SURVEY.md §2.4); our replay/stream sources parse
// newline-delimited tweet JSON. CPython json.loads + object assembly tops
// out near ~90k tweets/s on one core — an order of magnitude below the
// compute pipeline — so this parser extracts exactly the fields the
// featurizer reads (MllibHelper.scala:42-95: the retweeted status' text,
// retweet_count, user counts, timestamp) straight into columnar buffers,
// applying the isRetweet + retweet-count-interval filter in-line
// (MllibHelper.scala:89-95). Text is emitted as UTF-16-LE code units with
// JSON escapes resolved (\uXXXX surrogate halves pass through exactly like
// the JVM sees them), ready for the UnitBatch wire format (the device
// hashes bigrams over these units — ops/text_hash.py).
//
// Only well-formed JSON is expected; a malformed line is skipped and
// counted (callers surface the count). Semantic ground truth remains the
// Python path (features/featurizer.py Status.from_json + filtrate +
// featurize) — differential tests assert unit-for-unit equality.
//
// Build: compiled into libfasthash.so together with fasthash.cpp.

#include <cstdint>
#include <cstring>

namespace {

struct Cursor {
  const char* p;
  const char* end;
  bool ok = true;

  bool at_end() const { return p >= end; }
  char peek() const { return at_end() ? '\0' : *p; }
  void skip_ws() {
    while (!at_end() && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool eat(char c) {
    skip_ws();
    if (at_end() || *p != c) return false;
    ++p;
    return true;
  }
};

// ---- string scanning ------------------------------------------------------

inline int hex_val(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

// Scan a JSON string (cursor at opening quote). If out != nullptr, write
// UTF-16 code units (escapes resolved, UTF-8 decoded) and return the unit
// count via *n_units (buffer has cap units; overflow sets cur.ok = false).
bool scan_string(Cursor& cur, uint16_t* out, int64_t cap, int64_t* n_units) {
  if (!cur.eat('"')) return false;
  int64_t n = 0;
  auto emit = [&](uint32_t cp) {
    if (out == nullptr) {
      n += cp >= 0x10000 ? 2 : 1;
      return;
    }
    if (cp >= 0x10000) {
      if (n + 2 > cap) { cur.ok = false; return; }
      cp -= 0x10000;
      out[n++] = static_cast<uint16_t>(0xD800 + (cp >> 10));
      out[n++] = static_cast<uint16_t>(0xDC00 + (cp & 0x3FF));
    } else {
      if (n + 1 > cap) { cur.ok = false; return; }
      out[n++] = static_cast<uint16_t>(cp);
    }
  };
  while (!cur.at_end() && cur.ok) {
    // bulk fast path: plain-ASCII runs (the overwhelming majority of tweet
    // bytes) copy/count without per-byte dispatch — SWAR scans 8 bytes per
    // iteration for the next special byte (quote/escape/UTF-8 lead); the
    // scalar loop below handles only that byte
    {
      const char* q = cur.p;
      while (cur.end - q >= 8) {
        uint64_t v;
        std::memcpy(&v, q, 8);
        uint64_t hi = v & 0x8080808080808080ULL;           // >= 0x80
        uint64_t xq = v ^ 0x2222222222222222ULL;           // '"'
        uint64_t xb = v ^ 0x5C5C5C5C5C5C5C5CULL;           // '\\'
        uint64_t sq = (xq - 0x0101010101010101ULL) & ~xq;
        uint64_t sb = (xb - 0x0101010101010101ULL) & ~xb;
        uint64_t special = (hi | sq | sb) & 0x8080808080808080ULL;
        if (special) {
          q += __builtin_ctzll(special) >> 3;
          break;
        }
        q += 8;
      }
      while (q < cur.end) {
        unsigned char cc = static_cast<unsigned char>(*q);
        if (cc == '"' || cc == '\\' || cc >= 0x80) break;
        ++q;
      }
      int64_t run = q - cur.p;
      if (run > 0) {
        if (out != nullptr) {
          if (n + run > cap) { cur.ok = false; return false; }
          for (int64_t i = 0; i < run; ++i)
            out[n + i] = static_cast<uint16_t>(
                static_cast<unsigned char>(cur.p[i]));
        }
        n += run;
        cur.p = q;
        if (cur.at_end()) break;
      }
    }
    unsigned char c = static_cast<unsigned char>(*cur.p);
    if (c == '"') {
      ++cur.p;
      if (n_units) *n_units = n;
      return true;
    }
    if (c == '\\') {
      ++cur.p;
      if (cur.at_end()) break;
      char e = *cur.p++;
      switch (e) {
        case '"': emit('"'); break;
        case '\\': emit('\\'); break;
        case '/': emit('/'); break;
        case 'b': emit('\b'); break;
        case 'f': emit('\f'); break;
        case 'n': emit('\n'); break;
        case 'r': emit('\r'); break;
        case 't': emit('\t'); break;
        case 'u': {
          if (cur.end - cur.p < 4) return false;
          int v = 0;
          for (int i = 0; i < 4; ++i) {
            int h = hex_val(cur.p[i]);
            if (h < 0) return false;
            v = (v << 4) | h;
          }
          cur.p += 4;
          // emit the unit as-is: surrogate halves stay halves, exactly the
          // JVM's view of the string (features/hashing.py utf16_units)
          if (out != nullptr) {
            if (n + 1 > cap) { cur.ok = false; break; }
            out[n++] = static_cast<uint16_t>(v);
          } else {
            n += 1;
          }
          break;
        }
        default: return false;
      }
      continue;
    }
    // UTF-8 decode (1-4 bytes) -> code point, matching what the Python
    // fallback's json.loads(bytes) accepts: overlong encodings and values
    // past U+10FFFF are malformed (CPython utf-8 is strict about those),
    // but UTF-8-encoded SURROGATE code points are kept as lone UTF-16
    // units — json decodes bytes with errors='surrogatepass', and the
    // hashing ground truth handles lone surrogates by design
    // (features/hashing.py utf16_units)
    uint32_t cp;
    int extra;
    if (c < 0x80) { cp = c; extra = 0; }
    else if ((c >> 5) == 0x6) { cp = c & 0x1F; extra = 1; }
    else if ((c >> 4) == 0xE) { cp = c & 0x0F; extra = 2; }
    else if ((c >> 3) == 0x1E) { cp = c & 0x07; extra = 3; }
    else return false;
    if (cur.end - cur.p < extra + 1) return false;
    for (int i = 1; i <= extra; ++i) {
      unsigned char cc = static_cast<unsigned char>(cur.p[i]);
      if ((cc >> 6) != 0x2) return false;
      cp = (cp << 6) | (cc & 0x3F);
    }
    if (extra == 1 && cp < 0x80) return false;          // overlong
    if (extra == 2 && cp < 0x800) return false;         // overlong
    if (extra == 3 && (cp < 0x10000 || cp > 0x10FFFF)) return false;
    cur.p += extra + 1;
    emit(cp);
  }
  return false;
}

// ---- generic value skipping ----------------------------------------------

// Depth cap: a well-formed line with ~100k nested brackets would otherwise
// recurse once per level and smash the C stack; past the cap the line is a
// counted bad line, like the Python fallback's caught RecursionError.
constexpr int kMaxSkipDepth = 256;

bool skip_value(Cursor& cur, int depth = 0);

bool skip_container(Cursor& cur, char open, char close, int depth) {
  if (depth >= kMaxSkipDepth) return false;
  if (!cur.eat(open)) return false;
  cur.skip_ws();
  if (cur.peek() == close) { ++cur.p; return true; }
  while (true) {
    if (open == '{') {
      if (!scan_string(cur, nullptr, 0, nullptr)) return false;
      if (!cur.eat(':')) return false;
    }
    if (!skip_value(cur, depth + 1)) return false;
    cur.skip_ws();
    if (cur.peek() == ',') { ++cur.p; cur.skip_ws(); continue; }
    if (cur.peek() == close) { ++cur.p; return true; }
    return false;
  }
}

bool skip_value(Cursor& cur, int depth) {
  cur.skip_ws();
  char c = cur.peek();
  if (c == '"') return scan_string(cur, nullptr, 0, nullptr);
  if (c == '{') return skip_container(cur, '{', '}', depth);
  if (c == '[') return skip_container(cur, '[', ']', depth);
  // number / true / false / null: scan to a structural delimiter
  const char* start = cur.p;
  while (!cur.at_end() && *cur.p != ',' && *cur.p != '}' && *cur.p != ']' &&
         *cur.p != ' ' && *cur.p != '\t' && *cur.p != '\n' && *cur.p != '\r')
    ++cur.p;
  return cur.p > start;
}

// Parse an integer-valued JSON number (or a string wrapping one, Twitter's
// "timestamp_ms"); fractional digits are truncated. Returns false on
// non-numeric values with the cursor UNTOUCHED (parsing happens on a probe
// copy), so the caller's skip_value fallback starts from a clean position —
// e.g. a non-numeric quoted value is then skipped as a string, matching the
// Python path's keep-the-row-with-default behavior.
bool parse_int(Cursor& cur, int64_t* out) {
  Cursor probe = cur;
  probe.skip_ws();
  bool quoted = probe.peek() == '"';
  if (quoted) ++probe.p;
  bool neg = false;
  if (probe.peek() == '-') { neg = true; ++probe.p; }
  if (probe.at_end() || *probe.p < '0' || *probe.p > '9') return false;
  int64_t v = 0;
  while (!probe.at_end() && *probe.p >= '0' && *probe.p <= '9')
    v = v * 10 + (*probe.p++ - '0');
  if (!probe.at_end() && *probe.p == '.') {  // truncate fraction
    ++probe.p;
    while (!probe.at_end() && *probe.p >= '0' && *probe.p <= '9') ++probe.p;
  }
  if (quoted && !probe.eat('"')) return false;
  *out = neg ? -v : v;
  cur = probe;
  return true;
}

// "Wed Aug 27 13:08:45 +0000 2008" -> epoch millis (0 on mismatch).
int64_t parse_created_at(const uint16_t* u, int64_t n) {
  if (n != 30) return 0;
  char s[31];
  for (int i = 0; i < 30; ++i) {
    if (u[i] > 127) return 0;
    s[i] = static_cast<char>(u[i]);
  }
  s[30] = '\0';
  static const char* months = "JanFebMarAprMayJunJulAugSepOctNovDec";
  int mon = -1;
  for (int m = 0; m < 12; ++m)
    if (std::memcmp(s + 4, months + m * 3, 3) == 0) { mon = m; break; }
  if (mon < 0) return 0;
  auto num = [&](int off, int len) {
    int v = 0;
    for (int i = 0; i < len; ++i) {
      if (s[off + i] < '0' || s[off + i] > '9') return -1;
      v = v * 10 + (s[off + i] - '0');
    }
    return v;
  };
  int day = num(8, 2), hh = num(11, 2), mm = num(14, 2), ss = num(17, 2);
  int tz_h = num(21, 2), tz_m = num(23, 2), year = num(26, 4);
  if (day < 0 || hh < 0 || mm < 0 || ss < 0 || tz_h < 0 || tz_m < 0 ||
      year < 0 || (s[20] != '+' && s[20] != '-'))
    return 0;
  // days since epoch (civil calendar, Howard Hinnant's algorithm)
  int y = year - (mon < 2 ? 1 : 0);
  int era = (y >= 0 ? y : y - 399) / 400;
  unsigned yoe = static_cast<unsigned>(y - era * 400);
  unsigned m2 = static_cast<unsigned>(mon >= 2 ? mon - 2 : mon + 10);
  unsigned doy = (153 * m2 + 2) / 5 + static_cast<unsigned>(day) - 1;
  unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  int64_t days = static_cast<int64_t>(era) * 146097 +
                 static_cast<int64_t>(doe) - 719468;
  int64_t secs = days * 86400 + hh * 3600 + mm * 60 + ss;
  int64_t tz = (tz_h * 3600 + tz_m * 60);
  secs -= (s[20] == '+') ? tz : -tz;
  return secs * 1000;
}

struct RtFields {
  // absent numeric fields default to 0, exactly like Status.from_json
  int64_t retweet_count = 0;
  int64_t followers = 0, favourites = 0, friends = 0, created_ms = 0;
  int64_t text_units = 0;       // units written to the text buffer
  int64_t full_text_units = 0;  // units written to the full_text buffer
  bool present = false;
};

// Documented bound of the columnar wire format: a retweeted status whose
// "text"/"full_text" exceeds this many UTF-16 units makes the LINE a counted
// bad line (cur.ok = false on buffer overflow below). Real tweets cap well
// below this; the Python block fallback (_py_parse) pins the identical drop,
// and the object-ingest Status path (the semantic ground truth) has no such
// bound — a flagged, tested divergence on adversarial input only
// (tests/test_block_ingest.py::test_oversized_text_drops_line_both_paths).
constexpr int64_t kMaxTextUnits = 4096;

// Parse the retweeted_status object, extracting our fields. ``text_buf``
// and ``full_buf`` each hold kMaxTextUnits; the caller picks text-or-
// full_text afterwards (Status.from_json semantics: "text" wins unless
// empty — extended-tweet archives store the body in "full_text").
bool parse_rt_object(Cursor& cur, RtFields* rt, uint16_t* text_buf,
                     uint16_t* full_buf) {
  if (!cur.eat('{')) return false;
  rt->present = true;
  cur.skip_ws();
  if (cur.peek() == '}') { ++cur.p; return true; }
  uint16_t key[32];
  while (true) {
    int64_t klen = 0;
    {
      Cursor probe = cur;
      if (!scan_string(probe, key, 32, &klen)) {
        // long/unsupported key: skip it generically
        if (!scan_string(cur, nullptr, 0, nullptr)) return false;
        klen = -1;
      } else {
        cur = probe;
      }
    }
    if (!cur.eat(':')) return false;
    auto is_key = [&](const char* name) {
      int64_t len = static_cast<int64_t>(std::strlen(name));
      if (klen != len) return false;
      for (int64_t i = 0; i < len; ++i)
        if (key[i] != static_cast<uint16_t>(name[i])) return false;
      return true;
    };
    if (klen > 0 && is_key("text")) {
      cur.skip_ws();
      if (cur.peek() == '"') {
        if (!scan_string(cur, text_buf, kMaxTextUnits, &rt->text_units))
          return false;
      } else if (!skip_value(cur)) {
        return false;
      }
    } else if (klen > 0 && is_key("full_text")) {
      cur.skip_ws();
      if (cur.peek() == '"') {
        if (!scan_string(cur, full_buf, kMaxTextUnits, &rt->full_text_units))
          return false;
      } else if (!skip_value(cur)) {
        return false;
      }
    } else if (klen > 0 && is_key("retweet_count")) {
      if (!parse_int(cur, &rt->retweet_count)) {
        if (!skip_value(cur)) return false;
      }
    } else if (klen > 0 && is_key("timestamp_ms")) {
      int64_t v;
      if (parse_int(cur, &v)) rt->created_ms = v;
      else if (!skip_value(cur)) return false;
    } else if (klen > 0 && is_key("created_at")) {
      cur.skip_ws();
      if (cur.peek() == '"') {
        uint16_t date[40];
        int64_t dn = 0;
        if (!scan_string(cur, date, 40, &dn)) return false;
        if (rt->created_ms == 0) rt->created_ms = parse_created_at(date, dn);
      } else if (!skip_value(cur)) {
        return false;
      }
    } else if (klen > 0 && is_key("user")) {
      cur.skip_ws();
      if (cur.peek() != '{') {
        if (!skip_value(cur)) return false;
      } else {
        ++cur.p;
        cur.skip_ws();
        if (cur.peek() == '}') { ++cur.p; }
        else while (true) {
          int64_t uklen = 0;
          uint16_t ukey[32];
          Cursor probe = cur;
          if (!scan_string(probe, ukey, 32, &uklen)) {
            if (!scan_string(cur, nullptr, 0, nullptr)) return false;
            uklen = -1;
          } else {
            cur = probe;
          }
          if (!cur.eat(':')) return false;
          auto is_ukey = [&](const char* name) {
            int64_t len = static_cast<int64_t>(std::strlen(name));
            if (uklen != len) return false;
            for (int64_t i = 0; i < len; ++i)
              if (ukey[i] != static_cast<uint16_t>(name[i])) return false;
            return true;
          };
          int64_t* dst = nullptr;
          if (uklen > 0 && is_ukey("followers_count")) dst = &rt->followers;
          else if (uklen > 0 && is_ukey("favourites_count")) dst = &rt->favourites;
          else if (uklen > 0 && is_ukey("friends_count")) dst = &rt->friends;
          if (dst != nullptr) {
            if (!parse_int(cur, dst)) {
              if (!skip_value(cur)) return false;
            }
          } else if (!skip_value(cur)) {
            return false;
          }
          cur.skip_ws();
          if (cur.peek() == ',') { ++cur.p; continue; }
          if (cur.peek() == '}') { ++cur.p; break; }
          return false;
        }
      }
    } else if (!skip_value(cur)) {
      return false;
    }
    cur.skip_ws();
    if (cur.peek() == ',') { ++cur.p; cur.skip_ws(); continue; }
    if (cur.peek() == '}') { ++cur.p; return true; }
    return false;
  }
}

}  // namespace

extern "C" {

// Parse a block of newline-delimited tweet JSON, keeping only rows that
// pass the reference filter (isRetweet && begin <= rt.retweet_count <= end,
// MllibHelper.scala:89-95). Outputs, per kept row i:
//   out_numeric[i*5 .. i*5+4] = {retweet_count (label), followers,
//                                favourites, friends, created_ms}
//   out_units[out_offsets[i] .. out_offsets[i+1]) = the original tweet's
//     text as UTF-16 code units (escapes resolved; NOT lowercased — callers
//     use the pad-time ASCII fold + Python lower for non-ASCII rows)
//   out_ascii[i] = 1 when every unit < 128 (row skips Python lower())
//
// buf/len: UTF-8 bytes; rows split on '\n'. cap_rows/cap_units bound the
// outputs; parsing stops early (cleanly) when either would overflow, and
// *consumed reports how many input bytes were processed so the caller can
// continue from there. Malformed lines are skipped and counted in
// *bad_lines. Returns the number of kept rows.
int64_t parse_tweet_block(const char* buf, int64_t len,
                          int64_t begin, int64_t end,
                          int64_t cap_rows, int64_t cap_units,
                          int64_t* out_numeric, uint16_t* out_units,
                          int64_t* out_offsets, uint8_t* out_ascii,
                          int64_t* consumed, int64_t* bad_lines) {
  int64_t rows = 0, unit_pos = 0, bad = 0;
  const char* p = buf;
  const char* block_end = buf + len;
  out_offsets[0] = 0;
  uint16_t text[kMaxTextUnits];
  uint16_t full_text[kMaxTextUnits];
  while (p < block_end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', block_end - p));
    if (nl == nullptr) break;  // incomplete trailing line: leave for carry
    const char* line_end = nl;
    if (rows >= cap_rows || unit_pos + kMaxTextUnits > cap_units) break;
    Cursor cur{p, line_end};
    cur.skip_ws();
    if (!cur.at_end()) {
      RtFields rt;
      bool parsed = false;
      if (cur.eat('{')) {
        cur.skip_ws();
        parsed = true;
        if (cur.peek() == '}') { ++cur.p; }
        else while (true) {
          uint16_t key[32];
          int64_t klen = 0;
          Cursor probe = cur;
          if (!scan_string(probe, key, 32, &klen)) {
            if (!scan_string(cur, nullptr, 0, nullptr)) { parsed = false; break; }
            klen = -1;
          } else {
            cur = probe;
          }
          if (!cur.eat(':')) { parsed = false; break; }
          bool is_rt_key = false;
          if (klen == 16) {
            static const char* name = "retweeted_status";
            is_rt_key = true;
            for (int i = 0; i < 16; ++i)
              if (key[i] != static_cast<uint16_t>(name[i])) {
                is_rt_key = false;
                break;
              }
          }
          if (is_rt_key) {
            cur.skip_ws();
            if (cur.peek() == '{') {
              if (!parse_rt_object(cur, &rt, text, full_text)) {
                parsed = false;
                break;
              }
            } else if (!skip_value(cur)) {  // null and friends
              parsed = false;
              break;
            }
          } else if (!skip_value(cur)) {
            parsed = false;
            break;
          }
          cur.skip_ws();
          if (cur.peek() == ',') { ++cur.p; cur.skip_ws(); continue; }
          if (cur.peek() == '}') { ++cur.p; break; }
          parsed = false;
          break;
        }
      }
      if (!parsed || !cur.ok) {
        ++bad;
      } else if (rt.present && rt.retweet_count >= begin &&
                 rt.retweet_count <= end) {
        int64_t* num = out_numeric + rows * 5;
        num[0] = rt.retweet_count;
        num[1] = rt.followers;
        num[2] = rt.favourites;
        num[3] = rt.friends;
        num[4] = rt.created_ms;
        // "text" wins unless empty, else "full_text" (Status.from_json)
        const uint16_t* body = rt.text_units > 0 ? text : full_text;
        const int64_t body_units =
            rt.text_units > 0 ? rt.text_units : rt.full_text_units;
        bool ascii = true;
        for (int64_t i = 0; i < body_units; ++i) {
          out_units[unit_pos + i] = body[i];
          if (body[i] >= 128) ascii = false;
        }
        out_ascii[rows] = ascii ? 1 : 0;
        unit_pos += body_units;
        ++rows;
        out_offsets[rows] = unit_pos;
      }
    }
    p = nl + 1;
  }
  *consumed = p - buf;
  *bad_lines = bad;
  return rows;
}

}  // extern "C"
