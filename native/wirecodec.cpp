// Digram wire codec — the C fast path of the compressed ragged units wire
// (--wireCodec dict; twtml_tpu/features/wirecodec.py is the pure-numpy
// ground truth and the two must emit IDENTICAL byte streams).
//
// Greedy left-to-right maximal munch over a 65536-entry pair LUT built by
// the Python side from the one static dictionary (the LUT travels by
// pointer each call, so the dictionary has exactly one definition). Input
// is the uint8 (all-ASCII) units buffer; output bytes < 0x80 are literals,
// >= 0x80 are dictionary codes expanding to two units on decode.
//
// The encode is ONE sequential pass at memory-bandwidth-class speed: the
// host has a single usable core (CLAUDE.md), so this rides the native
// ingest machinery like the wire emitter (tweetjson.cpp) rather than
// adding a Python-level pass. No allocation, no threads, no state.

#include <cstdint>

extern "C" {

// Encode n input bytes into out (capacity cap). lut is uint8[65536]:
// lut[(a << 8) | b] = dictionary code index, 0xFF = no code. Returns the
// number of output bytes, or -1 when the output would exceed cap (the
// caller falls back to the raw wire — an encode that cannot shrink the
// buffer is useless anyway).
int64_t digram_encode(const uint8_t* in, int64_t n, const uint8_t* lut,
                      uint8_t* out, int64_t cap) {
  int64_t m = 0;
  int64_t i = 0;
  while (i < n) {
    if (i + 1 < n) {
      uint8_t code = lut[((uint16_t)in[i] << 8) | in[i + 1]];
      if (code != 0xFF) {
        if (m >= cap) return -1;
        out[m++] = (uint8_t)(0x80 + code);
        i += 2;
        continue;
      }
    }
    if (m >= cap) return -1;
    out[m++] = in[i++];
  }
  return m;
}

}  // extern "C"
