"""Headline benchmark: streaming tweets/sec ingested+trained.

Measures the full pipeline (host featurization → padded batch → fused
predict+stats+train device step) on the attached accelerator, against the
BASELINE.md metric "tweets/sec ingested+trained". The reference publishes no
numbers (BASELINE.json ``published: {}``), so the baseline is measured in the
same process family: the identical pipeline forced onto the CPU backend in a
subprocess (the moral equivalent of the reference's ``local[8]`` operating
point on this host).

Prints ONE JSON line:
  {"metric": "tweets_per_sec_e2e", "value": N, "unit": "tweets/s",
   "vs_baseline": N / cpu_tweets_per_sec}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_TWEETS = 65536
BATCH = 2048
WARMUP_BATCHES = 2
REPEATS = 6  # best-of — passes are ~0.3 s, transport stalls come in
# multi-second bursts, so more short passes = better odds of a clean window


def measure(
    n_tweets: int = N_TWEETS, batch_size: int = BATCH, repeats: int = REPEATS
) -> dict:
    import numpy as np  # noqa: F401

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    feat = Featurizer(now_ms=1785320000000)
    model = StreamingLinearRegressionWithSGD()

    from twtml_tpu.utils.benchloop import measure_pipeline

    chunks = [statuses[i : i + batch_size] for i in range(0, n_tweets, batch_size)]

    def featurize(chunk):
        # on-device featurization wire format: the host encodes + pads raw
        # code units; bigram hashing happens inside the fused device step
        # (bit-identical features — tests/test_device_hash.py)
        return feat.featurize_batch_units(
            chunk, row_bucket=batch_size, pre_filtered=True
        )

    out = measure_pipeline(
        model, featurize, chunks, warmup_steps=WARMUP_BATCHES, repeats=repeats
    )
    del out["batches"]
    return out


def _run_child(kind: str, timeout: float) -> tuple[dict | None, str]:
    """Run one measurement in a subprocess (clean backend state; a hung
    accelerator tunnel can be timed out instead of hanging the bench).
    Returns (record, failure detail) — record None on any failure, with the
    detail distinguishing a timeout from a crash (stderr tail included)."""
    proc = None
    try:
        env = dict(os.environ, TWTML_BENCH_CHILD=kind)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
        return json.loads(proc.stdout.strip().splitlines()[-1]), ""
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout:.0f}s (accelerator unreachable?)"
    except Exception as exc:
        detail = (proc.stderr or proc.stdout).strip()[-400:] if proc else ""
        return None, detail or repr(exc)


def main() -> None:
    child = os.environ.get("TWTML_BENCH_CHILD")
    if child == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(measure(n_tweets=4096, repeats=2)))
        return
    if child == "device":
        print(json.dumps(measure()))
        return

    # device measurement with a watchdog (TWTML_BENCH_TIMEOUT seconds):
    # a dead TPU tunnel yields a CPU-fallback record instead of a hang and
    # no record at all. Healthy run ≈ compile (20-40 s) + 6×~0.3 s passes; the
    # margin covers a degraded-but-alive tunnel without tripping on it.
    timeout = float(os.environ.get("TWTML_BENCH_TIMEOUT", "1200"))
    device_result, device_err = _run_child("device", timeout)
    cpu_result, cpu_err = _run_child("cpu", timeout)
    cpu_rate = cpu_result["tweets_per_sec"] if cpu_result else None

    record: dict
    if device_result:
        value = device_result["tweets_per_sec"]
        record = {
            "metric": "tweets_per_sec_e2e",
            "value": round(value, 1),
            "unit": "tweets/s",
            "vs_baseline": round(value / cpu_rate, 2) if cpu_rate else None,
        }
    elif cpu_result:
        record = {
            "metric": "tweets_per_sec_e2e",
            "value": round(cpu_rate, 1),
            "unit": "tweets/s",
            "vs_baseline": 1.0,
            "note": f"device measurement failed ({device_err}); CPU fallback",
        }
    else:
        record = {
            "metric": "tweets_per_sec_e2e",
            "value": 0,
            "unit": "tweets/s",
            "vs_baseline": None,
            "note": f"device: {device_err}; cpu: {cpu_err}",
        }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
