"""Headline benchmark: streaming tweets/sec ingested+trained.

Measures the full pipeline (host featurization → ragged units wire → fused
re-pad+hash+predict+stats+train device step) on the attached accelerator,
against the
BASELINE.md metric "tweets/sec ingested+trained". The reference publishes no
numbers (BASELINE.json ``published: {}``), so the baseline is measured in the
same process family: the identical pipeline forced onto the CPU backend in a
subprocess (the moral equivalent of the reference's ``local[8]`` operating
point on this host).

Prints ONE JSON line:
  {"metric": "tweets_per_sec_e2e", "value": N, "unit": "tweets/s",
   "vs_baseline": N / cpu_tweets_per_sec,
   "passes": P, "best": N, "median": M}

Measurement policy (r2): every timed pass ends with a real host fetch of
the last step's mse — through this build's TPU tunnel, ``block_until_ready``
neither reliably waits nor syncs cheaply, so per-pass completion-fetch is
the only honest clock (utils/benchloop.py has the full story). Round-1
numbers measured without it overstated throughput ~3x.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_TWEETS = 524288  # 32 batches/pass at the r4 batch — the ONE honest
# completion fetch closing each pass is measurement cost, not pipeline
# cost (production streaming never syncs); a longer pass amortizes it
# toward steady-state streaming (r3: +8% best / +17% median vs short
# passes, paired)
# r4 operating point: the batch-size sweep (tools/bench_batchsize.py,
# two windows, paired interleaved vs the r2/r3 b2048 point) measured
# monotone gains to b16384 — 1.44x at b8192, 1.62x at b16384, 1.58x at
# b32768 — on the upload-bound transport (bandwidth improves with
# transfer size; per-batch fixed costs amortize). Device compute stays
# micro-seconds; this is all transport/host.
BATCH = 16384
WARMUP_BATCHES = 2
# best-of over a FIXED time budget, no early settle: the tunnel's health
# swings the rate 2-3× on ~10-minute phases (measured r2), and a settle
# check "converges" on whatever phase it lands in — during a degraded
# phase every pass is uniformly slow, so early-stopping just records the
# degraded rate. The headline runs once per round; a budget on the order
# of a phase length maximizes the chance that some passes land in a
# healthy window (no guarantee — a run that starts a fresh degraded
# phase can still spend its whole budget inside it), and the median in
# the output exposes when that happened. Watchdog margin: 600 s + compile
# stays well under the 1200 s per-child TWTML_BENCH_TIMEOUT.
REPEATS = 6
TIME_BUDGET_S = 600.0
SETTLED_AFTER = 0


def measure(
    n_tweets: int = N_TWEETS,
    batch_size: int = BATCH,
    repeats: int = REPEATS,
    time_budget_s: float | None = TIME_BUDGET_S,
    settled_after: int = SETTLED_AFTER,
    tenants: int | None = None,
) -> dict:
    import numpy as np  # noqa: F401

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    feat = Featurizer(now_ms=1785320000000)
    # TWTML_BENCH_TENANTS > 1 runs the headline pipeline through the
    # multi-tenant model plane (M models, one program, one fetch —
    # parallel/tenants.py); the tenant count rides the JSON record so a
    # multi-tenant headline number is never mistaken for the M=1 one
    tenants = (
        int(os.environ.get("TWTML_BENCH_TENANTS", "1") or 1)
        if tenants is None else tenants
    )
    if tenants > 1:
        from twtml_tpu.parallel import TenantStackModel

        model = TenantStackModel(tenants)
    else:
        model = StreamingLinearRegressionWithSGD()

    from twtml_tpu.utils.benchloop import measure_pipeline

    chunks = [statuses[i : i + batch_size] for i in range(0, n_tweets, batch_size)]

    def featurize(chunk):
        # ragged device wire (r3): the host encodes raw code units and
        # ships them CONCATENATED (no per-row pad bytes on the
        # upload-bound transport — 53% of the padded buffer was padding);
        # the fused device step re-pads with one gather and hashes bigrams
        # in-program. Bit-identical features (tests/test_ragged_wire.py,
        # test_device_hash.py); measured +14% paired vs the padded wire
        # over 76 interleaved passes, and PACKED into one buffer for
        # another +11.4% paired (per-array request overhead stops hiding
        # once the wire is lean — tools/bench_ragged.py, BENCHMARKS.md)
        # the tenant plane builds its own routed wire at the model boundary
        # (TenantStackModel.prepare_wire); the single-model path keeps the
        # k=1 packed wire
        return feat.featurize_batch_ragged(
            chunk, row_bucket=batch_size, pre_filtered=True,
            pack=(tenants == 1),
        )

    out = measure_pipeline(
        model, featurize, chunks, warmup_steps=WARMUP_BATCHES, repeats=repeats,
        time_budget_s=time_budget_s, settled_after=settled_after,
    )
    del out["batches"]
    out["tenants"] = tenants
    return out


def _run_child(kind: str, timeout: float) -> tuple[dict | None, str]:
    """Run one measurement in a subprocess (clean backend state; a hung
    accelerator tunnel can be timed out instead of hanging the bench).
    Returns (record, failure detail) — record None on any failure, with the
    detail distinguishing a timeout from a crash (stderr tail included)."""
    proc = None
    try:
        env = dict(os.environ, TWTML_BENCH_CHILD=kind)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
        return json.loads(proc.stdout.strip().splitlines()[-1]), ""
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout:.0f}s (accelerator unreachable?)"
    except Exception as exc:
        detail = (proc.stderr or proc.stdout).strip()[-400:] if proc else ""
        return None, detail or repr(exc)


def main() -> None:
    child = os.environ.get("TWTML_BENCH_CHILD")
    if child == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        # no transport jitter on the host backend: two plain passes suffice.
        # The CPU sample keeps the r2/r3 batch (2048): the r4 16384 batch is
        # a TRANSPORT operating point (upload amortization), and padding a
        # 4096-tweet sample to a 16384-row bucket would 4x the CPU work and
        # artificially inflate vs_baseline.
        print(json.dumps(
            measure(
                n_tweets=4096, batch_size=2048, repeats=2, time_budget_s=None
            )
        ))
        return
    if child == "device":
        print(json.dumps(measure()))
        return
    if child == "wire":
        # compact compressed-wire record (ISSUE 12): digram codec off/on,
        # paired, object ingest, with the modeled upload-bound transport
        # control — tools/bench_wirecodec.py is the full harness (both
        # ingest regimes + the coalesced group-wire arms)
        from tools.bench_wirecodec import measure as wire_measure

        rec = wire_measure(
            regime="object", n_tweets=32768, batch=4096, k=4, budget_s=25.0
        )
        modeled = rec["modeled_upload"]
        print(json.dumps({
            "wire_ratio": modeled["wire_ratio_single"],
            "units_ratio": modeled["units_ratio"],
            "paired_codec_cpu_control": (
                rec["control"]["paired_single_codec_vs_raw"]
            ),
            "paired_codec_upload_bound": {
                mbs: arms["single_codec_vs_raw"]
                for mbs, arms in modeled["paired_upload_bound"].items()
            },
            "backend": rec["backend"],
        }))
        return
    if child == "serving":
        # compact serving-plane record (ISSUE 9): coalesced + depth-8
        # pipelined vs naive per-request under the 70 ms modeled-RTT
        # control — the mechanism number; tools/bench_serving.py is the
        # full paired harness (run it on the tunnel with --modelRttMs 0)
        from tools.bench_serving import measure as serving_measure

        rec = serving_measure(
            requests=64, rows_per_request=16, batch_rows=256, depth=8,
            budget=25.0, model_rtt_ms=70.0,
        )
        print(json.dumps({
            "qps_pipelined_rtt70": rec["pipelined_rtt"]["qps_median"],
            "qps_naive_rtt70": rec["naive_rtt"]["qps_median"],
            "p99_ms_rtt70": rec["pipelined_rtt"]["p99_ms"],
            "paired_speedup_rtt70": (
                rec["pipelined_rtt"]["paired_speedup_vs_naive"]
            ),
            "paired_speedup_cpu_control": (
                rec["pipelined"]["paired_speedup_vs_naive"]
            ),
            "backend": rec["backend"],
        }))
        return

    # device measurement with a watchdog (TWTML_BENCH_TIMEOUT seconds):
    # a dead TPU tunnel yields a CPU-fallback record instead of a hang and
    # no record at all. Healthy run ≈ compile (20-40 s) + a pass loop that may
    # legitimately spend up to TIME_BUDGET_S (600 s) riding out transport
    # stalls; the margin above that covers a degraded-but-alive tunnel.
    timeout = float(os.environ.get("TWTML_BENCH_TIMEOUT", "1200"))
    device_result, device_err = _run_child("device", timeout)
    cpu_result, cpu_err = _run_child("cpu", timeout)
    cpu_rate = cpu_result["tweets_per_sec"] if cpu_result else None
    # serving-plane record (ISSUE 9; TWTML_BENCH_SERVING=0 skips): a short
    # paired child — ~1 minute against the headline's 600 s budget — so the
    # one JSON line also answers "what does the read path sustain?"
    serving_result = None
    if os.environ.get("TWTML_BENCH_SERVING", "1") != "0":
        serving_result, serving_err = _run_child("serving", 300.0)
        if serving_result is None:
            serving_result = {"error": serving_err}
    # compressed-wire record (ISSUE 12; TWTML_BENCH_WIRE=0 skips): a short
    # paired child — codec off/on in the object-ingest regime under the
    # modeled upload-bound control (tools/bench_wirecodec.py)
    wire_result = None
    if os.environ.get("TWTML_BENCH_WIRE", "1") != "0":
        wire_result, wire_err = _run_child("wire", 300.0)
        if wire_result is None:
            wire_result = {"error": wire_err}

    record: dict
    if device_result:
        value = device_result["tweets_per_sec"]
        record = {
            "metric": "tweets_per_sec_e2e",
            "value": round(value, 1),
            "unit": "tweets/s",
            "vs_baseline": round(value / cpu_rate, 2) if cpu_rate else None,
            # vs_baseline compares OPERATING POINTS, not just backends: the
            # device arm runs its b16384 transport optimum, the CPU arm its
            # own b2048 point (padding the CPU sample 8x would understate
            # it). The multiplier is end-to-end pipeline vs pipeline; it is
            # not a same-batch backend ratio (r4 advisor).
            "vs_baseline_basis": "device b16384 vs cpu b2048 (per-backend operating points)",
            # self-explaining round-over-round numbers: how many passes ran
            # and where the distribution sits (best == value's basis)
            "passes": device_result.get("passes"),
            "best": round(value, 1),
            "median": round(
                device_result.get("median_tweets_per_sec", value), 1
            ),
            # tunnel health-phase counts over the pass loop (the rolling
            # completion-fetch classifier, telemetry/metrics.py): how many
            # passes sat in a healthy vs degraded window, and how often the
            # phase flipped — the per-run form of the r2 "health phases"
            # story, so a degraded-budget run explains its own median
            "health": device_result.get("health"),
            # active tenant count of the measured pipeline (the multi-
            # tenant model plane, TWTML_BENCH_TENANTS; 1 = the headline
            # single-model configuration)
            "tenants": device_result.get("tenants", 1),
        }
    elif cpu_result:
        record = {
            "metric": "tweets_per_sec_e2e",
            "value": round(cpu_rate, 1),
            "unit": "tweets/s",
            "vs_baseline": 1.0,
            "note": f"device measurement failed ({device_err}); CPU fallback",
        }
    else:
        record = {
            "metric": "tweets_per_sec_e2e",
            "value": 0,
            "unit": "tweets/s",
            "vs_baseline": None,
            "note": f"device: {device_err}; cpu: {cpu_err}",
        }
    if serving_result is not None:
        # the serving plane's sustained read-path record (see the "serving"
        # child above; full paired harness: tools/bench_serving.py)
        record["serving"] = serving_result
    if wire_result is not None:
        # the compressed-wire record (see the "wire" child above; full
        # paired harness: tools/bench_wirecodec.py)
        record["wire"] = wire_result
    # run provenance (ISSUE 20): the monotonic per-host run id and the
    # operating-point fingerprint join this line to the telemetry
    # historian's segments and the round tables in BENCHMARKS.md
    from twtml_tpu.utils.runid import config_fingerprint, next_run_id

    record["run_id"] = next_run_id()
    record["config_fingerprint"] = config_fingerprint({
        "bench": "headline", "n_tweets": N_TWEETS, "batch": BATCH,
        "time_budget_s": TIME_BUDGET_S,
        "tenants": os.environ.get("TWTML_BENCH_TENANTS", "1"),
    })
    print(json.dumps(record))


if __name__ == "__main__":
    main()
