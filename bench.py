"""Headline benchmark: streaming tweets/sec ingested+trained.

Measures the full pipeline (host featurization → padded batch → fused
predict+stats+train device step) on the attached accelerator, against the
BASELINE.md metric "tweets/sec ingested+trained". The reference publishes no
numbers (BASELINE.json ``published: {}``), so the baseline is measured in the
same process family: the identical pipeline forced onto the CPU backend in a
subprocess (the moral equivalent of the reference's ``local[8]`` operating
point on this host).

Prints ONE JSON line:
  {"metric": "tweets_per_sec_e2e", "value": N, "unit": "tweets/s",
   "vs_baseline": N / cpu_tweets_per_sec}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_TWEETS = 16384
BATCH = 2048
WARMUP_BATCHES = 2


def measure(n_tweets: int = N_TWEETS, batch_size: int = BATCH) -> dict:
    import numpy as np  # noqa: F401

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    feat = Featurizer(now_ms=1785320000000)
    model = StreamingLinearRegressionWithSGD()

    from twtml_tpu.utils.benchloop import measure_pipeline

    chunks = [statuses[i : i + batch_size] for i in range(0, n_tweets, batch_size)]

    def featurize(chunk):
        return feat.featurize_batch(chunk, row_bucket=batch_size, pre_filtered=True)

    out = measure_pipeline(model, featurize, chunks, warmup_steps=WARMUP_BATCHES)
    del out["batches"]
    return out


def main() -> None:
    if os.environ.get("TWTML_BENCH_CHILD") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        out = measure(n_tweets=4096)
        print(json.dumps(out))
        return

    device_result = measure()

    # CPU baseline in a subprocess (same pipeline, CPU backend)
    cpu_rate = None
    try:
        env = dict(os.environ, TWTML_BENCH_CHILD="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=900,
        )
        cpu_rate = json.loads(proc.stdout.strip().splitlines()[-1])["tweets_per_sec"]
    except Exception:
        cpu_rate = None

    value = device_result["tweets_per_sec"]
    print(
        json.dumps(
            {
                "metric": "tweets_per_sec_e2e",
                "value": round(value, 1),
                "unit": "tweets/s",
                "vs_baseline": round(value / cpu_rate, 2) if cpu_rate else None,
            }
        )
    )


if __name__ == "__main__":
    main()
