"""Asset minifier — the analog of the reference's sbt-uglify pipeline
(web/build.sbt:25-39, the one declared asset-pipeline step without an
analog until r3).

Token-level whitespace/comment stripper built on jsmini's tokenizer (which
already drops comments): tokens re-emit per ORIGINAL source line, so
line-break placement — and with it ASI semantics (``return\\nexpr``) —
cannot change; only indentation, inter-token spaces, and comments go.
Every minification self-verifies: the output must re-tokenize to the
identical token stream (kind + value), or this raises.

Usage: python tools/jsminify.py file.js [...]   # writes file.min.js next to each
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.jsmini import tokenize  # noqa: E402

_WORD = lambda c: c.isalnum() or c in "_$"  # noqa: E731


def _emit(tok) -> str:
    if tok.kind == "str":
        return json.dumps(tok.value)  # valid JS string literal
    if tok.kind == "num":
        v = tok.value
        if float(v).is_integer() and abs(v) < 2**53:
            return str(int(v))
        return repr(v)
    if tok.kind == "regex":
        body, flags = tok.value
        return f"/{body}/{flags}"
    return str(tok.value)


def _needs_space(a: str, b: str) -> bool:
    if _WORD(a[-1]) and _WORD(b[0]):
        return True  # e.g. `var x`, `in x`, `3 in`
    if a[-1] in "+-" and b[0] == a[-1]:
        return True  # `+ ++x` must not become `+++x`
    if a[-1] == "/" and b[0] in "/*":
        return True  # never form a comment
    return False


def minify(src: str) -> str:
    tokens = tokenize(src)[:-1]  # drop eof
    pieces: list[str] = []
    buf: list[str] = []
    last_line = None
    for tok in tokens:
        if tok.line != last_line:
            if buf:
                pieces.append("".join(buf))
            buf, last_line = [], tok.line
        s = _emit(tok)
        if buf and _needs_space(buf[-1], s):
            buf.append(" ")
        buf.append(s)
    if buf:
        pieces.append("".join(buf))
    out = "\n".join(pieces) + "\n"
    # self-verification: identical token stream or refuse
    before = [(t.kind, t.value) for t in tokens]
    after = [(t.kind, t.value) for t in tokenize(out)[:-1]]
    if before != after:
        raise ValueError("minified output does not re-tokenize identically")
    return out


def main(argv=None) -> None:
    paths = list(sys.argv[1:] if argv is None else argv)
    if not paths:
        raise SystemExit("usage: jsminify.py file.js [...]")
    for path in paths:
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        out = minify(src)
        dst = path[: -len(".js")] + ".min.js" if path.endswith(".js") else path + ".min"
        with open(dst, "w", encoding="utf-8") as fh:
            fh.write(out)
        print(f"{path}: {len(src)} -> {len(out)} bytes ({dst})")


if __name__ == "__main__":
    main()
