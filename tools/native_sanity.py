"""Sanitized differential harness for the C parity fast paths.

Builds ``native/*.cpp`` with ASan/UBSan instrumentation (honoring the
``TWTML_NATIVE_SANITIZE`` seam in features/native.py) into a TEMP library
— never clobbering the production ``.so`` — and drives the same
differentials the parity law rests on, jax-free:

- ``hash_texts`` vs the pure-Python ground truth (features/hashing.py:
  char_bigrams + hashing_tf_counts), on an adversarial corpus (emoji,
  lone surrogates, empties, 1-unit rows, long rows, seeded fuzz);
- ``parse_tweet_block`` vs ``parse_tweet_block_wire`` byte-parity on
  crafted JSONL blocks (unicode, garbage lines, truncated tails, the
  retweet-count filter window);
- ``pad_units`` (narrow + wide + ASCII fold) vs a numpy reference.

Memory errors (OOB reads on ragged offsets, the classic parser bug class)
abort with a sanitizer report; semantic divergence exits 1. Exit 0 = the
instrumented library is parity-clean; exit 2 = environment cannot run the
harness (no g++ / no sanitizer runtime) — callers decide whether that is
fatal (CI: yes; the slow-marked test skips).

ASan's runtime must be loaded before CPython itself, so when ``asan`` is
requested the script re-execs itself once with ``LD_PRELOAD`` pointing at
g++'s libasan (leak checking off: CPython "leaks" by design).

Usage::

    python tools/native_sanity.py                 # ubsan+asan (default)
    TWTML_NATIVE_SANITIZE=ubsan python tools/native_sanity.py
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import tempfile
import types

_REEXEC_MARK = "TWTML_NATIVE_SANITY_REEXEC"


def _fail_env(msg: str) -> "int":
    print(f"native_sanity: SKIP-ENV {msg}", file=sys.stderr)
    return 2


def _sanitizer_runtime(name: str) -> str | None:
    try:
        out = subprocess.run(
            ["g++", f"-print-file-name={name}"],
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout.strip()
    except Exception:
        return None
    return out if os.path.sep in out and os.path.exists(out) else None


def _maybe_reexec(modes: set[str]) -> None:
    """Re-exec once with libasan preloaded when asan is requested (its
    interceptors must initialize before CPython's first allocation)."""
    if "asan" not in modes or os.environ.get(_REEXEC_MARK):
        return
    rt = _sanitizer_runtime("libasan.so")
    if rt is None:
        raise SystemExit(_fail_env("libasan.so not found via g++"))
    env = dict(os.environ)
    env[_REEXEC_MARK] = "1"
    env["LD_PRELOAD"] = " ".join(
        p for p in (rt, env.get("LD_PRELOAD", "")) if p
    )
    env.setdefault("ASAN_OPTIONS", "detect_leaks=0:abort_on_error=1")
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)]
              + sys.argv[1:], env)


def _stub_jax() -> None:
    """features/__init__ registers two pytree nodes at import; the harness
    never builds jax pytrees, and importing real jax under an ASan preload
    drowns the report in uninstrumented-jaxlib noise — stub the one entry
    point the import chain touches. A real already-imported jax wins."""
    if "jax" in sys.modules:
        return
    fake = types.ModuleType("jax")
    fake.tree_util = types.SimpleNamespace(
        register_pytree_node=lambda *a, **k: None
    )
    sys.modules["jax"] = fake


# ---------------------------------------------------------------------------
# corpora


def _texts_corpus() -> list[str]:
    rng = random.Random(42)
    crafted = [
        "", "a", "aa", "plain ascii tweet about tpus",
        "MiXeD CaSe ASCII with    spaces",
        "héllo wörld",  # BMP latin-1 supplement
        "こんにちは",  # CJK
        "\U0001f600\U0001f680",  # astral emoji: surrogate-pair bigrams
        "a\U0001f600b",
        "\ud800",  # lone high surrogate (json.loads produces these)
        "x\udfffy",  # lone low surrogate mid-string
        "aa" * 2000,  # long row
        "\t\n weird\x00控制 chars\x1f",
    ]
    alphabet = "abcdefghij éöあ\U0001f600"
    fuzz = [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 80)))
        for _ in range(200)
    ]
    return crafted + fuzz


def _block_corpus() -> bytes:
    rng = random.Random(7)

    def rt(text, count=500, **extra):
        inner = {"text": text, "retweet_count": count,
                 "user": {"followers_count": rng.randrange(0, 10**6),
                          "favourites_count": rng.randrange(0, 10**5),
                          "friends_count": rng.randrange(0, 10**4)},
                 "timestamp_ms": "1785313333333"}
        inner.update(extra)
        return {"text": "RT", "retweeted_status": inner}

    lines: list[str] = []
    for i in range(64):
        lines.append(json.dumps(rt(f"plain ascii tweet {i}", count=100 + i)))
    lines.append(json.dumps(rt("héllo été", count=150),
                            ensure_ascii=False))
    lines.append(json.dumps(rt("\U0001f600 emoji \U0001f680", count=151)))
    lines.append(json.dumps(rt("edge counts", count=0)))
    lines.append(json.dumps(rt("over the window", count=10**7)))
    lines.append(json.dumps({"text": "no retweet here"}))  # filtered
    lines.append("{garbage not json")  # bad line
    lines.append("")  # blank
    lines.append(json.dumps(rt("escaped \\\" quote \\u00e9", count=152)))
    lines.append(json.dumps(rt("x" * 5000, count=153)))  # over kMaxTextUnits
    return ("\n".join(lines) + "\n").encode("utf-8")


# ---------------------------------------------------------------------------
# differentials


def _check_hash_parity(native, hashing, np) -> list[str]:
    errors: list[str] = []
    texts = [t.lower() for t in _texts_corpus()]
    num_features = 2**18
    encoded = native.encode_texts(texts)
    lengths = np.diff(encoded[1])
    l_max = max(64, int(lengths.max()))
    idx = np.zeros((len(texts), l_max), dtype=np.int32)
    val = np.zeros((len(texts), l_max), dtype=np.float32)
    ntok = native.hash_texts(texts, num_features, idx, val, encoded=encoded)
    if ntok is None:
        return ["hash_texts returned None (fallback) on the corpus"]
    for i, text in enumerate(texts):
        want = hashing.hashing_tf_counts(
            hashing.char_bigrams(text), num_features
        )
        got: dict[int, float] = {}
        for j in range(l_max):
            if val[i, j] != 0:
                got[int(idx[i, j])] = got.get(int(idx[i, j]), 0.0) + float(
                    val[i, j]
                )
        if got != want:
            errors.append(
                f"hash row {i} diverged from features/hashing.py "
                f"(text={text[:40]!r}...)"
            )
    return errors


def _check_pad_units(native, np) -> list[str]:
    errors: list[str] = []
    texts = [t.lower() for t in _texts_corpus()[:40]]
    encoded = native.encode_texts(texts)
    units, offsets = encoded
    lengths = np.diff(offsets)
    l_max = max(8, int(lengths.max()))
    for narrow in (False, True):
        if narrow and any(u > 0xFF for u in units.tolist()):
            ascii_texts = [t for t in texts if t.isascii()]
            enc = native.encode_texts(ascii_texts)
        else:
            ascii_texts, enc = texts, encoded
        u, off = enc
        n = len(ascii_texts)
        got = native.pad_units(enc, n, n + 3, l_max, ascii_lower=False,
                               narrow=narrow)
        if got is None:
            errors.append(f"pad_units(narrow={narrow}) returned None")
            continue
        buf, length = got
        want_dtype = np.uint8 if narrow else np.uint16
        if buf.dtype != want_dtype:
            errors.append(f"pad_units(narrow={narrow}) dtype {buf.dtype}")
        for i in range(n):
            row = u[off[i]:off[i + 1]]
            if int(length[i]) != len(row) or not (
                buf[i, :len(row)].astype(np.uint16) == row.astype(np.uint16)
            ).all() or buf[i, len(row):].any():
                errors.append(f"pad_units(narrow={narrow}) row {i} mismatch")
                break
        if buf[n:].any() or length[n:].any():
            errors.append(f"pad_units(narrow={narrow}) padding rows dirty")
    return errors


def _check_block_wire_parity(native, np) -> list[str]:
    errors: list[str] = []
    data = _block_corpus()
    for begin, end in ((0, 2**62), (120, 160), (0, 1)):
        legacy = native.parse_tweet_block(data, begin, end)
        wire = native.parse_tweet_block_wire(data, begin, end)
        if legacy is None or wire is None:
            errors.append(f"parser unavailable (begin={begin})")
            continue
        l_num, l_units, l_off, l_ascii, l_cons, l_bad = legacy
        w_num, w_units, w_off, w_ascii, w_cons, w_bad = wire
        tag = f"[{begin},{end})"
        if not (np.array_equal(l_num, w_num)
                and np.array_equal(l_off, w_off)
                and np.array_equal(l_ascii, w_ascii)
                and l_cons == w_cons):
            errors.append(f"block {tag}: legacy/wire metadata diverged")
            continue
        if not np.array_equal(
            l_units.astype(np.uint16), w_units.astype(np.uint16)
        ):
            errors.append(f"block {tag}: unit payloads diverged")
        if len(w_ascii) and w_ascii.all() and w_units.dtype != np.uint8:
            errors.append(f"block {tag}: all-ASCII block not narrow")
        # bad-line counts: the wire parser's keyless-line prescreen may
        # UNDERCOUNT JSON-shaped lines with no "retweeted_status" key —
        # the documented telemetry-only divergence (BENCHMARKS.md r9);
        # kept-row payloads above are exact either way
        if w_bad > l_bad:
            errors.append(f"block {tag}: wire bad-count exceeds legacy "
                          f"({w_bad} > {l_bad})")
        # truncated tail: both parsers must stop at the same consumed byte
        cut = data[: len(data) - 37]
        lt = native.parse_tweet_block(cut, begin, end)
        wt = native.parse_tweet_block_wire(cut, begin, end)
        if lt[4] != wt[4] or wt[5] > lt[5]:
            errors.append(f"block {tag}: truncated-tail consumed/bad differ")
    return errors


def _check_codec_parity(native, np) -> "list[str]":
    """C ``digram_encode`` vs the pure-numpy ground truth
    (features/wirecodec.encode_np), byte-for-byte, plus a decode
    round-trip — the compressed-wire parity law (r15) under ASan/UBSan
    (the greedy loop reads pairs at the buffer tail: the OOB class)."""
    from twtml_tpu.features import wirecodec as wc

    errors: list[str] = []
    rng = random.Random(99)
    bufs = [
        np.zeros((0,), np.uint8),
        np.zeros((1,), np.uint8),
        np.zeros((4096,), np.uint8),
        np.frombuffer(
            b"the quick brown fox https://t.co/Ab12 jumps over the lazy "
            b"dog again and again ", np.uint8,
        ),
    ]
    for _ in range(200):
        n = rng.randrange(0, 3000)
        bufs.append(np.frombuffer(
            bytes(rng.randrange(0, 128) for _ in range(n)), np.uint8
        ).copy())
    lut = wc.pair_lut()
    for i, buf in enumerate(bufs):
        ref = wc.encode_np(buf)
        got = native.digram_encode(buf, lut) if buf.shape[0] >= 2 else ref
        if got is None:
            return [f"codec[{i}]: digram_encode unavailable in the "
                    "instrumented library"]
        if not np.array_equal(got, ref):
            errors.append(f"codec[{i}]: C encode diverges from numpy "
                          f"ground truth (n={buf.shape[0]})")
            continue
        if not np.array_equal(wc.decode_np(ref, buf.shape[0]), buf):
            errors.append(f"codec[{i}]: decode round-trip mismatch")
    return errors


def _check_assemble_parity(native, np) -> "list[str]":
    """Fused wire assembler (native/wireassemble.cpp) vs the numpy pack
    pipeline (features/batch.py, the ground truth), byte-for-byte across
    flat / per-shard / coalesced-group layouts × codec on/off × narrow
    and int32 offsets × uint16-widened and incompressible fallbacks —
    under ASan/UBSan (segment-stride memcpys over ragged offsets: the
    OOB class the sanitizers exist for)."""
    from twtml_tpu.features import assemble
    from twtml_tpu.features.batch import (
        RaggedUnitBatch, align_ragged_shards, pack_batch,
        pack_ragged_group, pack_ragged_sharded, ragged_wire_arrays,
    )

    if not native.assemble_available():
        return ["wire_assemble unavailable in the instrumented library"]
    errors: list[str] = []
    rng = random.Random(17)

    def make(b, seed, wide=False, incompressible=False, row_len=96):
        r = random.Random(seed)
        rows = []
        for i in range(b - 3):
            n = r.randrange(1, row_len)
            if incompressible:
                rows.append([r.randrange(0, 128) for _ in range(n)])
            else:
                text = b"the streaming fox https://t.co/ab again "
                rows.append([text[j % len(text)] for j in range(n)])
        if wide and rows:
            rows[0] = rows[0] + [0x3042]
        units = np.array(
            [u for row in rows for u in row], np.uint16
        ).reshape(-1)
        offsets = np.zeros(len(rows) + 1, np.int64)
        np.cumsum([len(row) for row in rows], out=offsets[1:])
        flat, offs = ragged_wire_arrays(
            units, offsets, len(rows), b, narrow=not wide
        )
        numeric = np.arange(b * 4, dtype=np.float32).reshape(b, 4) + seed
        label = np.arange(b, dtype=np.float32) * 0.5
        mask = np.zeros(b, np.float32)
        mask[: len(rows)] = 1.0
        return RaggedUnitBatch(
            flat, offs, numeric, label, mask, row_len=row_len
        )

    def both(tag, fn):
        with assemble.forced("off"):
            ref = fn()
        with assemble.forced("on"):
            got = fn()
        if got.layout != ref.layout:
            errors.append(f"assemble {tag}: layout diverged")
        elif not np.array_equal(
            np.asarray(got.buffer), np.asarray(ref.buffer)
        ):
            errors.append(f"assemble {tag}: buffer bytes diverged")

    for codec in (None, "dict"):
        for wide in (False, True):
            for inc in (False, True):
                rb = make(32, rng.randrange(1 << 20), wide, inc)
                both(f"flat c={codec} w={wide} i={inc}",
                     lambda rb=rb, c=codec: pack_batch(rb, codec=c))
                for s in (1, 2, 4):
                    al = align_ragged_shards(rb, s)
                    both(f"shard{s} c={codec} w={wide} i={inc}",
                         lambda al=al, c=codec: pack_ragged_sharded(
                             al, codec=c))
                al2 = align_ragged_shards(rb, 2)
                parts = [
                    RaggedUnitBatch(
                        al2.units.copy(), al2.offsets.copy(),
                        al2.numeric + j, al2.label + j, al2.mask.copy(),
                        row_len=al2.row_len, num_shards=al2.num_shards,
                    )
                    for j in range(3)
                ]
                both(f"group c={codec} w={wide} i={inc}",
                     lambda p=parts, c=codec: pack_ragged_group(p, codec=c))
    rb = make(32, 5)
    both("flat raw-offs", lambda: pack_batch(rb, narrow_offsets=False))
    return errors


def _check_featurize_parity(native, np) -> "list[str]":
    """One-pass fused featurize (native/featurize.cpp) vs the
    Python/numpy ground truth (features/featurizer.py), bit-for-bit on
    both ingest paths — under ASan/UBSan (the narrowing units copy and
    the column-order indexed reads are exactly the OOB class the
    sanitizers exist for)."""
    from twtml_tpu.features import featurize_native as ffz
    from twtml_tpu.features.blocks import ParsedBlock
    from twtml_tpu.features.featurizer import Featurizer, Status

    if not native.featurize_available():
        return ["featurize_wire unavailable in the instrumented library"]
    errors: list[str] = []
    rng = random.Random(99)
    statuses = []
    for i, text in enumerate(_texts_corpus()):
        statuses.append(Status(
            text="RT", retweet_count=1,
            retweeted_status=Status(
                text=text,
                retweet_count=rng.choice((99, 100, 500, 1000, 1001)),
                followers_count=rng.randrange(0, 10**7),
                favourites_count=rng.randrange(0, 10**6),
                friends_count=rng.randrange(0, 10**5),
                created_at_ms=rng.randrange(0, 1785313333333),
            ),
        ))
        if i % 11 == 0:
            statuses.append(Status(text="plain, filtered out"))
    feat = Featurizer(now_ms=1785313333333)

    def both(tag, fn):
        with ffz.forced("off"):
            ref = fn()
        with ffz.forced("on"):
            got = fn()
        for f in ("units", "offsets", "numeric", "label", "mask"):
            a, b = getattr(ref, f), getattr(got, f)
            if a.dtype != b.dtype or not np.array_equal(a, b):
                errors.append(f"featurize {tag}: {f} diverged")
                return
        if ref.row_len != got.row_len:
            errors.append(f"featurize {tag}: row_len diverged")

    both("object mixed", lambda: feat.featurize_batch_ragged(
        statuses, row_bucket=0))
    ascii_only = [
        s for s in statuses
        if s.retweeted_status is not None
        and s.retweeted_status.text.isascii()
    ]
    both("object ascii", lambda: feat.featurize_batch_ragged(
        ascii_only, row_bucket=64, pre_filtered=True))
    both("object empty", lambda: feat.featurize_batch_ragged(
        [], row_bucket=8))
    parsed = native.parse_tweet_block_wire(_block_corpus(), 0, 10**9)
    if parsed is None:
        errors.append("featurize: block wire parser unavailable")
        return errors
    block = ParsedBlock(*parsed[:4])
    both("block mixed", lambda: feat.featurize_parsed_block(
        block, row_bucket=0, ragged=True))
    keep_ascii = [i for i in range(block.rows) if block.ascii[i]]
    if keep_ascii:
        stop = 0
        while stop < block.rows and block.ascii[stop]:
            stop += 1
        from twtml_tpu.features.blocks import slice_block

        ascii_blk = slice_block(block, 0, stop)
        both("block ascii prefix", lambda: feat.featurize_parsed_block(
            ascii_blk, row_bucket=32, ragged=True))
        wide_blk = ParsedBlock(
            ascii_blk.numeric, ascii_blk.units.astype(np.uint16),
            ascii_blk.offsets, ascii_blk.ascii,
        )
        both("block u16 ascii", lambda: feat.featurize_parsed_block(
            wide_blk, row_bucket=32, ragged=True))
    return errors


def main() -> int:
    os.environ.setdefault("TWTML_NATIVE_SANITIZE", "asan,ubsan")
    modes = {m.strip()
             for m in os.environ["TWTML_NATIVE_SANITIZE"].split(",") if m}
    _maybe_reexec(modes)

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    _stub_jax()

    tmp = tempfile.mkdtemp(prefix="twtml-native-sanity-")
    os.environ.setdefault(
        "TWTML_NATIVE_LIB", os.path.join(tmp, "libfasthash_san.so")
    )
    import numpy as np

    from twtml_tpu.features import hashing, native

    if native.get_lib() is None:
        return _fail_env("instrumented library failed to build/load "
                         "(no g++, or sanitizer link failure)")
    errors: list[str] = []
    errors += _check_hash_parity(native, hashing, np)
    errors += _check_pad_units(native, np)
    errors += _check_block_wire_parity(native, np)
    errors += _check_codec_parity(native, np)
    errors += _check_assemble_parity(native, np)
    errors += _check_featurize_parity(native, np)
    for e in errors:
        print(f"native_sanity: FAIL {e}", file=sys.stderr)
    print(
        f"native_sanity: modes={','.join(sorted(modes)) or 'none'} "
        f"lib={os.environ['TWTML_NATIVE_LIB']} "
        f"{'FAIL ' + str(len(errors)) + ' differential(s)' if errors else 'PASS'}"
    )
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
