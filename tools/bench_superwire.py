"""Lean wire v2 verdict: coalesced one-buffer superbatch wire vs stacked.

The question (ISSUE 3): ``--superBatch K`` stacks the ragged wire as K
per-field arrays — K small puts — while two measured facts say one LARGE
coalesced put should win on the tunnel: upload bandwidth improves with
transfer size (the b16384/b32768 batch-sweep result) and packing the lean
ragged wire paid +11.4% paired (r3). ``--wirePack group``
(features/batch.pack_ragged_group) composes them: one contiguous buffer
per K batches, uint16-delta offsets, unpacked inside the scanned program.

Verdict comes from the house method only (tools/pairedbench.py):
interleaved single passes + paired per-round ratios, in BOTH regimes the
measured record names —

- telemetry  : the upload-bound per-batch-telemetry regime (f_text=1000,
               the SuperBatcher path end-to-end, per-batch handler work
               included — the regime where the wire binds);
- 2e18       : config #4 at its b1024 operating point (Gram-domain,
               device-bound — where r3 measured --superBatch itself
               NEGATIVE; if coalescing is negative here too it must ship
               flag-off for this config, per the "measure in the target
               regime" law).

Each regime also reports the wire accounting directly: bytes per group on
both layouts and the offset bytes the uint16-delta sideband deletes.

Usage: python tools/bench_superwire.py [--regime telemetry|2e18|both]
       [--tweets N] [--batch B] [--k K] [--budget S]
Prints one JSON line. Parity is asserted per round (identical final mse
across arms — the wire may never change the math).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _regime(
    name: str, f_text: int, l2: float, int8, batch: int, k: int,
    n_tweets: int, budget: float,
) -> dict:
    import jax

    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )
    from twtml_tpu.apps.common import SuperBatcher
    from twtml_tpu.features.batch import (
        pack_ragged_group, wire_composition, wire_nbytes,
    )
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    feat = Featurizer(num_text_features=f_text, now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [
        statuses[i : i + batch] for i in range(0, len(statuses), batch)
    ]
    batches = [
        feat.featurize_batch_ragged(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]

    def consume(out, b, t, at_boundary=True):
        # the app handlers' per-batch work: read every StepOutput field
        float(out.count); float(out.mse)
        float(out.real_stdev); float(out.pred_stdev)
        _ = out.predictions[0]

    # ---- wire accounting on the first full group -------------------------
    head = batches[: min(k, len(batches))]
    sig0 = (head[0].units.shape, str(head[0].units.dtype), head[0].row_len)
    same_sig = [
        b for b in head
        if (b.units.shape, str(b.units.dtype), b.row_len) == sig0
    ]
    stacked_bytes = sum(wire_nbytes(b) for b in same_sig)
    grouped = pack_ragged_group(same_sig)
    comp = wire_composition(same_sig[0])
    out = {
        "batch": batch,
        "k": k,
        "group_batches_sampled": len(same_sig),
        "stacked_wire_bytes_per_group": stacked_bytes,
        "coalesced_wire_bytes_per_group": int(grouped.buffer.nbytes),
        "offsets_bytes_per_batch_i32": comp["offsets"],
        "offsets_bytes_per_batch_u16delta": wire_composition(grouped)[
            "offsets"
        ] // len(same_sig),
    }

    finals: dict = {}

    def make_arm(mode):
        model = StreamingLinearRegressionWithSGD(
            num_text_features=f_text, l2_reg=l2, gram_int8=int8
        )

        def one_pass():
            model.reset()
            t0 = time.perf_counter()
            sb = SuperBatcher(
                model, k, consume, fetch_depth=4, wire_pack=mode
            )
            for rb in batches:
                sb.on_batch(rb, 0.0)
            sb.flush()
            dt = time.perf_counter() - t0
            finals[mode] = round(float(model.latest_weights.sum()), 6)
            return dt

        one_pass()  # warm every program this arm dispatches (per layout)
        return one_pass

    arms = {"stacked": make_arm("stacked"), "group": make_arm("group")}
    times = run_rounds(arms, budget)
    for mode, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[mode] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
            "passes": len(ts),
        }
    out["paired_group_vs_stacked"] = paired_ratio_median(
        times["stacked"], times["group"]
    )
    assert finals["stacked"] == finals["group"], (
        "wire layouts diverged — parity violation"
    )
    out["backend"] = jax.default_backend()
    return out


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    regime, n_tweets, budget, k = "both", 65536, 120.0, 8
    batch = 0  # per-regime default below
    i = 0
    while i < len(args):
        if args[i] == "--regime":
            regime = args[i + 1]; i += 2
        elif args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--k":
            k = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")
    if regime not in ("telemetry", "2e18", "both"):
        raise SystemExit(f"unknown --regime {regime!r}")

    out = {"bench": "superwire"}
    per = budget / (2 if regime == "both" else 1)
    if regime in ("telemetry", "both"):
        # the upload-bound regime: f_text=1000, b2048 (the telemetry
        # operating point the fetch-pipeline/superbatch record uses)
        out["telemetry"] = _regime(
            "telemetry", 1000, 0.0, None, batch or 2048, k, n_tweets, per
        )
    if regime in ("2e18", "both"):
        # config #4 at its r3 operating point (b1024, Gram-domain int8)
        out["2e18"] = _regime(
            "2e18", 2**18, 0.1, True, batch or 1024, k, n_tweets, per
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
