"""Headline-config batch-size sweep (r4): is 2048 still the right batch
for the dense ragged+packed flagship pipeline?

Why re-ask: the upload-bound tunnel's effective bandwidth IMPROVES with
transfer size (BENCHMARKS.md "Measurement integrity"), and the r3 wire
work (ragged + packed) changed the bytes-per-batch landscape the r2
choice of 2048 was made in. Larger batches amortize per-batch fixed
costs (dispatch, the packed-buffer assembly, featurize-call overhead);
smaller ones pipeline more finely. Device compute is nowhere near
binding on this config, so the answer is all transport/host.

Arms interleave round-robin within one window (tunnel phase swings hit
every arm equally) and the report gives paired per-round ratios vs the
b2048 incumbent — the same methodology as tools/bench_2e18.py.

Usage: python tools/bench_batchsize.py [--tweets N] [--budget S]
       [--config headline|logistic] [--batches 2048,8192,...]
``--config logistic`` sweeps CONFIG #3's own pipeline (lexicon sentiment
labeler + logistic learner, ragged+packed) instead of the headline's —
VERDICT r4 #6: the suite default there was set by analogy to the headline
profile; this measures it on the config itself.
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, budget, config = 131072, 300.0, "headline"
    batches = (1024, 2048, 4096, 8192, 16384, 32768)
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        elif args[i] == "--batches":
            batches = tuple(int(b) for b in args[i + 1].split(",")); i += 2
        elif args[i] == "--config":
            config = args[i + 1]; i += 2
            if config not in ("headline", "logistic"):
                raise SystemExit(f"unknown --config {config!r}")
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")
    if 2048 not in batches:
        batches = (2048,) + batches  # the paired baseline arm

    import jax

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import (
        StreamingLinearRegressionWithSGD,
        StreamingLogisticRegressionWithSGD,
    )
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.utils.benchloop import _run_once

    feat = Featurizer(now_ms=1785320000000)
    if config == "logistic":
        # config #3's exact pipeline: lexicon sentiment labels via the C
        # batched labeler + the logistic learner (tools/bench_suite.py)
        from twtml_tpu.features.sentiment import (
            sentiment_label,
            sentiment_labels,
        )

        feat.label_fn = sentiment_label
        feat.batch_label_fn = sentiment_labels
        model_cls = StreamingLogisticRegressionWithSGD
    else:
        model_cls = StreamingLinearRegressionWithSGD
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())

    arms: dict = {}

    def arm(batch):
        chunks = [
            statuses[i : i + batch] for i in range(0, len(statuses), batch)
        ]

        def fz(c, batch=batch):
            return feat.featurize_batch_ragged(
                c, row_bucket=batch, pre_filtered=True, pack=True
            )

        m = model_cls()
        for _ in range(2):
            float(m.step(fz(chunks[0])).mse)  # completion-fetch warmup

        def one_pass(m=m, fz=fz, chunks=chunks):
            m.reset()
            return _run_once(m, fz, chunks, prefetch=True)

        arms[f"b{batch}"] = one_pass

    for b in batches:
        arm(b)

    times: dict[str, list] = {k: [] for k in arms}
    t_end = time.perf_counter() + budget
    while time.perf_counter() < t_end:
        for name, run in arms.items():
            dt, _ = run()
            times[name].append(dt)

    out = {"config": f"{config}_batch_sweep", "tweets": n_tweets,
           "backend": jax.default_backend(), "rounds": len(times["b2048"])}
    base = times["b2048"]
    for name, ts in times.items():
        out[name] = {
            "best": round(n_tweets / min(ts), 1),
            "median": round(n_tweets / statistics.median(ts), 1),
        }
        if name != "b2048":
            out[name]["paired_speedup_median"] = round(
                statistics.median([b / t for b, t in zip(base, ts)]), 3
            )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
