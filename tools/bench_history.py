"""Telemetry-historian overhead check (ISSUE 20): the full --history plane
— per-publish sample framing (CRC32 journal frames, stage-clock deltas,
registry snapshot, phase tracking, perfGuard window) written to real
segments — measured against a no-historian control in the per-batch-
telemetry regime (the regime where per-batch host costs bind;
BENCHMARKS.md).

Arms (interleaved single passes + paired per-round ratios, the house
method — tools/pairedbench.py):

- off  : the consume loop never touches the historian — the exact HEAD
         hot path (``--history off`` uninstalls the module hook, so
         production pays even less: one no-op call per stats tick);
- hist : ``historian.sample()`` once per delivered batch (the stats ticks
         run every batch in this regime, so this is the WORST-CASE
         sampling cadence; production samples every METRICS_EVERY=8
         updates at most).

Both arms dispatch the SAME model/program — the historian is host-side
only (zero added fetches, zero collectives; the counted test in
tests/test_history.py proves it), so any delta is pure Python + buffered
disk writes. Passes the acceptance gate when the paired ratio (off/hist)
is >= 0.97x (the ISSUE's <= 3% budget).

Usage: python tools/bench_history.py [--tweets N] [--batch B] [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget = 65536, 2048, 120.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.apps.common import FetchPipeline
    from twtml_tpu.features.batch import pack_batch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.telemetry import historian as _historian

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]
    r_batches = [
        feat.featurize_batch_ragged(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]

    # one segment directory for the whole run: the hist arm appends to real
    # segments round over round (rotation included), exactly like a long
    # production run; the off arm never touches the configured historian,
    # which is the HEAD hot path (no call at all)
    hist_dir = tempfile.mkdtemp(prefix="twtml-bench-history-")
    _historian.configure(
        hist_dir, max_mb=64, perf_guard=True, run_id=1, fingerprint="bench",
    )

    def consume_off(out, b, t, at_boundary=True):
        float(out.count); float(out.mse)
        float(out.real_stdev); float(out.pred_stdev)
        _ = out.predictions[0]

    def consume_hist(out, b, t, at_boundary=True):
        consume_off(out, b, t, at_boundary)
        _historian.sample()

    model = StreamingLinearRegressionWithSGD()
    seen = set()
    for rb in r_batches:  # warm every packed layout both arms dispatch
        key = (rb.units.shape, str(rb.units.dtype), rb.row_len)
        if key not in seen:
            seen.add(key)
            float(model.step(pack_batch(rb)).mse)

    def run_pass(consume):
        model.reset()
        t0 = time.perf_counter()
        pipe = FetchPipeline(model, consume, depth=8, pack=True)
        for rb in r_batches:
            pipe.on_batch(rb, 0.0)
        pipe.flush()
        return time.perf_counter() - t0

    def off_pass():
        return run_pass(consume_off)

    def hist_pass():
        return run_pass(consume_hist)

    off_pass(); hist_pass()  # warm both arms' code paths

    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )

    times = run_rounds({"off": off_pass, "hist": hist_pass}, budget)
    view = _historian.last_history() or {}
    disk_mb = _historian.get().disk_bytes() / 1e6 if _historian.get() else 0.0
    _historian.uninstall()
    shutil.rmtree(hist_dir, ignore_errors=True)
    out = {
        "regime": "history-overhead", "batch": batch,
        "tweets": n_tweets, "backend": jax.default_backend(),
        "rounds": len(times["off"]),
        "samples_written": view.get("samples", 0),
        "segments_disk_mb": round(disk_mb, 2),
    }
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
        }
    out["hist"]["paired_vs_off"] = paired_ratio_median(
        times["off"], times["hist"]
    )
    out["neutral"] = out["hist"]["paired_vs_off"] >= 0.97
    print(json.dumps(out))


if __name__ == "__main__":
    main()
