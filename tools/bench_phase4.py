"""Config #4 (hashing_2e18_l2) sustained-rate measurement ACROSS tunnel
health phases — VERDICT r4 #3.

The r4 suite met the ≥150k bar inside one healthy window; the acceptance as
written was "sustained across phases". This tool runs the suite's exact
config-#4 shape (65536 synthetic tweets, ragged wire, int8 Gram plane,
batch 2048 vs 3072) as INTERLEAVED single passes for a fixed long budget
(default 1500 s — sized to straddle at least two of the tunnel's ~10-minute
health phases, BENCHMARKS.md "Measurement integrity"), timestamps every
round, and reports:

- per-arm best / median over the WHOLE window (the sustained number);
- per-300 s-window medians (the phase profile — how far the swings go);
- the paired per-round b3072/b2048 ratio (operating-point check);
- the fraction of b2048 rounds at or above 150k tweets/s.

Usage: python tools/bench_phase4.py [--tweets N] [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

F_TEXT = 2**18
WINDOW_S = 300.0


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, budget = 65536, 1500.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.utils.benchloop import _run_once

    feat = Featurizer(num_text_features=F_TEXT, now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())

    arms: dict = {}
    for batch in (2048, 3072):
        chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]

        def fz(c, batch=batch):
            return feat.featurize_batch_ragged(
                c, row_bucket=batch, pre_filtered=True
            )

        m = StreamingLinearRegressionWithSGD(
            num_text_features=F_TEXT, l2_reg=0.1, gram_int8=True
        )
        for _ in range(2):
            float(m.step(fz(chunks[0])).mse)  # completion-fetch warmup

        def one_pass(m=m, fz=fz, chunks=chunks):
            m.reset()
            return _run_once(m, fz, chunks, prefetch=True)

        arms[f"b{batch}"] = one_pass

    rounds: dict[str, list] = {k: [] for k in arms}  # (t_offset, seconds)
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget:
        for name, run in arms.items():
            dt, _ = run()
            rounds[name].append((round(time.perf_counter() - t0, 1), dt))

    out = {
        "config": "hashing_2e18_l2_phase_sustain",
        "tweets": n_tweets,
        "backend": jax.default_backend(),
        "budget_s": budget,
        "rounds": len(rounds["b2048"]),
    }
    for name, rs in rounds.items():
        ts = [dt for _, dt in rs]
        rates = [n_tweets / dt for dt in ts]
        windows: dict[int, list] = {}
        for off, dt in rs:
            windows.setdefault(int(off // WINDOW_S), []).append(n_tweets / dt)
        out[name] = {
            "best": round(max(rates), 1),
            "median": round(statistics.median(rates), 1),
            "per_window_median": {
                str(w): round(statistics.median(v), 1)
                for w, v in sorted(windows.items())
            },
            "frac_ge_150k": round(
                sum(r >= 150_000 for r in rates) / len(rates), 3
            ),
        }
    a, b = [dt for _, dt in rounds["b2048"]], [dt for _, dt in rounds["b3072"]]
    out["paired_b3072_over_b2048"] = round(
        statistics.median([x / y for x, y in zip(a, b)]), 3
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
