"""Config #4 int8-plane MFU accounting — VERDICT r4 #2.

Measures COMPLETION-VERIFIED device time for the shipped 2^18 Gram step and
decomposes it, then states achieved FLOP/s against v5e peaks. Method: the
batch is made device-RESIDENT first (one upload), then K chained dispatches
end with ONE scalar fetch; per-step time is the (K2 − K1) delta so the
fixed dispatch/RTT overhead cancels (the r2 measurement rules —
BENCHMARKS.md "Measurement integrity"; `block_until_ready` is not a clock
on this transport).

Arms (each its own jit program over the same resident operands):
  full_step   — the shipped train step (ragged re-pad + hash + int8 Gram
                + 50-iteration dual loop + write-back)
  counts_i8   — the two-level one-hot densify alone ([B, L]→[B, F] int8)
  gram_i8     — the G = C·Cᵀ s8×s8→s32 matmul alone (resident counts)
  dual_50     — the 50-iteration dual loop alone (resident G)

FLOP model (B rows, L token slots, F = 2^18 — k_hi·k_lo = F exactly):
  counts: 2·B·L·F    gram: 2·B²·F    dual: 50·2·B²    (rest negligible)

Peaks used: v5e ≈ 394.5 TOPS int8, 197.2 TFLOPS bf16.

Usage: python tools/bench_mfu.py [--batch 2048] [--k 64]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

F_TEXT = 2**18
V5E_INT8_PEAK = 394.5e12
V5E_BF16_PEAK = 197.2e12


def _chained_step_time(dispatch, fetch, k1: int = 8, k2: int = 72,
                       budget_s: float = 75.0, min_reps: int = 3,
                       settle: int = 6):
    """Per-iteration seconds via the (k2−k1) chained-dispatch delta, timed
    under the repo's shared stall-riding policy (benchloop.measure_passes:
    reps spread over a time budget, settled when the best stops improving
    — best-of-3 back-to-back reps can land entirely inside one of the
    tunnel's minutes-long stall bursts and report a stalled delta as the
    truth). Returns ``(best_dt, reps, median_over_best)`` — the last is
    the burst-visibility diagnostic (a large ratio = the window was mostly
    stalled)."""
    from twtml_tpu.utils.benchloop import measure_passes

    def run_pass():
        ts = {}
        for k in (k1, k2):
            t0 = time.perf_counter()
            for _ in range(k):
                out = dispatch()
            fetch(out)
            ts[k] = time.perf_counter() - t0
        dt = (ts[k2] - ts[k1]) / (k2 - k1)
        if dt <= 0:
            # a stall burst inside the k1 window makes the delta
            # meaningless (even negative). Substitute the k2 pass's
            # per-step mean — a strict UPPER bound on the true per-step
            # time (it still carries the fixed dispatch/RTT overhead), so
            # a stalled rep can never fake a best.
            dt = ts[k2] / k2
        return dt, None

    best, _, times = measure_passes(
        run_pass, repeats=min_reps, time_budget_s=budget_s,
        settled_after=settle,
    )
    # statistics.median, not sorted(times)[len//2]: the upper-middle pick
    # is biased high on even-length samples (ADVICE r5)
    import statistics

    med = statistics.median(times)
    return best, len(times), round(med / best, 3)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    batch, k_hi = 2048, 72
    i = 0
    while i < len(args):
        if args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--k":
            k_hi = int(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")
    if k_hi <= 8:
        raise SystemExit(
            "--k must exceed the fixed k1=8 (the per-step time is the "
            "(k2-k1) chained delta)"
        )

    import jax
    import jax.numpy as jnp

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.ops.gram import onehot_counts_int8
    from twtml_tpu.streaming.sources import SyntheticSource

    feat = Featurizer(num_text_features=F_TEXT, now_ms=1785320000000)
    statuses = list(SyntheticSource(total=batch, seed=3).produce())
    unit = feat.featurize_batch_units(
        statuses, row_bucket=batch, pre_filtered=True
    )
    dev_batch = jax.device_put(unit)
    # the hashed token width the one-hot build actually sees (bigrams)
    l_tok = unit.units.shape[1] - 1

    model = StreamingLinearRegressionWithSGD(
        num_text_features=F_TEXT, l2_reg=0.1, gram_int8=True
    )
    num_iter = 50

    # resident token arrays for the sub-programs
    from twtml_tpu.ops.text_hash import hash_bigrams_device

    @jax.jit
    def tokens(b):
        return hash_bigrams_device(b.units, b.length, F_TEXT, jnp.float32)

    tok_idx, tok_val = jax.tree_util.tree_map(
        lambda x: jax.device_put(x), jax.device_get(tokens(dev_batch))
    )
    tok_idx = jnp.asarray(tok_idx, jnp.int32)
    tok_val = jnp.asarray(tok_val, jnp.float32)

    @jax.jit
    def counts_only(idx, val, salt):
        # salt keeps repeated dispatches distinct (no constant folding of
        # identical result reuse); MUST stay int32 — a float salt would
        # silently promote the operands off the integer MXU path
        c = onehot_counts_int8(idx + 0 * salt, val, F_TEXT)
        # abs defeats XLA's sum-of-matmul factorization (sum(C) would
        # reduce the one-hot matmul to a cheap vector rewrite)
        return jnp.sum(jnp.abs(c.astype(jnp.int32)))

    counts = jax.jit(
        lambda idx, val: onehot_counts_int8(idx, val, F_TEXT)
    )(tok_idx, tok_val)
    counts = jax.device_put(jax.device_get(counts))

    @jax.jit
    def gram_only(c, salt):
        g = jnp.matmul(
            c + (0 * salt).astype(jnp.int8), c.T,
            preferred_element_type=jnp.int32,
        )
        # abs is load-bearing: plain sum(C·Cᵀ) factorizes to Σ_f colsum²
        # and XLA takes that rewrite (measured "484 TFLOP/s" — above
        # peak — before this guard)
        return jnp.sum(jnp.abs(g))

    g_f32 = jax.jit(
        lambda c: jnp.matmul(
            c, c.T, preferred_element_type=jnp.int32
        ).astype(jnp.float32)
    )(counts)
    g_f32 = jax.device_put(jax.device_get(g_f32))
    u0 = jnp.zeros((batch,), jnp.float32)
    lab = jnp.asarray(unit.label)
    msk = jnp.asarray(unit.mask)

    from twtml_tpu.models.sgd import run_dual_loop

    @jax.jit
    def dual_only(g, salt):
        dual = run_dual_loop(
            u=u0 + salt * 0.0, g=g, labels=lab, mask=msk,
            dtype=jnp.float32,
            residual_fn=lambda raw, label: raw - label,
            num_iterations=num_iter, step_size=0.005,
            mini_batch_fraction=1.0, l2_reg=0.1, convergence_tol=0.001,
            p_prev=jnp.zeros((), jnp.float32),
        )
        return dual["alpha"].sum()

    # ---- warmups (full completion fetch each) -----------------------------
    float(model.step(dev_batch).mse)
    float(counts_only(tok_idx, tok_val, jnp.int32(0)))
    float(gram_only(counts, jnp.int32(0)))
    float(dual_only(g_f32, jnp.float32(0.0)))

    # ---- chained timings --------------------------------------------------
    t_step, n_step, sp_step = _chained_step_time(
        lambda: model.step(dev_batch), lambda o: float(o.mse), k2=k_hi
    )
    salt_box = [0]

    def salted(fn, *operands, flt: bool = False):
        def dispatch():
            salt_box[0] += 1
            salt = (
                jnp.float32(salt_box[0]) if flt else jnp.int32(salt_box[0])
            )
            return fn(*operands, salt)
        return dispatch

    t_counts, n_counts, sp_counts = _chained_step_time(
        salted(counts_only, tok_idx, tok_val), lambda o: float(o), k2=k_hi
    )
    t_gram, n_gram, sp_gram = _chained_step_time(
        salted(gram_only, counts), lambda o: float(o), k2=k_hi
    )
    t_dual, n_dual, sp_dual = _chained_step_time(
        salted(dual_only, g_f32, flt=True), lambda o: float(o), k2=k_hi
    )

    f_counts = 2.0 * batch * l_tok * F_TEXT
    f_gram = 2.0 * batch * batch * F_TEXT
    f_dual = 2.0 * batch * batch * num_iter
    f_total = f_counts + f_gram + f_dual

    def tflops(f, t):
        return round(f / t / 1e12, 2)

    out = {
        "config": "hashing_2e18_l2_mfu",
        "backend": jax.default_backend(),
        "batch": batch,
        "l_tok": l_tok,
        "flops_per_step_T": round(f_total / 1e12, 3),
        "step_ms": round(t_step * 1e3, 3),
        "counts_ms": round(t_counts * 1e3, 3),
        "gram_ms": round(t_gram * 1e3, 3),
        "dual_ms": round(t_dual * 1e3, 3),
        "achieved_tflops_full_step": tflops(f_total, t_step),
        "mfu_vs_int8_peak": round(f_total / t_step / V5E_INT8_PEAK, 3),
        "mfu_vs_bf16_peak": round(f_total / t_step / V5E_BF16_PEAK, 3),
        "gram_tflops": tflops(f_gram, t_gram),
        "gram_mfu_int8": round(f_gram / t_gram / V5E_INT8_PEAK, 3),
        "counts_tflops": tflops(f_counts, t_counts),
        "dual_tflops": tflops(f_dual, t_dual),
        # burst visibility: reps taken and median/best per arm — a large
        # ratio means the budget sat mostly in a stalled phase
        "reps": [n_step, n_counts, n_gram, n_dual],
        "median_over_best": [sp_step, sp_counts, sp_gram, sp_dual],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
