"""Shared interleaved/paired-ratio bench harness — the house measurement
method as a library.

The tunnel's health swings on ~10-minute phases (BENCHMARKS.md), so
sequential per-arm blocks confound arm with phase. Every wire/dispatch
verdict in this repo therefore comes from ONE method: single passes
round-robin A/B/A/B… inside one budget window, then PAIRED per-round
ratios (each pair shares a phase window) summarized by their median —
health-phase-safe, because a phase swing hits both members of a pair.

This module extracts the arm scheduling and the ratio math that
tools/bench_ragged.py, tools/bench_2e18.py and tools/bench_telemetry.py
each re-implemented (r3–r5), so the method cannot drift between tools;
tools/bench_superwire.py is built on it directly.

An *arm* is a zero-arg callable running ONE full pass and returning its
wall-clock seconds (or a ``(seconds, anything)`` tuple — the extra value
is discarded here; arms that need finals record them via closure). Arms
are responsible for their own warmup (compile + completion-fetch) before
entering the window: the harness times passes, it does not classify them.
"""

from __future__ import annotations

import statistics
import time


def run_rounds(
    arms: "dict[str, object]", budget_s: float, min_rounds: int = 1
) -> "dict[str, list[float]]":
    """Round-robin single passes over ``arms`` until the budget expires.

    Every started round COMPLETES (each arm ends with the same sample
    count — the paired-ratio invariant), and at least ``min_rounds``
    rounds run even past a tiny budget. Returns per-arm pass times in
    round order; ``paired_ratio_median`` consumes them pairwise."""
    times: "dict[str, list[float]]" = {name: [] for name in arms}
    t_end = time.perf_counter() + budget_s
    rounds = 0
    while rounds < min_rounds or time.perf_counter() < t_end:
        for name, run in arms.items():
            result = run()
            dt = result[0] if isinstance(result, tuple) else result
            times[name].append(float(dt))
        rounds += 1
    return times


def best_median_rate(
    pass_times: "list[float]", items: int
) -> "tuple[float, float]":
    """(best, median) items/second over a list of pass times."""
    return (
        round(items / min(pass_times), 1),
        round(items / statistics.median(pass_times), 1),
    )


def paired_ratios(
    base_times: "list[float]", arm_times: "list[float]"
) -> "list[float]":
    """Per-round base/arm time ratios (>1 = the arm is faster): the
    phase-robust comparison — each pair shares one tunnel-phase window."""
    return [b / a for b, a in zip(base_times, arm_times)]


def paired_ratio_median(
    base_times: "list[float]", arm_times: "list[float]", digits: int = 3
) -> float:
    """Median paired speedup of ``arm`` over ``base`` — the ONE number a
    wire/dispatch verdict quotes (BENCHMARKS.md house rules)."""
    return round(
        statistics.median(paired_ratios(base_times, arm_times)), digits
    )
