"""In-process v1.1-protocol stream server — lets bench config #2
(twitter_live) MEASURE the real TwitterSource → train path on rigs without
Twitter credentials or egress (VERDICT r2 #6), instead of skipping.

Same protocol shape as the reference's endpoint (chunked HTTP/1.1,
delimited JSON lines, keep-alive blanks — what Twitter4j consumes at
LinearRegression.scala:44): the client exercises its full native stack
(OAuth1 signing, chunked decode, line reassembly, Status parse). Results
against it are tagged {"mode": "local-protocol"} so they are never
confused with real-Twitter numbers.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class LocalV11StreamServer:
    """Serves ``lines`` (JSON tweet strings) as one chunked stream per
    connection, then a clean terminator; reconnects replay the corpus
    (the consumer's batch cap decides when the run ends)."""

    def __init__(self, lines: list[str], chunk_bytes: int = 1 << 14):
        body = ("\r\n".join(lines) + "\r\n").encode()
        chunk = chunk_bytes

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                self.send_response(200)
                self.send_header("Transfer-Encoding", "chunked")
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                try:
                    for i in range(0, len(body), chunk):
                        piece = body[i : i + chunk]
                        self.wfile.write(
                            f"{len(piece):x}\r\n".encode() + piece + b"\r\n"
                        )
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # consumer hit its cap and hung up
                self.close_connection = True

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self._server.server_address[1]}/stream"

    def __enter__(self) -> "LocalV11StreamServer":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._server.shutdown()
        self._server.server_close()
