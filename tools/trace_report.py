"""Summarize a ``--trace`` pipeline trace into the per-stage time budget.

Reads the Chrome-trace-event file ``telemetry/trace.py`` writes (a ``[``
line + one event per line, trailing commas — also valid input for Perfetto)
and prints the per-stage table that used to take a bench investigation to
reconstruct: total/mean/max milliseconds and event count per stage, wire
bytes, health-phase transitions.

Exit status is a CHECK (bench scripts gate on it): 0 = a valid trace with at
least one pipeline span; 2 = malformed (unparseable event line, no events,
or not a trace at all). ``--json`` emits one machine-readable JSON line
instead of the table.

Usage: python tools/trace_report.py TRACE_FILE [--json]
"""

from __future__ import annotations

import json
import sys


class MalformedTrace(ValueError):
    pass


def load_events(path: str) -> list[dict]:
    """Parse a trace into event dicts, STITCHING rotated segments: when
    size rotation (--traceMaxMb) left a ``PATH.1`` next to ``PATH``, its
    (older) events are prepended so one report covers both segments.
    Tolerates the incremental array decoration (leading ``[``/trailing
    ``]``, per-line trailing commas) and a plain JSON-array file; raises
    MalformedTrace on anything that is not a sequence of event objects."""
    import os

    rotated = path + ".1"
    if os.path.exists(rotated):
        return _load_one(rotated) + _load_one(path)
    return _load_one(path)


def _load_one(path: str) -> list[dict]:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    stripped = text.strip()
    if not stripped:
        raise MalformedTrace("empty trace file")
    events: list[dict] = []
    try:
        # complete-JSON path (a hand-closed array, or {"traceEvents": [...]})
        doc = json.loads(stripped)
        if isinstance(doc, dict):
            doc = doc.get("traceEvents")
        if not isinstance(doc, list):
            raise MalformedTrace("JSON document is not a trace event array")
        events = doc
    except json.JSONDecodeError:
        # incremental form: one event per line, trailing commas
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = raw.strip().rstrip(",")
            if line in ("", "[", "]"):
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as exc:
                raise MalformedTrace(f"line {lineno}: {exc}") from exc
    if not events:
        raise MalformedTrace("no events in trace")
    for ev in events:
        if not isinstance(ev, dict) or "ph" not in ev:
            raise MalformedTrace(f"not a trace event: {ev!r}")
    return events


def summarize(events: list[dict]) -> dict:
    """Aggregate complete ("X") spans per stage + health-phase marks."""
    stages: dict[str, dict] = {}
    phases: list[dict] = []
    for ev in events:
        if ev.get("ph") == "X":
            name = ev.get("name", "?")
            dur_ms = float(ev.get("dur", 0.0)) / 1e3
            st = stages.setdefault(
                name,
                {"count": 0, "total_ms": 0.0, "max_ms": 0.0, "bytes": 0},
            )
            st["count"] += 1
            st["total_ms"] += dur_ms
            st["max_ms"] = max(st["max_ms"], dur_ms)
            args = ev.get("args") or {}
            for key in ("wire_bytes", "bytes"):
                if key in args:
                    st["bytes"] += int(args[key])
                    break
        elif ev.get("ph") == "i" and ev.get("name") == "health_phase":
            phases.append((ev.get("args") or {}))
    for st in stages.values():
        st["mean_ms"] = round(st["total_ms"] / st["count"], 3)
        st["total_ms"] = round(st["total_ms"], 3)
        st["max_ms"] = round(st["max_ms"], 3)
    return {
        "stages": dict(
            sorted(stages.items(), key=lambda kv: -kv[1]["total_ms"])
        ),
        "health_transitions": phases,
        "events": len(events),
    }


def render(summary: dict) -> str:
    rows = [
        (name, st["count"], st["total_ms"], st["mean_ms"], st["max_ms"],
         st["bytes"])
        for name, st in summary["stages"].items()
    ]
    widths = (14, 8, 12, 10, 10, 14)
    head = ("stage", "events", "total ms", "mean ms", "max ms", "bytes")
    out = [
        "  ".join(h.ljust(w) for h, w in zip(head, widths)),
        "  ".join("-" * w for w in widths),
    ]
    for r in rows:
        out.append("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    tr = summary["health_transitions"]
    out.append(
        f"health-phase transitions: {len(tr)}"
        + (f" (last → {tr[-1].get('phase')})" if tr else "")
    )
    return "\n".join(out)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    if len(args) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        summary = summarize(load_events(args[0]))
    except (OSError, MalformedTrace) as exc:
        print(f"trace_report: malformed trace: {exc}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(summary))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
