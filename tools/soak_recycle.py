"""Recycle soak: demonstrate ``--recycleAfterMb`` against the REAL axon
transfer-buffer retention (VERDICT r4 #7's done bar — the unit test
``tests/test_recycler.py`` forces a 1 MB ceiling on CPU; this soak runs the
shipped linear-regression app on the tunnel, lets the tunnel client's
retention grow host RSS at its natural rate, and proves the mechanism
end-to-end: ceiling crossed -> checkpoint at a weights-current boundary ->
in-place re-exec -> bit-identical resume -> bounded per-life RSS).

Two phases over the same replay corpus (identical flags except the ceiling):

1. CALIBRATE: run the app with recycling off, sampling its RSS from the
   OUTSIDE (/proc/<pid>/statm, ~4 Hz) — yields the post-compile baseline
   and the corpus' natural retention growth on this transport.
2. DEMONSTRATE: ceiling = baseline + 60% of the measured growth (guaranteed
   to cross mid-file), TWTML_RECYCLE_MAX=1. The harness keeps sampling the
   SAME pid across the os.execv and asserts, from the run's own logs:
   exactly one recycle; save/restore state CRCs match (bit-identical
   weights); the final count equals count-at-recycle + corpus size (exact
   counter resume + full second replay, the documented replay-recycle
   semantics); and the re-exec actually reclaimed the retention (RSS cliff
   at the exec, every life bounded).

Usage: python tools/soak_recycle.py [--tweets N] [--batch B]
Prints one JSON line (machine-checkable; "ok": true is the soak passing).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CLOSED = "http://127.0.0.1:9"  # closed port: telemetry stays best-effort-off


def _write_corpus(path: str, total: int) -> None:
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    with open(path, "w") as fh:
        for s in SyntheticSource(
            total=total, seed=11, base_ms=1785320000000
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")


def _statm_mb(pid: int) -> float | None:
    try:
        with open(f"/proc/{pid}/statm") as fh:
            return int(fh.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE") / 1e6)
    except (OSError, IndexError, ValueError):
        return None


class _AppRun:
    """Launch the app, drain stdout/stderr on threads, sample RSS at ~4 Hz
    until exit. The recycler re-execs IN PLACE (same pid), so one sample
    series spans every life; the exec shows up as an RSS cliff."""

    def __init__(self, argv, env):
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO,
        )
        self.out_lines: list[str] = []
        self.err_lines: list[str] = []
        self.samples: list[tuple[float, float]] = []  # (t, rss_mb)
        self.first_stat_t: float | None = None
        self._threads = [
            threading.Thread(target=self._drain, args=(self.proc.stdout, True)),
            threading.Thread(target=self._drain, args=(self.proc.stderr, False)),
        ]
        for t in self._threads:
            t.daemon = True
            t.start()

    def _drain(self, pipe, is_out):
        for line in pipe:
            (self.out_lines if is_out else self.err_lines).append(line)
            if is_out and self.first_stat_t is None and line.startswith("count:"):
                self.first_stat_t = time.monotonic()

    def wait(self, timeout: float) -> int:
        deadline = time.monotonic() + timeout
        while self.proc.poll() is None:
            if time.monotonic() > deadline:
                self.proc.kill()
                self.proc.wait()
                raise TimeoutError("app run exceeded its budget")
            mb = _statm_mb(self.proc.pid)
            if mb is not None:
                self.samples.append((time.monotonic(), mb))
            time.sleep(0.25)
        for t in self._threads:
            t.join(timeout=10)
        return self.proc.returncode

    @property
    def stdout(self) -> str:
        return "".join(self.out_lines)

    @property
    def stderr(self) -> str:
        return "".join(self.err_lines)


def _app_argv(replay: str, ckdir: str, batch: int, ceiling_mb: int) -> list:
    argv = [
        sys.executable, "-m", "twtml_tpu.apps.linear_regression",
        "--source", "replay", "--replayFile", replay,
        "--seconds", "0", "--batchBucket", str(batch),
        # cadence 16: boundary drains (the recycler's only actuation
        # points) land ~8x per corpus at the default batch, so a ceiling
        # crossed mid-file recycles well before the file ends
        "--checkpointDir", ckdir, "--checkpointEvery", "16",
        "--lightning", CLOSED, "--twtweb", CLOSED,
    ]
    if ceiling_mb > 0:
        argv += ["--recycleAfterMb", str(ceiling_mb)]
    return argv


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    total, batch = 2_000_000, 16384
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            total = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import tempfile

    work = tempfile.mkdtemp(prefix="twtml-recycle-soak-")
    replay = os.path.join(work, "tweets.jsonl")
    t0 = time.monotonic()
    _write_corpus(replay, total)
    gen_s = time.monotonic() - t0
    # APPEND the repo to PYTHONPATH — platform plugins (the axon tunnel's
    # jax backend) register via entries already on it, and operator modules
    # on the existing path keep precedence over same-named repo files
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        env["PYTHONPATH"] + os.pathsep + REPO
        if env.get("PYTHONPATH") else REPO
    )

    # ---- phase 1: calibrate the natural retention on this transport ----
    run_a = _AppRun(
        _app_argv(replay, os.path.join(work, "ck_a"), batch, 0), env
    )
    rc_a = run_a.wait(timeout=900)
    if rc_a != 0:
        print(json.dumps({"ok": False, "phase": "calibrate", "rc": rc_a,
                          "stderr_tail": run_a.stderr[-2000:]}))
        raise SystemExit(1)
    # post-compile baseline: first sample at/after the first stats line
    # (compile + device init are done once streaming starts)
    base = next(
        (mb for (t, mb) in run_a.samples
         if run_a.first_stat_t and t >= run_a.first_stat_t),
        run_a.samples[-1][1] if run_a.samples else 0.0,
    )
    # default=0.0: a sub-250ms crash leaves no samples, and the empty-max
    # ValueError would mask the {"ok": false} line below (ADVICE r5)
    peak_a = max((mb for (_, mb) in run_a.samples), default=0.0)
    growth = peak_a - base
    if growth < 50.0:
        print(json.dumps({
            "ok": False, "phase": "calibrate", "rc": 0,
            "error": "retention growth below the 50 MB demo floor; "
                     "raise --tweets (or the transport stopped leaking)",
            "rss_base_mb": round(base, 1), "rss_peak_mb": round(peak_a, 1),
        }))
        raise SystemExit(1)

    # ---- phase 2: demonstrate the automatic recycle ----
    ceiling = int(base + 0.6 * growth)
    env_b = dict(env, TWTML_RECYCLE_MAX="1")
    run_b = _AppRun(
        _app_argv(replay, os.path.join(work, "ck_b"), batch, ceiling), env_b
    )
    rc_b = run_b.wait(timeout=1200)
    err = run_b.stderr
    ok = rc_b == 0
    recycles = re.findall(
        r"checkpointed at batch (\d+) \(count=(\d+), state crc ([0-9a-f]+)\)"
        r" and re-exec'ing", err,
    )
    resumes = re.findall(
        r"resumed from checkpoint step \d+ \(count=(\d+), state crc "
        r"([0-9a-f]+)\)", err,
    )
    ok &= len(recycles) == 1 and len(resumes) == 1
    crc_match = count_match = False
    count_r = 0
    if recycles and resumes:
        count_r = int(recycles[0][1])
        crc_match = resumes[0][1] == recycles[0][2]
        count_match = int(resumes[0][0]) == count_r
    stats = [l for l in run_b.out_lines if l.startswith("count:")]
    final_count = int(re.findall(r"count: (\d+)", stats[-1])[0]) if stats else -1
    full_resume = final_count == count_r + total

    # RSS cliff at the exec: largest single-step drop in the series
    drops = [
        (run_b.samples[j - 1][1] - run_b.samples[j][1], j)
        for j in range(1, len(run_b.samples))
    ]
    cliff_mb, j_cliff = max(drops) if drops else (0.0, 0)
    pre_exec_peak = max(
        (mb for (_, mb) in run_b.samples[:j_cliff]), default=0.0
    )
    post_exec_floor = run_b.samples[j_cliff][1] if drops else 0.0
    life2_peak = max(
        (mb for (_, mb) in run_b.samples[j_cliff:]), default=0.0
    )
    reclaimed = cliff_mb > 0.3 * max(pre_exec_peak, 1.0)
    # bounded: no life strays above ceiling + one full corpus' retention
    # (the recycler acts at the NEXT boundary, so one cadence of overshoot
    # is by design; life 2 replays the whole file under MAX=1)
    bound_mb = ceiling + growth + 256
    bounded = max(
        (mb for (_, mb) in run_b.samples), default=0.0
    ) <= bound_mb

    import shutil

    shutil.rmtree(work, ignore_errors=True)  # the corpus is ~350 MB/1M tweets
    ok &= crc_match and count_match and full_resume and reclaimed and bounded
    print(json.dumps({
        "ok": bool(ok), "metric": "recycle_soak", "tweets": total,
        "batch": batch, "corpus_gen_s": round(gen_s, 1),
        "calibrate": {
            "rss_base_mb": round(base, 1), "rss_peak_mb": round(peak_a, 1),
            "growth_mb": round(growth, 1),
            "retention_bytes_per_tweet": round(growth * 1e6 / total, 1),
        },
        "ceiling_mb": ceiling, "recycles": len(recycles),
        "crc_match": crc_match, "count_at_recycle": count_r,
        "final_count": final_count, "full_resume": full_resume,
        "exec_cliff_mb": round(cliff_mb, 1),
        "pre_exec_peak_mb": round(pre_exec_peak, 1),
        "post_exec_floor_mb": round(post_exec_floor, 1),
        "life2_peak_mb": round(life2_peak, 1),
        "bounded_under_mb": bound_mb, "bounded": bounded, "rc": rc_b,
    }))
    if not ok:
        sys.stderr.write(err[-3000:] + "\n")
        raise SystemExit(1)


if __name__ == "__main__":
    main()
