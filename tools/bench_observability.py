"""Observability-overhead neutrality check: the full r8 observability
stack — --trace (with rotation), the sideband stage clock + per-tick
collection, and the crash flight recorder — measured against an
instrumentation-free control in the per-batch-telemetry regime (the regime
where per-batch overheads bind; BENCHMARKS.md).

Arms (interleaved single passes + paired per-round ratios, the house
method — tools/pairedbench.py):

- off : stage clock disabled, no tracer, no recorder — the pre-PR-1 cost
        of the pipeline;
- obs : trace to a rotating file + stage clock + flight recorder + one
        sideband collection per batch (the per-tick cost a lockstep host
        pays, charged at the worst-case cadence of every batch).

Passes the acceptance gate when the paired ratio (off/obs) is >= 0.98x.

Usage: python tools/bench_observability.py [--tweets N] [--batch B]
          [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget = 65536, 2048, 120.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.apps.common import FetchPipeline
    from twtml_tpu.features.batch import pack_batch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.telemetry import blackbox as _blackbox
    from twtml_tpu.telemetry import sideband as _sideband
    from twtml_tpu.telemetry import trace as _trace

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]
    r_batches = [
        feat.featurize_batch_ragged(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]

    def consume(out, b, t, at_boundary=True):
        float(out.count); float(out.mse)
        float(out.real_stdev); float(out.pred_stdev)
        _ = out.predictions[0]

    model = StreamingLinearRegressionWithSGD()
    seen = set()
    for rb in r_batches:  # warm every packed layout the arms dispatch
        key = (rb.units.shape, str(rb.units.dtype), rb.row_len)
        if key not in seen:
            seen.add(key)
            float(model.step(pack_batch(rb)).mse)

    tmp = tempfile.mkdtemp(prefix="bench-obs-")

    def run_pass():
        model.reset()
        t0 = time.perf_counter()
        pipe = FetchPipeline(model, consume, depth=8, pack=True)
        for b in r_batches:
            pipe.on_batch(b, 0.0)
        pipe.flush()
        return time.perf_counter() - t0

    def off_pass():
        _trace.uninstall()
        _blackbox.uninstall()
        _sideband.set_stage_clock(False)
        try:
            return run_pass()
        finally:
            _sideband.set_stage_clock(True)

    collector = _sideband.SidebandCollector()

    def obs_pass():
        # rotation armed small enough to actually rotate during the pass,
        # so the obs arm pays the rotation cost too
        _trace.install(os.path.join(tmp, "obs.trace"),
                       max_bytes=4 * 1024 * 1024)
        _blackbox.install(config={"bench": "observability"}, out_dir=tmp)
        dt = None
        try:
            model.reset()
            t0 = time.perf_counter()
            pipe = FetchPipeline(model, consume, depth=8, pack=True)
            for b in r_batches:
                pipe.on_batch(b, 0.0)
                collector.collect()  # worst case: a sideband tick per batch
            pipe.flush()
            dt = time.perf_counter() - t0
        finally:
            _trace.uninstall()
            _blackbox.uninstall()
        return dt

    off_pass(); obs_pass()  # warm both arms' code paths

    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )

    times = run_rounds({"off": off_pass, "obs": obs_pass}, budget)
    out = {
        "regime": "observability-overhead", "batch": batch,
        "tweets": n_tweets, "backend": jax.default_backend(),
        "rounds": len(times["off"]),
    }
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
        }
    out["obs"]["paired_vs_off"] = paired_ratio_median(
        times["off"], times["obs"]
    )
    out["neutral"] = out["obs"]["paired_vs_off"] >= 0.98
    print(json.dumps(out))


if __name__ == "__main__":
    main()
