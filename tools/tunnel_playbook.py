"""First-tunnel-window playbook (ROADMAP item 5): probe the backend, and
on a LIVE window run the prioritized paired-bench backlog end to end —
so the next lucky window costs ONE command instead of a session.

NINE consecutive sessions found no reachable TPU tunnel while the
measurement backlog grew to span five shipped features. This tool makes
the window cheap to exploit:

1. **Probe** — a subprocess imports jax WITHOUT the CPU pin (the test
   conftest and CI set ``JAX_PLATFORMS=cpu``; the probe strips it) under
   a hard timeout, and reports the backend it actually got. A hung
   tunnel handshake is a dead window, not a hung session.
2. **Backlog** — on a live accelerator the prioritized bench list runs
   sequentially, each under its own timeout. Every tool here is built on
   the house harness (tools/pairedbench.py: interleaved arms, paired
   per-round ratios), so each verdict is health-phase-safe by
   construction; the playbook adds the cross-tool discipline — priority
   order (the standing ``auto``-default decisions first), per-tool wall
   clocks sized to straddle the tunnel's ~10-minute health phases, and
   one BENCHMARKS-ready JSONL record per tool.
3. **Retune notes** — after the run it prints the flip instructions for
   each standing ``auto`` default (``--wireCodec``, ``--wirePack``)
   keyed to the thresholds BENCHMARKS.md records, so the session that
   hits the window can also land the config change.

On a cpu-only probe it emits ``{"live": false, ...}`` and exits 0 — the
attempt itself is the BENCHMARKS record (the per-PR "probed, cpu-only"
line).

Usage: python tools/tunnel_playbook.py [--probeTimeout S] [--budget S]
       [--only NAME] [--out PATH] [--force]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the prioritized backlog: (name, argv tail, per-tool timeout seconds,
# why it is in the queue — the BENCHMARKS section its number lands in).
# Budgets are sized to straddle the tunnel's ~10-minute health phases
# (CLAUDE.md): a verdict measured inside one phase window is not a
# verdict (the r2/r3 interleaving law).
BACKLOG = (
    ("wirecodec", ["tools/bench_wirecodec.py", "--regime", "both",
                   "--budget", "600"], 1800,
     "the standing --wireCodec auto decision: bandwidth improves with "
     "transfer size, so the modeled arm cannot capture a smaller "
     "transfer landing on a worse bandwidth point (BENCHMARKS "
     "'Compressed wire')"),
    ("wireassemble", ["tools/bench_wireassemble.py", "--regime", "both",
                      "--budget", "300"], 1200,
     "r17 fused pack on the real tunnel: host-chain dilution under live "
     "upload (BENCHMARKS 'One-pass wire assembly')"),
    ("superwire", ["tools/bench_superwire.py", "--budget", "600"], 1800,
     "the standing --wirePack auto decision (BENCHMARKS 'Lean wire v2' "
     "flip instructions)"),
    ("fleet", ["tools/bench_fleet.py", "--modelRttMs", "0",
               "--budget", "300"], 1200,
     "fleet QPS with the REAL tunnel instead of the 70 ms modeled RTT "
     "(ROADMAP item 2 REMAINING)"),
    ("serving", ["tools/bench_serving.py", "--modelRttMs", "0",
                 "--budget", "300"], 1200,
     "serving-plane QPS, real tunnel (ROADMAP item 5 backlog)"),
    ("tenants", ["tools/bench_tenants.py", "--budget", "300"], 1200,
     "the tenant >=3x verdict in the regime that motivated it "
     "(per-batch telemetry through a real RTT)"),
    ("blockparse", ["tools/bench_blockparse.py"], 900,
     "block-wire ingest rates on the tunnel (PR 6 REMAINING)"),
    ("featurize", ["tools/bench_featurize.py", "--budget", "120"], 900,
     "r18 one-pass featurize: host-stage ratios are backend-free, but "
     "the tunnel window shows the end-to-end dilution under live "
     "upload (BENCHMARKS 'One-pass featurize')"),
    ("freshness", ["tools/bench_freshness.py", "--budget", "300"], 1200,
     "r19 freshness plane on the real tunnel: the <=3% overhead gate in "
     "the regime where delivered-batch host costs bind (BENCHMARKS "
     "'Freshness plane overhead')"),
    ("journal", ["tools/bench_journal.py", "--budget", "300"], 1200,
     "r21 intake journal on the real tunnel: the CPU 0.981x paired "
     "ratio co-schedules the append with the device step on one core; "
     "under live upload RTT the append should hide entirely "
     "(BENCHMARKS 'Durable intake journal')"),
    ("soak", ["tools/soak.py", "--minutes", "20",
              "--maxRssSlopeMbPerMin", "10"], 1800,
     "the axon RSS retention under the arena (r17): slope gate proves "
     "the pooled transfer buffers bound it (ROADMAP item 5)"),
    ("history", ["tools/bench_history.py", "--budget", "300"], 1200,
     "r22 telemetry historian on the real tunnel: the <=3% overhead "
     "gate with segment writes co-scheduled against live upload RTT, "
     "plus real healthy/degraded phase intervals in the segments "
     "(BENCHMARKS 'Historian overhead')"),
)

RETUNE_NOTES = """\
Retune instructions (apply in config.py, cite the JSONL record):
- wirecodec: if paired_upload_bound group_codec_vs_raw >= 1.10 across
  the live window, flip effective_wire_codec()'s auto default to 'dict'
  (and effective_wire_pack resolves group automatically).
- superwire: if the group arm wins paired >= 1.05 live, flip
  effective_wire_pack()'s auto default to 'group'.
- wireassemble: auto already means on-when-available (host-only work);
  record the live host-chain dilution next to the CPU number.
- soak: slope <= gate with --arena on proves the r17 mitigation on the
  real transport; record both slopes in BENCHMARKS 'Endurance soaks'.
"""


def probe(timeout_s: float) -> dict:
    """Backend probe in a subprocess with the CPU pin stripped — a hung
    tunnel handshake times out there, not here."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    code = (
        "import json, jax; "
        "print(json.dumps({'backend': jax.default_backend(), "
        "'devices': len(jax.devices())}))"
    )
    t0 = time.perf_counter()
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], env=env, cwd=REPO,
            capture_output=True, text=True, timeout=timeout_s,
        )
        got = json.loads(out.stdout.strip().splitlines()[-1]) if (
            out.returncode == 0 and out.stdout.strip()
        ) else {"backend": "error", "devices": 0,
                "stderr": out.stderr[-500:]}
    except subprocess.TimeoutExpired:
        got = {"backend": "timeout", "devices": 0}
    except Exception as exc:  # probe infrastructure failure, not a verdict
        got = {"backend": "error", "devices": 0, "error": str(exc)}
    got["probe_s"] = round(time.perf_counter() - t0, 2)
    got["live"] = got.get("backend") not in ("cpu", "timeout", "error")
    return got


def run_backlog(only: "str | None", budget_scale: float, out_path: str,
                sink) -> list:
    records = []
    for name, argv, timeout_s, why in BACKLOG:
        if only and name != only:
            continue
        scaled = [
            str(int(float(a) * budget_scale))
            if argv[i - 1] in ("--budget", "--minutes") else a
            for i, a in enumerate(argv)
        ]
        t0 = time.time()
        rec = {"tool": name, "argv": scaled, "t_start": round(t0, 1),
               "why": why}
        try:
            out = subprocess.run(
                [sys.executable, *scaled], cwd=REPO,
                capture_output=True, text=True,
                timeout=timeout_s * budget_scale,
            )
            lines = [ln for ln in out.stdout.strip().splitlines() if ln]
            try:
                rec["result"] = json.loads(lines[-1]) if lines else None
            except json.JSONDecodeError:
                rec["result"] = None
                rec["stdout_tail"] = "\n".join(lines[-3:])
            rec["exit"] = out.returncode
            if out.returncode != 0:
                rec["stderr_tail"] = out.stderr[-800:]
        except subprocess.TimeoutExpired:
            rec["exit"] = -1
            rec["timeout_s"] = timeout_s * budget_scale
        rec["seconds"] = round(time.time() - t0, 1)
        records.append(rec)
        line = json.dumps(rec)
        print(line, file=sink, flush=True)
        with open(out_path, "a", encoding="utf-8") as fh:
            fh.write(line + "\n")
    return records


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)

    def opt(name, default, cast):
        if name in args:
            return cast(args[args.index(name) + 1])
        return default

    probe_timeout = opt("--probeTimeout", 120.0, float)
    budget_scale = opt("--budget", 1.0, float)
    only = opt("--only", None, str)
    out_path = opt(
        "--out", os.path.join(REPO, "tunnel_playbook_out.jsonl"), str
    )
    force = "--force" in args  # run the backlog even on a cpu probe

    got = probe(probe_timeout)
    header = {"playbook": "tunnel", "probe": got,
              "t": round(time.time(), 1)}
    print(json.dumps(header), flush=True)
    with open(out_path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(header) + "\n")
    if not got["live"] and not force:
        # the attempt IS the record: append the probe line to the
        # BENCHMARKS backlog section by hand (or let the PR do it)
        return 0
    run_backlog(only, budget_scale, out_path, sys.stdout)
    print(RETUNE_NOTES, file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
