"""Intake-journal overhead check (ISSUE 19): the healthy-path cost of the
durable journal — one CRC32-framed disk append + dispatch-token push per
intake batch, one token pop + commit per delivered batch — measured
against a ``--journal off`` control in the per-batch-telemetry regime
(the regime where per-batch host costs bind; BENCHMARKS.md).

Arms (interleaved single passes + paired per-round ratios, the house
method — tools/pairedbench.py):

- off     : no journal installed — the seam no-ops, the exact
            ``--journal off`` hot path (the bit-parity arm);
- journal : a live ``IntakeJournal`` (fresh directory per pass): append +
            push_dispatch per seam batch, pop_dispatch + note_delivered
            per delivery — the full healthy-path cost of the journal
            (replay/retirement are recovery-path-only and never run here).

Both arms dispatch the SAME model/program — the journal is host-side only
(zero added fetches, zero device traffic, zero collectives), so any delta
is Python serialization + buffered disk writes. Passes the acceptance
gate when the paired ratio (journal/off) is >= 0.97x (the ISSUE's <= 3%
budget).

The bench drives the ``IntakeJournal`` instance directly instead of the
``streaming.journal.record_intake`` seam hook: lawcheck TW009 reserves
the hook for streaming/context.py, and the instance calls are the exact
same code path.

Usage: python tools/bench_journal.py [--tweets N] [--batch B]
          [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    # passes are kept short (~1.5 s on the 1-core CPU host) so the budget
    # buys MANY paired rounds: the true overhead (~2 µs/row) is far below
    # this box's per-pass noise, and only the paired-round median at high
    # round counts resolves it
    n_tweets, batch, budget = 16384, 2048, 120.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.apps.common import FetchPipeline
    from twtml_tpu.features.batch import pack_batch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.journal import IntakeJournal
    from twtml_tpu.streaming.sources import SyntheticSource

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]
    r_batches = [
        feat.featurize_batch_ragged(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]

    def consume_off(out, b, t, at_boundary=True):
        float(out.count); float(out.mse)
        float(out.real_stdev); float(out.pred_stdev)
        _ = out.predictions[0]

    model = StreamingLinearRegressionWithSGD()
    seen = set()
    for rb in r_batches:  # warm every packed layout both arms dispatch
        key = (rb.units.shape, str(rb.units.dtype), rb.row_len)
        if key not in seen:
            seen.add(key)
            float(model.step(pack_batch(rb)).mse)

    tmp = tempfile.mkdtemp(prefix="bench-journal-")
    pass_no = [0]

    def run_pass(consume, journal):
        model.reset()
        t0 = time.perf_counter()
        pipe = FetchPipeline(model, consume, depth=8, pack=True)
        for chunk, rb in zip(chunks, r_batches):
            if journal is not None:
                # the intake seam (streaming/context.py): append the
                # drained rows, push the dispatch token
                journal.append(chunk)
                journal.push_dispatch()
            pipe.on_batch(rb, 0.0)
        pipe.flush()
        return time.perf_counter() - t0

    def off_pass():
        return run_pass(consume_off, journal=None)

    def journal_pass():
        pass_no[0] += 1
        d = os.path.join(tmp, f"j{pass_no[0]}")
        j = IntakeJournal(d, max_mb=512)

        def consume(out, b, t, at_boundary=True):
            # the delivery wrappers (apps/common.py): outermost pops the
            # token, innermost commits it
            j.pop_dispatch()
            consume_off(out, b, t, at_boundary)
            j.note_delivered()

        try:
            return run_pass(consume, j)
        finally:
            j.close()
            shutil.rmtree(d, ignore_errors=True)

    off_pass(); journal_pass()  # warm both arms' code paths

    # regime-independent absolute seam cost: append + dispatch-token push,
    # timed directly, for both record kinds (the pipeline arms above only
    # resolve the RELATIVE cost in this regime). The block row uses a
    # representative parsed-block layout (~21 uint8 units/row).
    import numpy as np

    def seam_us_per_row(items_per_append, n_appends, rows_per_append):
        d = os.path.join(tmp, "seam")
        j = IntakeJournal(d, max_mb=512)
        try:
            t0 = time.perf_counter()
            for _ in range(n_appends):
                j.append(items_per_append)
                j.push_dispatch()
                j.pop_dispatch()
                j.note_delivered()
            dt = time.perf_counter() - t0
        finally:
            j.close()
            shutil.rmtree(d, ignore_errors=True)
        return round(dt / (n_appends * rows_per_append) * 1e6, 3)

    from twtml_tpu.features.blocks import ParsedBlock

    rng = np.random.default_rng(7)
    lens = rng.integers(12, 32, size=batch)
    offsets = np.zeros(batch + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    block = ParsedBlock(
        rng.integers(0, 1000, size=(batch, 5)).astype(np.int64),
        rng.integers(32, 127, size=int(offsets[-1])).astype(np.uint8),
        offsets, np.ones(batch, np.uint8),
    )
    obj_us = seam_us_per_row(chunks[0], 24, len(chunks[0]))
    block_us = seam_us_per_row([block], 24, block.rows)

    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )

    times = run_rounds({"off": off_pass, "journal": journal_pass}, budget)
    shutil.rmtree(tmp, ignore_errors=True)
    out = {
        "regime": "journal-overhead", "batch": batch,
        "tweets": n_tweets, "backend": jax.default_backend(),
        "rounds": len(times["off"]),
        "seam_obj_us_per_row": obj_us,
        "seam_block_us_per_row": block_us,
    }
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
        }
    out["journal"]["paired_vs_off"] = paired_ratio_median(
        times["off"], times["journal"]
    )
    out["neutral"] = out["journal"]["paired_vs_off"] >= 0.97
    print(json.dumps(out))


if __name__ == "__main__":
    main()
