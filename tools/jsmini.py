"""jsmini — a minimal ECMAScript interpreter in pure Python.

WHY THIS EXISTS: VERDICT r1 #5 requires the shipped dashboard JavaScript
(web/assets/js/{api,index,chart,test}.js) to actually EXECUTE in CI — a
broken jsonClass dispatch or counter id must fail a test — and this build
image has no JavaScript runtime at all (no node/deno/bun, no embeddable
engine). The reference at least declared selenium/HtmlUnit
(WebTestSuite.scala:7,44-52, commented out); this is the working analog:
tests/test_dashboard_js.py runs the real asset files against a stub DOM
(tools/jsdom.py). Parsing every shipped asset also doubles as the syntax
lint the reference got from sbt-jshint (web/build.sbt:25-39):
``python tools/jsmini.py --check <file.js...>``.

Scope: the ES2015 subset the assets use — functions/arrows/closures,
prototypes + ``new``, const/let/var, if/else, classic and for-of loops,
while, switch, try/catch, ternary/logical/arithmetic/bitwise/comparison
operators, object & array literals (incl. shorthand), spread in calls,
array-destructuring params, regex literals (translated to Python ``re``),
and a small standard library (JSON, Math, Number, String/Array methods,
Promise-as-job-queue). NOT a general JS engine: no generators, async/await,
classes, getters, labels, or prototype mutation beyond ``F.prototype.x =``.
Unsupported syntax raises at parse time — which is exactly the lint.
"""

from __future__ import annotations

import json as _json
import math as _math
import random as _random
import re as _re

# ---------------------------------------------------------------------------
# tokenizer

KEYWORDS = {
    "var", "let", "const", "function", "return", "if", "else", "for", "of",
    "in", "while", "do", "break", "continue", "new", "typeof", "delete",
    "switch", "case", "default", "try", "catch", "finally", "throw", "this",
    "true", "false", "null", "undefined", "instanceof", "void",
}

PUNCT = [
    "===", "!==", "**=", "...", "=>", "==", "!=", "<=", ">=", "&&", "||",
    "++", "--", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "<<", ">>",
    "{", "}", "(", ")", "[", "]", ";", ",", "<", ">", "+", "-", "*", "/",
    "%", "&", "|", "^", "~", "!", "?", ":", "=", ".",
]


class Token:
    __slots__ = ("kind", "value", "line")

    def __init__(self, kind, value, line):
        self.kind = kind
        self.value = value
        self.line = line

    def __repr__(self):
        return f"Token({self.kind}, {self.value!r}, L{self.line})"


class JSSyntaxError(SyntaxError):
    pass


def tokenize(src: str) -> list[Token]:
    tokens: list[Token] = []
    i, n, line = 0, len(src), 1
    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if src.startswith("//", i):
            j = src.find("\n", i)
            i = n if j < 0 else j
            continue
        if src.startswith("/*", i):
            j = src.find("*/", i + 2)
            if j < 0:
                raise JSSyntaxError(f"unterminated comment at line {line}")
            line += src.count("\n", i, j)
            i = j + 2
            continue
        if c in "'\"":
            j, buf = i + 1, []
            while j < n and src[j] != c:
                if src[j] == "\\":
                    esc = src[j + 1]
                    buf.append({
                        "n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
                        "\\": "\\", "'": "'", '"': '"', "0": "\0", "/": "/",
                    }.get(esc, esc) if esc != "u" else chr(int(src[j + 2 : j + 6], 16)))
                    j += 6 if esc == "u" else 2
                else:
                    if src[j] == "\n":
                        raise JSSyntaxError(f"newline in string at line {line}")
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise JSSyntaxError(f"unterminated string at line {line}")
            tokens.append(Token("str", "".join(buf), line))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            if src.startswith("0x", i) or src.startswith("0X", i):
                j = i + 2
                while j < n and src[j] in "0123456789abcdefABCDEF":
                    j += 1
                tokens.append(Token("num", float(int(src[i:j], 16)), line))
            else:
                while j < n and (src[j].isdigit() or src[j] == "."):
                    j += 1
                if j < n and src[j] in "eE":
                    j += 1
                    if j < n and src[j] in "+-":
                        j += 1
                    while j < n and src[j].isdigit():
                        j += 1
                tokens.append(Token("num", float(src[i:j]), line))
            i = j
            continue
        if c.isalpha() or c in "_$":
            j = i
            while j < n and (src[j].isalnum() or src[j] in "_$"):
                j += 1
            word = src[i:j]
            tokens.append(Token("kw" if word in KEYWORDS else "name", word, line))
            i = j
            continue
        if c == "/" and _regex_allowed(tokens):
            j, in_class = i + 1, False
            while j < n:
                ch = src[j]
                if ch == "\\":
                    j += 2
                    continue
                if ch == "[":
                    in_class = True
                elif ch == "]":
                    in_class = False
                elif ch == "/" and not in_class:
                    break
                elif ch == "\n":
                    raise JSSyntaxError(f"unterminated regex at line {line}")
                j += 1
            if j >= n:
                raise JSSyntaxError(f"unterminated regex at line {line}")
            body = src[i + 1 : j]
            j += 1
            k = j
            while k < n and src[k].isalpha():
                k += 1
            tokens.append(Token("regex", (body, src[j:k]), line))
            i = k
            continue
        for p in PUNCT:
            if src.startswith(p, i):
                tokens.append(Token("punct", p, line))
                i += len(p)
                break
        else:
            raise JSSyntaxError(f"unexpected character {c!r} at line {line}")
    tokens.append(Token("eof", None, line))
    return tokens


def _regex_allowed(tokens: list[Token]) -> bool:
    """A '/' starts a regex literal when the previous token cannot end an
    expression (start of input, operators, '(', ',', 'return', ...)."""
    if not tokens:
        return True
    t = tokens[-1]
    if t.kind in ("num", "str", "name", "regex"):
        return False
    if t.kind == "kw":
        return t.value not in ("this", "true", "false", "null", "undefined")
    return t.value not in (")", "]", "}", "++", "--")


# ---------------------------------------------------------------------------
# parser — AST nodes are tuples: (kind, ...)

ASSIGN_OPS = {"=", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^="}
# binding powers for binary operators
BP = {
    "||": 4, "&&": 5, "|": 6, "^": 7, "&": 8,
    "==": 9, "!=": 9, "===": 9, "!==": 9,
    "<": 10, ">": 10, "<=": 10, ">=": 10, "instanceof": 10, "in": 10,
    "<<": 11, ">>": 11,
    "+": 12, "-": 12,
    "*": 13, "/": 13, "%": 13,
}


class Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.pos = 0

    def peek(self, off=0) -> Token:
        return self.toks[min(self.pos + off, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def expect(self, value) -> Token:
        t = self.next()
        if t.value != value:
            raise JSSyntaxError(
                f"expected {value!r}, got {t.value!r} at line {t.line}"
            )
        return t

    def at(self, value) -> bool:
        return self.peek().value == value and self.peek().kind in ("punct", "kw")

    def eat(self, value) -> bool:
        if self.at(value):
            self.next()
            return True
        return False

    # -- statements ---------------------------------------------------------

    def parse_program(self):
        body = []
        while self.peek().kind != "eof":
            body.append(self.statement())
        return ("program", body)

    def statement(self):
        t = self.peek()
        if t.kind == "punct" and t.value == "{":
            return self.block()
        if t.kind == "punct" and t.value == ";":
            self.next()
            return ("empty",)
        if t.kind == "kw":
            v = t.value
            if v in ("var", "let", "const"):
                decl = self.var_decl()
                self.semicolon()
                return decl
            if v == "function":
                return self.function_decl()
            if v == "if":
                return self.if_stmt()
            if v == "for":
                return self.for_stmt()
            if v == "while":
                self.next()
                self.expect("(")
                cond = self.expression()
                self.expect(")")
                return ("while", cond, self.statement())
            if v == "do":
                self.next()
                body = self.statement()
                self.expect("while")
                self.expect("(")
                cond = self.expression()
                self.expect(")")
                self.semicolon()
                return ("dowhile", cond, body)
            if v == "return":
                self.next()
                if self.at(";") or self.at("}") or self.peek().kind == "eof":
                    self.semicolon()
                    return ("return", None)
                e = self.expression()
                self.semicolon()
                return ("return", e)
            if v == "break":
                self.next()
                self.semicolon()
                return ("break",)
            if v == "continue":
                self.next()
                self.semicolon()
                return ("continue",)
            if v == "switch":
                return self.switch_stmt()
            if v == "try":
                return self.try_stmt()
            if v == "throw":
                self.next()
                e = self.expression()
                self.semicolon()
                return ("throw", e)
        e = self.expression()
        self.semicolon()
        return ("expr", e)

    def semicolon(self):
        # the assets end statements with ';'; tolerate '}' / eof (ASI-lite)
        if self.eat(";"):
            return
        if self.at("}") or self.peek().kind == "eof":
            return
        t = self.peek()
        raise JSSyntaxError(f"missing ';' before {t.value!r} at line {t.line}")

    def block(self):
        self.expect("{")
        body = []
        while not self.at("}"):
            body.append(self.statement())
        self.expect("}")
        return ("block", body)

    def var_decl(self):
        kind = self.next().value
        decls = []
        while True:
            name = self.ident()
            init = self.assignment() if self.eat("=") else None
            decls.append((name, init))
            if not self.eat(","):
                break
        return ("vardecl", kind, decls)

    def ident(self) -> str:
        t = self.next()
        if t.kind != "name":
            raise JSSyntaxError(f"expected identifier, got {t.value!r} at line {t.line}")
        return t.value

    def function_decl(self):
        self.expect("function")
        name = self.ident()
        params = self.param_list()
        body = self.block()
        return ("funcdecl", name, params, body)

    def param_list(self):
        self.expect("(")
        params = []
        while not self.at(")"):
            if self.at("["):  # array destructuring param
                params.append(("destructure", self.array_pattern()))
            else:
                params.append(("name", self.ident()))
            if not self.eat(","):
                break
        self.expect(")")
        return params

    def array_pattern(self):
        self.expect("[")
        names = []
        while not self.at("]"):
            names.append(self.ident())
            if not self.eat(","):
                break
        self.expect("]")
        return names

    def if_stmt(self):
        self.expect("if")
        self.expect("(")
        cond = self.expression()
        self.expect(")")
        then = self.statement()
        other = self.statement() if self.eat("else") else None
        return ("if", cond, then, other)

    def for_stmt(self):
        self.expect("for")
        self.expect("(")
        init = None
        if not self.at(";"):
            if self.peek().kind == "kw" and self.peek().value in ("var", "let", "const"):
                init = self.var_decl()
                # for-of?
                if self.at("of") or self.at("in"):
                    kind = self.next().value
                    iterable = self.expression()
                    self.expect(")")
                    body = self.statement()
                    name = init[2][0][0]
                    return ("forof" if kind == "of" else "forin", name, iterable, body)
            else:
                init = ("expr", self.expression())
        self.expect(";")
        cond = None if self.at(";") else self.expression()
        self.expect(";")
        update = None if self.at(")") else self.expression()
        self.expect(")")
        body = self.statement()
        return ("for", init, cond, update, body)

    def switch_stmt(self):
        self.expect("switch")
        self.expect("(")
        subject = self.expression()
        self.expect(")")
        self.expect("{")
        cases = []  # (test|None, [stmts])
        while not self.at("}"):
            if self.eat("case"):
                test = self.expression()
            else:
                self.expect("default")
                test = None
            self.expect(":")
            stmts = []
            while not (self.at("case") or self.at("default") or self.at("}")):
                stmts.append(self.statement())
            cases.append((test, stmts))
        self.expect("}")
        return ("switch", subject, cases)

    def try_stmt(self):
        self.expect("try")
        body = self.block()
        param, handler, final = None, None, None
        if self.eat("catch"):
            if self.eat("("):
                param = self.ident()
                self.expect(")")
            handler = self.block()
        if self.eat("finally"):
            final = self.block()
        return ("try", body, param, handler, final)

    # -- expressions --------------------------------------------------------

    def expression(self):
        e = self.assignment()
        while self.at(","):
            self.next()
            e = ("comma", e, self.assignment())
        return e

    def assignment(self):
        # arrow functions need lookahead: (params) => ... / name => ...
        arrow = self.try_arrow()
        if arrow is not None:
            return arrow
        left = self.conditional()
        t = self.peek()
        if t.kind == "punct" and t.value in ASSIGN_OPS:
            op = self.next().value
            right = self.assignment()
            if left[0] not in ("name", "member", "index"):
                raise JSSyntaxError(f"bad assignment target at line {t.line}")
            return ("assign", op, left, right)
        return left

    def try_arrow(self):
        start = self.pos
        t = self.peek()
        if t.kind == "name" and self.peek(1).value == "=>":
            name = self.ident()
            self.expect("=>")
            return self.arrow_body([("name", name)])
        if t.value == "(":
            # scan for the matching ')' followed by '=>'
            depth, j = 0, self.pos
            while j < len(self.toks):
                v = self.toks[j].value
                if v == "(":
                    depth += 1
                elif v == ")":
                    depth -= 1
                    if depth == 0:
                        break
                j += 1
            if j + 1 < len(self.toks) and self.toks[j + 1].value == "=>":
                params = self.param_list()
                self.expect("=>")
                return self.arrow_body(params)
            self.pos = start
        return None

    def arrow_body(self, params):
        if self.at("{"):
            return ("arrow", params, self.block())
        return ("arrow", params, ("return", self.assignment()))

    def conditional(self):
        cond = self.binary(0)
        if self.eat("?"):
            then = self.assignment()
            self.expect(":")
            other = self.assignment()
            return ("cond", cond, then, other)
        return cond

    def binary(self, min_bp):
        left = self.unary()
        while True:
            t = self.peek()
            op = t.value
            if (t.kind == "punct" or op in ("instanceof", "in")) and op in BP:
                bp = BP[op]
                if bp < min_bp:
                    break
                self.next()
                right = self.binary(bp + 1)
                left = ("bin", op, left, right)
                continue
            break
        return left

    def unary(self):
        t = self.peek()
        if t.kind == "punct" and t.value in ("!", "-", "+", "~"):
            self.next()
            return ("unary", t.value, self.unary())
        if t.kind == "punct" and t.value in ("++", "--"):
            self.next()
            target = self.unary()
            return ("update", t.value, target, True)
        if t.kind == "kw" and t.value in ("typeof", "void", "delete"):
            self.next()
            return ("unary", t.value, self.unary())
        if t.kind == "kw" and t.value == "new":
            self.next()
            callee = self.member_chain(self.primary(), allow_call=False)
            args = self.arguments() if self.at("(") else []
            return self.member_chain(("new", callee, args), allow_call=True)
        return self.postfix()

    def postfix(self):
        e = self.member_chain(self.primary(), allow_call=True)
        t = self.peek()
        if t.kind == "punct" and t.value in ("++", "--"):
            self.next()
            return ("update", t.value, e, False)
        return e

    def member_chain(self, e, allow_call):
        while True:
            if self.eat("."):
                e = ("member", e, self.prop_name())
            elif self.at("["):
                self.next()
                idx = self.expression()
                self.expect("]")
                e = ("index", e, idx)
            elif allow_call and self.at("("):
                e = ("call", e, self.arguments())
            else:
                return e

    def prop_name(self) -> str:
        t = self.next()
        if t.kind in ("name", "kw"):
            return t.value
        raise JSSyntaxError(f"expected property name at line {t.line}")

    def arguments(self):
        self.expect("(")
        args = []
        while not self.at(")"):
            if self.eat("..."):
                args.append(("spread", self.assignment()))
            else:
                args.append(self.assignment())
            if not self.eat(","):
                break
        self.expect(")")
        return args

    def primary(self):
        t = self.next()
        if t.kind == "num":
            return ("num", t.value)
        if t.kind == "str":
            return ("str", t.value)
        if t.kind == "regex":
            return ("regex", t.value[0], t.value[1])
        if t.kind == "name":
            return ("name", t.value)
        if t.kind == "kw":
            if t.value == "true":
                return ("bool", True)
            if t.value == "false":
                return ("bool", False)
            if t.value == "null":
                return ("null",)
            if t.value == "undefined":
                return ("undefined",)
            if t.value == "this":
                return ("this",)
            if t.value == "function":
                name = self.ident() if self.peek().kind == "name" else None
                params = self.param_list()
                body = self.block()
                return ("funcexpr", name, params, body)
            raise JSSyntaxError(f"unexpected keyword {t.value!r} at line {t.line}")
        if t.value == "(":
            e = self.expression()
            self.expect(")")
            return e
        if t.value == "[":
            items = []
            while not self.at("]"):
                if self.eat("..."):
                    items.append(("spread", self.assignment()))
                else:
                    items.append(self.assignment())
                if not self.eat(","):
                    break
            self.expect("]")
            return ("array", items)
        if t.value == "{":
            props = []
            while not self.at("}"):
                k = self.next()
                if k.kind == "str":
                    key = k.value
                elif k.kind in ("name", "kw"):
                    key = k.value
                elif k.kind == "num":
                    key = _num_to_key(k.value)
                else:
                    raise JSSyntaxError(f"bad object key at line {k.line}")
                if self.at("("):  # method shorthand
                    params = self.param_list()
                    body = self.block()
                    props.append((key, ("funcexpr", key, params, body)))
                elif self.eat(":"):
                    props.append((key, self.assignment()))
                else:  # property shorthand
                    props.append((key, ("name", key)))
                if not self.eat(","):
                    break
            self.expect("}")
            return ("object", props)
        raise JSSyntaxError(f"unexpected token {t.value!r} at line {t.line}")


def parse(src: str):
    return Parser(tokenize(src)).parse_program()


# ---------------------------------------------------------------------------
# runtime values

class JSUndefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


undefined = JSUndefined()


class JSObject:
    def __init__(self, props=None, proto=None):
        self.props = dict(props or {})
        self.proto = proto

    def get(self, key):
        o = self
        while o is not None:
            if key in o.props:
                return o.props[key]
            o = o.proto
        return undefined

    def set(self, key, value):
        self.props[key] = value

    def has(self, key):
        o = self
        while o is not None:
            if key in o.props:
                return True
            o = o.proto
        return False


class JSFunction:
    def __init__(self, name, params, body, env, interp, is_arrow=False,
                 this_val=None):
        self.name = name or ""
        self.params = params
        self.body = body
        self.env = env
        self.interp = interp
        self.is_arrow = is_arrow
        self.this_val = this_val  # captured lexically for arrows
        self.prototype = JSObject()

    def call(self, this, args):
        return self.interp.call_function(self, this, args)


class JSRegex:
    def __init__(self, body, flags):
        self.source = body
        self.flags = flags
        py = body  # JS character classes used by the assets map directly
        self.pattern = _re.compile(py)
        self.global_ = "g" in flags


class BreakSignal(Exception):
    pass


class ContinueSignal(Exception):
    pass


class ReturnSignal(Exception):
    def __init__(self, value):
        self.value = value


class JSThrow(Exception):
    def __init__(self, value):
        self.value = value
        super().__init__(repr(value))


class Environment:
    __slots__ = ("vars", "parent")

    def __init__(self, parent=None):
        self.vars = {}
        self.parent = parent

    def lookup(self, name):
        e = self
        while e is not None:
            if name in e.vars:
                return e.vars[name]
            e = e.parent
        raise JSThrow(f"ReferenceError: {name} is not defined")

    def set_existing(self, name, value) -> bool:
        e = self
        while e is not None:
            if name in e.vars:
                e.vars[name] = value
                return True
            e = e.parent
        return False

    def declare(self, name, value):
        self.vars[name] = value


def _num_to_key(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(v)


def js_truthy(v) -> bool:
    if v is undefined or v is None:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, float):
        return v != 0 and not _math.isnan(v)
    if isinstance(v, str):
        return len(v) > 0
    return True


def js_number(v) -> float:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, float):
        return v
    if v is None:
        return 0.0
    if v is undefined:
        return float("nan")
    if isinstance(v, str):
        s = v.strip()
        if not s:
            return 0.0
        try:
            return float(int(s, 16)) if s.lower().startswith("0x") else float(s)
        except ValueError:
            return float("nan")
    if isinstance(v, list):
        if not v:
            return 0.0
        if len(v) == 1:
            return js_number(v[0])
    return float("nan")


def js_string(v) -> str:
    if isinstance(v, str):
        return v
    if v is undefined:
        return "undefined"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if _math.isnan(v):
            return "NaN"
        if v == float("inf"):
            return "Infinity"
        if v == float("-inf"):
            return "-Infinity"
        if v.is_integer() and abs(v) < 1e21:
            return str(int(v))
        return repr(v)
    if isinstance(v, list):
        return ",".join("" if x is undefined or x is None else js_string(x) for x in v)
    if isinstance(v, JSFunction):
        return f"function {v.name}() {{ ... }}"
    if isinstance(v, JSObject):
        return "[object Object]"
    return str(v)


def strict_equals(a, b) -> bool:
    if a is undefined and b is undefined:
        return True
    if a is None and b is None:
        return True
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, float) and isinstance(b, float):
        return a == b
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    return a is b


def loose_equals(a, b) -> bool:
    if (a is undefined or a is None) and (b is undefined or b is None):
        return True
    if isinstance(a, (float, bool)) and isinstance(b, str):
        return js_number(a) == js_number(b)
    if isinstance(a, str) and isinstance(b, (float, bool)):
        return js_number(a) == js_number(b)
    if isinstance(a, bool) or isinstance(b, bool):
        if isinstance(a, bool):
            return loose_equals(js_number(a), b)
        return loose_equals(a, js_number(b))
    return strict_equals(a, b)


# ---------------------------------------------------------------------------
# interpreter

class Interp:
    def __init__(self):
        self.global_env = Environment()
        self.jobs: list = []  # promise reactions (microtask-ish queue)
        self.global_this = JSObject()

    # -- job queue (Promises, the harness drains it) ------------------------

    def enqueue_job(self, fn):
        self.jobs.append(fn)

    def run_jobs(self):
        while self.jobs:
            self.jobs.pop(0)()

    # -- program ------------------------------------------------------------

    def run(self, src: str, env: Environment | None = None):
        ast = parse(src)
        env = env or self.global_env
        self.hoist(ast[1], env)
        for stmt in ast[1]:
            self.exec_stmt(stmt, env, self.global_this)

    def hoist(self, body, env):
        for stmt in body:
            if stmt[0] == "funcdecl":
                _, name, params, fbody = stmt
                env.declare(name, JSFunction(name, params, fbody, env, self))
            elif stmt[0] == "vardecl" and stmt[1] == "var":
                for name, _ in stmt[2]:
                    if name not in env.vars:
                        env.declare(name, undefined)

    # -- statements ---------------------------------------------------------

    def exec_stmt(self, node, env, this):
        kind = node[0]
        if kind == "expr":
            self.eval(node[1], env, this)
        elif kind == "vardecl":
            for name, init in node[2]:
                value = undefined if init is None else self.eval(init, env, this)
                if node[1] == "var" and env.set_existing(name, value):
                    continue
                env.declare(name, value)
        elif kind == "funcdecl":
            if node[1] not in env.vars:
                env.declare(node[1], JSFunction(node[1], node[2], node[3], env, self))
        elif kind == "block":
            inner = Environment(env)
            self.hoist(node[1], inner)
            for s in node[1]:
                self.exec_stmt(s, inner, this)
        elif kind == "if":
            if js_truthy(self.eval(node[1], env, this)):
                self.exec_stmt(node[2], env, this)
            elif node[3] is not None:
                self.exec_stmt(node[3], env, this)
        elif kind == "while":
            while js_truthy(self.eval(node[1], env, this)):
                try:
                    self.exec_stmt(node[2], env, this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    continue
        elif kind == "dowhile":
            while True:
                try:
                    self.exec_stmt(node[2], env, this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    pass
                if not js_truthy(self.eval(node[1], env, this)):
                    break
        elif kind == "for":
            inner = Environment(env)
            init, cond, update, body = node[1], node[2], node[3], node[4]
            if init is not None:
                self.exec_stmt(init, inner, this)
            while cond is None or js_truthy(self.eval(cond, inner, this)):
                try:
                    self.exec_stmt(body, inner, this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    pass
                if update is not None:
                    self.eval(update, inner, this)
        elif kind == "forof":
            name, iterable, body = node[1], node[2], node[3]
            seq = self.eval(iterable, env, this)
            if isinstance(seq, str):
                items = list(seq)
            elif isinstance(seq, list):
                items = list(seq)
            else:
                raise JSThrow("TypeError: value is not iterable")
            for item in items:
                inner = Environment(env)
                inner.declare(name, item)
                try:
                    self.exec_stmt(body, inner, this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    continue
        elif kind == "forin":
            name, obj_e, body = node[1], node[2], node[3]
            obj = self.eval(obj_e, env, this)
            keys = (
                list(obj.props) if isinstance(obj, JSObject)
                else [str(i) for i in range(len(obj))] if isinstance(obj, list)
                else []
            )
            for key in keys:
                inner = Environment(env)
                inner.declare(name, key)
                try:
                    self.exec_stmt(body, inner, this)
                except BreakSignal:
                    break
                except ContinueSignal:
                    continue
        elif kind == "return":
            raise ReturnSignal(
                undefined if node[1] is None else self.eval(node[1], env, this)
            )
        elif kind == "break":
            raise BreakSignal()
        elif kind == "continue":
            raise ContinueSignal()
        elif kind == "switch":
            subject = self.eval(node[1], env, this)
            inner = Environment(env)
            matched = False
            try:
                for test, stmts in node[2]:
                    if not matched:
                        if test is None:
                            matched = True
                        elif strict_equals(subject, self.eval(test, inner, this)):
                            matched = True
                    if matched:
                        for s in stmts:
                            self.exec_stmt(s, inner, this)
                if not matched:  # run default (JS runs it even if mid-list)
                    run = False
                    for test, stmts in node[2]:
                        if test is None:
                            run = True
                        if run:
                            for s in stmts:
                                self.exec_stmt(s, inner, this)
            except BreakSignal:
                pass
        elif kind == "try":
            _, body, param, handler, final = node
            try:
                self.exec_stmt(body, env, this)
            except JSThrow as exc:
                if handler is not None:
                    inner = Environment(env)
                    if param:
                        inner.declare(param, exc.value)
                    self.exec_stmt(handler, inner, this)
                elif final is None:
                    raise
            finally:
                if final is not None:
                    self.exec_stmt(final, env, this)
        elif kind == "throw":
            raise JSThrow(self.eval(node[1], env, this))
        elif kind == "empty":
            pass
        else:
            raise JSSyntaxError(f"unknown statement {kind}")

    # -- expressions --------------------------------------------------------

    def eval(self, node, env, this):
        kind = node[0]
        if kind == "num":
            return node[1]
        if kind == "str":
            return node[1]
        if kind == "bool":
            return node[1]
        if kind == "null":
            return None
        if kind == "undefined":
            return undefined
        if kind == "this":
            return this
        if kind == "name":
            try:
                return env.lookup(node[1])
            except JSThrow:
                # browser semantics: window IS the global object, so props
                # assigned to it (global.api = ...) resolve as bare names
                if self.global_this.has(node[1]):
                    return self.global_this.get(node[1])
                raise
        if kind == "regex":
            return JSRegex(node[1], node[2])
        if kind == "array":
            out = []
            for item in node[1]:
                if item[0] == "spread":
                    out.extend(self.eval(item[1], env, this))
                else:
                    out.append(self.eval(item, env, this))
            return out
        if kind == "object":
            obj = JSObject()
            for key, value_e in node[1]:
                obj.set(key, self.eval(value_e, env, this))
            return obj
        if kind == "funcexpr":
            return JSFunction(node[1], node[2], node[3], env, self)
        if kind == "arrow":
            return JSFunction(None, node[1], node[2], env, self,
                              is_arrow=True, this_val=this)
        if kind == "cond":
            return (
                self.eval(node[2], env, this)
                if js_truthy(self.eval(node[1], env, this))
                else self.eval(node[3], env, this)
            )
        if kind == "comma":
            self.eval(node[1], env, this)
            return self.eval(node[2], env, this)
        if kind == "bin":
            return self.eval_binary(node, env, this)
        if kind == "unary":
            return self.eval_unary(node, env, this)
        if kind == "update":
            return self.eval_update(node, env, this)
        if kind == "assign":
            return self.eval_assign(node, env, this)
        if kind == "member":
            obj = self.eval(node[1], env, this)
            return self.get_prop(obj, node[2])
        if kind == "index":
            obj = self.eval(node[1], env, this)
            key = self.eval(node[2], env, this)
            return self.get_index(obj, key)
        if kind == "call":
            return self.eval_call(node, env, this)
        if kind == "new":
            return self.eval_new(node, env, this)
        raise JSSyntaxError(f"unknown expression {kind}")

    def eval_binary(self, node, env, this):
        op = node[1]
        if op == "&&":
            left = self.eval(node[2], env, this)
            return left if not js_truthy(left) else self.eval(node[3], env, this)
        if op == "||":
            left = self.eval(node[2], env, this)
            return left if js_truthy(left) else self.eval(node[3], env, this)
        a = self.eval(node[2], env, this)
        b = self.eval(node[3], env, this)
        return self.apply_binop(op, a, b)

    def apply_binop(self, op, a, b):
        if op == "+":
            if isinstance(a, str) or isinstance(b, str) or \
               isinstance(a, (list, JSObject)) or isinstance(b, (list, JSObject)):
                return js_string(a) + js_string(b)
            return js_number(a) + js_number(b)
        if op == "-":
            return js_number(a) - js_number(b)
        if op == "*":
            return js_number(a) * js_number(b)
        if op == "/":
            bn = js_number(b)
            an = js_number(a)
            if bn == 0:
                if an == 0 or _math.isnan(an):
                    return float("nan")
                return float("inf") if (an > 0) == (bn >= 0) else float("-inf")
            return an / bn
        if op == "%":
            bn = js_number(b)
            an = js_number(a)
            if bn == 0 or _math.isnan(an) or _math.isnan(bn):
                return float("nan")
            return float(_math.fmod(an, bn))
        if op == "===":
            return strict_equals(a, b)
        if op == "!==":
            return not strict_equals(a, b)
        if op == "==":
            return loose_equals(a, b)
        if op == "!=":
            return not loose_equals(a, b)
        if op in ("<", ">", "<=", ">="):
            if isinstance(a, str) and isinstance(b, str):
                pass
            else:
                a, b = js_number(a), js_number(b)
                if _math.isnan(a) or _math.isnan(b):
                    return False
            return {"<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b}[op]
        if op in ("&", "|", "^", "<<", ">>"):
            ai, bi = _to_int32(a), _to_int32(b)
            if op == "&":
                r = ai & bi
            elif op == "|":
                r = ai | bi
            elif op == "^":
                r = ai ^ bi
            elif op == "<<":
                r = ai << (bi & 31)
            else:
                r = ai >> (bi & 31)
            return float(_wrap_int32(r))
        if op == "instanceof":
            if isinstance(b, JSFunction) and isinstance(a, JSObject):
                proto = a.proto
                while proto is not None:
                    if proto is b.prototype:
                        return True
                    proto = proto.proto
            return False
        if op == "in":
            key = js_string(a)
            if isinstance(b, JSObject):
                return b.has(key)
            if isinstance(b, list):
                return key.isdigit() and int(key) < len(b)
            return False
        raise JSSyntaxError(f"unknown operator {op}")

    def eval_unary(self, node, env, this):
        op = node[1]
        if op == "typeof":
            try:
                v = self.eval(node[2], env, this)
            except JSThrow:
                return "undefined"
            if v is undefined:
                return "undefined"
            if v is None:
                return "object"
            if isinstance(v, bool):
                return "boolean"
            if isinstance(v, float):
                return "number"
            if isinstance(v, str):
                return "string"
            if isinstance(v, JSFunction) or callable(v):
                return "function"
            return "object"
        v = self.eval(node[2], env, this)
        if op == "!":
            return not js_truthy(v)
        if op == "-":
            return -js_number(v)
        if op == "+":
            return js_number(v)
        if op == "~":
            return float(_wrap_int32(~_to_int32(v)))
        if op == "void":
            return undefined
        if op == "delete":
            return True
        raise JSSyntaxError(f"unknown unary {op}")

    def eval_update(self, node, env, this):
        _, op, target, prefix = node
        old = js_number(self.eval(target, env, this))
        new = old + (1 if op == "++" else -1)
        self.assign_to(target, new, env, this)
        return new if prefix else old

    def eval_assign(self, node, env, this):
        _, op, target, value_e = node
        value = self.eval(value_e, env, this)
        if op != "=":
            current = self.eval(target, env, this)
            value = self.apply_binop(op[:-1], current, value)
        self.assign_to(target, value, env, this)
        return value

    def assign_to(self, target, value, env, this):
        if target[0] == "name":
            if not env.set_existing(target[1], value):
                self.global_env.declare(target[1], value)
        elif target[0] == "member":
            obj = self.eval(target[1], env, this)
            self.set_prop(obj, target[2], value)
        elif target[0] == "index":
            obj = self.eval(target[1], env, this)
            key = self.eval(target[2], env, this)
            self.set_index(obj, key, value)
        else:
            raise JSSyntaxError("bad assignment target")

    # -- property access ----------------------------------------------------

    def get_prop(self, obj, name):
        try:
            from . import jsstdlib  # package import (tests)
        except ImportError:
            import jsstdlib  # script/CLI import

        return jsstdlib.get_member(self, obj, name)

    def set_prop(self, obj, name, value):
        if isinstance(obj, JSObject):
            obj.set(name, value)
        elif isinstance(obj, JSFunction):
            if name == "prototype":
                obj.prototype = value
            else:
                setattr(obj, "js_" + name, value)
        elif isinstance(obj, list) and name == "length":
            n = int(js_number(value))
            del obj[n:]
        else:
            raise JSThrow(f"TypeError: cannot set {name} on {type(obj).__name__}")

    def get_index(self, obj, key):
        if isinstance(obj, list):
            if isinstance(key, float) and float(key).is_integer():
                i = int(key)
                return obj[i] if 0 <= i < len(obj) else undefined
        if isinstance(obj, str):
            if isinstance(key, float) and float(key).is_integer():
                i = int(key)
                return obj[i] if 0 <= i < len(obj) else undefined
        return self.get_prop(obj, js_string(key))

    def set_index(self, obj, key, value):
        if isinstance(obj, list) and isinstance(key, float) and key.is_integer():
            i = int(key)
            while len(obj) <= i:
                obj.append(undefined)
            obj[i] = value
            return
        self.set_prop(obj, js_string(key), value)

    # -- calls --------------------------------------------------------------

    def eval_call(self, node, env, this):
        _, callee, arg_nodes = node
        args = []
        for a in arg_nodes:
            if a[0] == "spread":
                args.extend(self.eval(a[1], env, this))
            else:
                args.append(self.eval(a, env, this))
        if callee[0] == "member":
            obj = self.eval(callee[1], env, this)
            fn = self.get_prop(obj, callee[2])
            return self.invoke(fn, obj, args, name=callee[2])
        if callee[0] == "index":
            obj = self.eval(callee[1], env, this)
            key = js_string(self.eval(callee[2], env, this))
            fn = self.get_prop(obj, key)
            return self.invoke(fn, obj, args, name=key)
        fn = self.eval(callee, env, this)
        return self.invoke(fn, undefined, args)

    def invoke(self, fn, this, args, name="(anonymous)"):
        if isinstance(fn, JSFunction):
            return fn.call(this, args)
        if callable(fn):
            return fn(this, args)
        raise JSThrow(f"TypeError: {name} is not a function")

    def call_function(self, fn: JSFunction, this, args):
        env = Environment(fn.env)
        if fn.is_arrow:
            this = fn.this_val
        for i, p in enumerate(fn.params):
            value = args[i] if i < len(args) else undefined
            if p[0] == "name":
                env.declare(p[1], value)
            else:  # array destructuring
                seq = value if isinstance(value, list) else []
                for j, nm in enumerate(p[1]):
                    env.declare(nm, seq[j] if j < len(seq) else undefined)
        env.declare("arguments", list(args))
        body = fn.body
        try:
            if body[0] == "block":
                self.hoist(body[1], env)
                for stmt in body[1]:
                    self.exec_stmt(stmt, env, this)
            else:  # arrow expression body: ('return', expr)
                self.exec_stmt(body, env, this)
        except ReturnSignal as r:
            return r.value
        return undefined

    def eval_new(self, node, env, this):
        _, callee_e, arg_nodes = node
        fn = self.eval(callee_e, env, this)
        args = []
        for a in arg_nodes:
            if a[0] == "spread":
                args.extend(self.eval(a[1], env, this))
            else:
                args.append(self.eval(a, env, this))
        if isinstance(fn, JSFunction):
            proto = fn.prototype if isinstance(fn.prototype, JSObject) else JSObject()
            obj = JSObject(proto=proto)
            result = fn.call(obj, args)
            return result if isinstance(result, (JSObject, list)) else obj
        if callable(fn):  # host constructor
            return fn(None, args)
        raise JSThrow("TypeError: not a constructor")


def _to_int32(v) -> int:
    n = js_number(v)
    if _math.isnan(n) or _math.isinf(n):
        return 0
    return _wrap_int32(int(n))


def _wrap_int32(i: int) -> int:
    i &= 0xFFFFFFFF
    return i - 0x100000000 if i >= 0x80000000 else i


# ---------------------------------------------------------------------------
# CLI: parse-check files (the jshint analog)

def main(argv=None) -> int:
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] == "--check":
        args = args[1:]
    failed = 0
    for path in args:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        try:
            parse(src)
            print(f"{path}: OK")
        except JSSyntaxError as exc:
            failed += 1
            print(f"{path}: SYNTAX ERROR: {exc}")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
