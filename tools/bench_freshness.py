"""Freshness-plane overhead check (ISSUE 16): the full --freshness plane —
per-batch lineage records opened at featurize, FIFO-matched through
dispatch, enriched at delivery, folded into the watermark/percentile
windows, publish-lag stamps drained per stats tick — measured against a
``--freshness off`` control in the per-batch-telemetry regime (the regime
where per-batch host costs bind; BENCHMARKS.md).

Arms (interleaved single passes + paired per-round ratios, the house
method — tools/pairedbench.py):

- off   : ``freshness.configure(on=False)`` — every seam call no-ops, the
          exact HEAD hot path (the bit-parity arm);
- fresh : ``configure(on=True)`` + one lineage.open_batch per batch, the
          pipeline's own mark_dispatch at dispatch, and one
          record_delivery + periodic record_publish per delivered batch
          (the full delivered-batch cost of the plane).

Both arms dispatch the SAME model/program — the plane is host-side only
(zero added fetches, zero device traffic), so any delta is pure Python
bookkeeping. Passes the acceptance gate when the paired ratio (off/fresh)
is >= 0.97x (the ISSUE's <= 3% budget).

Usage: python tools/bench_freshness.py [--tweets N] [--batch B]
          [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget = 65536, 2048, 120.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.apps.common import FetchPipeline
    from twtml_tpu.features.batch import pack_batch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.telemetry import freshness as _freshness
    from twtml_tpu.telemetry import lineage as _lineage

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]
    r_batches = [
        feat.featurize_batch_ragged(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]
    # synthetic statuses carry created_at_ms=0 (no event time): stamp one
    # AFTER featurizing (features untouched) so the fresh arm pays the real
    # lag-fold cost — percentile windows, watermark floor, edge argmax
    for j, s in enumerate(statuses):
        s.created_at_ms = 1785320000000 + j

    def consume_off(out, b, t, at_boundary=True):
        float(out.count); float(out.mse)
        float(out.real_stdev); float(out.pred_stdev)
        _ = out.predictions[0]

    # stats ticks run every batch in the telemetry regime; drain the
    # publish-lag stamps at the same cadence the session publisher would
    def consume_fresh(out, b, t, at_boundary=True):
        consume_off(out, b, t, at_boundary)
        _freshness.record_delivery()
        _freshness.record_publish()

    model = StreamingLinearRegressionWithSGD()
    seen = set()
    for rb in r_batches:  # warm every packed layout both arms dispatch
        key = (rb.units.shape, str(rb.units.dtype), rb.row_len)
        if key not in seen:
            seen.add(key)
            float(model.step(pack_batch(rb)).mse)

    def run_pass(consume, open_lineage):
        model.reset()
        t0 = time.perf_counter()
        pipe = FetchPipeline(model, consume, depth=8, pack=True)
        for statuses_chunk, rb in zip(chunks, r_batches):
            if open_lineage:
                # the featurize-open seam (streaming/context.py); dispatch
                # marking rides FetchPipeline.on_batch itself
                _lineage.open_batch(statuses_chunk)
            pipe.on_batch(rb, 0.0)
        pipe.flush()
        return time.perf_counter() - t0

    def off_pass():
        _freshness.configure(on=False)
        return run_pass(consume_off, open_lineage=False)

    def fresh_pass():
        _freshness.reset_for_tests()  # fresh windows per pass
        _freshness.configure(on=True)
        return run_pass(consume_fresh, open_lineage=True)

    off_pass(); fresh_pass()  # warm both arms' code paths

    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )

    times = run_rounds({"off": off_pass, "fresh": fresh_pass}, budget)
    view = _freshness.last_freshness() or {}
    _freshness.configure(on=False)
    out = {
        "regime": "freshness-overhead", "batch": batch,
        "tweets": n_tweets, "backend": jax.default_backend(),
        "rounds": len(times["off"]),
        "last_event_lag_p95_ms": view.get("eventLagP95Ms", -1.0),
        "last_critical": view.get("critical", ""),
    }
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
        }
    out["fresh"]["paired_vs_off"] = paired_ratio_median(
        times["off"], times["fresh"]
    )
    out["neutral"] = out["fresh"]["paired_vs_off"] >= 0.97
    print(json.dumps(out))


if __name__ == "__main__":
    main()
