"""jsmini browser environment: the DOM/net/timer stubs the dashboard uses.

Gives tools/jsmini.py enough browser to run the REAL shipped assets
(web/assets/js/*.js) in CI: a document whose elements are materialized from
the ``id="..."`` attributes of the REAL page HTML (so a counter id missing
from index.html fails the test, exactly like a browser), createElement /
appendChild / replaceChildren / textContent / classList, table insertRow /
insertCell (test.html's log), a 2d-canvas context that records draw calls,
controllable WebSocket and fetch stubs, setTimeout on a virtual clock, and
``window`` as the global object (bare ``api`` resolves through it, like a
browser global).
"""

from __future__ import annotations

import re as _re

try:
    from .jsmini import (
        Interp, JSObject, JSThrow, js_number, js_string, js_truthy, undefined,
    )
    from .jsstdlib import MiniPromise, install_globals, promise_resolved
except ImportError:  # script import
    from jsmini import (  # type: ignore
        Interp, JSObject, JSThrow, js_number, js_string, js_truthy, undefined,
    )
    from jsstdlib import (  # type: ignore
        MiniPromise, install_globals, promise_resolved,
    )


def _arg(args, i, default=undefined):
    return args[i] if i < len(args) else default


class Element(JSObject):
    def __init__(self, harness, tag: str, el_id: str = ""):
        super().__init__()
        self.harness = harness
        self.tag = tag.lower()
        self.el_id = el_id
        self.children: list[Element] = []
        self.listeners: dict[str, list] = {}
        self.class_set: set[str] = set()
        self.rows: list[Element] = []  # table rows / row cells
        self.set("textContent", "")
        self.set("value", "")
        self.set("src", "")
        self.set("title", "")
        if self.tag == "canvas":
            self.set("clientWidth", 800.0)
            self.set("clientHeight", 360.0)
            self.set("width", 0.0)
            self.set("height", 0.0)
            self.ctx = CanvasContext()
        self._install_methods()

    def _install_methods(self):
        self.set("appendChild", lambda this, args: self._append(_arg(args, 0)))
        self.set("replaceChildren", lambda this, args: self._replace(list(args)))
        self.set("addEventListener", lambda this, args: self._listen(
            js_string(_arg(args, 0)), _arg(args, 1)
        ))
        self.set("insertRow", lambda this, args: self._insert_row(
            int(js_number(_arg(args, 0, 0.0)))
        ))
        self.set("insertCell", lambda this, args: self._insert_cell())
        if self.tag == "canvas":
            self.set("getContext", lambda this, args: self.ctx)
        cl = JSObject({
            "toggle": lambda this, args: self._class_toggle(args),
            "add": lambda this, args: self.class_set.update(
                {js_string(a) for a in args}
            ) or undefined,
            "remove": lambda this, args: [
                self.class_set.discard(js_string(a)) for a in args
            ] and undefined or undefined,
            "contains": lambda this, args: js_string(_arg(args, 0)) in self.class_set,
        })
        self.set("classList", cl)

    def _append(self, child):
        self.children.append(child)
        return child

    def _replace(self, new_children):
        self.children = list(new_children)
        return undefined

    def _listen(self, event, fn):
        self.listeners.setdefault(event, []).append(fn)
        return undefined

    def _insert_row(self, index):
        row = Element(self.harness, "tr")
        self.rows.insert(min(index, len(self.rows)), row)
        return row

    def _insert_cell(self):
        cell = Element(self.harness, "td")
        self.rows.append(cell)  # a row's cells live in its rows list
        return cell

    def _class_toggle(self, args):
        name = js_string(_arg(args, 0))
        if len(args) >= 2:
            force = js_truthy(args[1])  # JS coercion, not Python truthiness
            (self.class_set.add if force else self.class_set.discard)(name)
            return force
        if name in self.class_set:
            self.class_set.discard(name)
            return False
        self.class_set.add(name)
        return True

    # convenience for tests
    @property
    def text(self) -> str:
        return js_string(self.get("textContent"))

    def fire(self, interp: Interp, event: str, event_obj=None):
        ev = event_obj or JSObject({"target": self, "type": event})
        for fn in list(self.listeners.get(event, [])):
            interp.invoke(fn, undefined, [ev])


class CanvasContext(JSObject):
    """Records every draw call so tests can assert the chart actually drew."""

    def __init__(self):
        super().__init__()
        self.calls: list[tuple] = []
        for m in ("clearRect", "beginPath", "moveTo", "lineTo", "stroke",
                  "fillRect", "fillText"):
            self.set(m, self._recorder(m))
        self.set("measureText", lambda this, args: JSObject({"width": 40.0}))

    def _recorder(self, m):
        def record(this, args):
            self.calls.append((m, tuple(js_string(a) if isinstance(a, str)
                                        else a for a in args)))
            return undefined
        return record

    def ops(self, name=None):
        return [c for c in self.calls if name is None or c[0] == name]


class FakeWebSocket(JSObject):
    CONNECTING, OPEN, CLOSING, CLOSED = 0.0, 1.0, 2.0, 3.0

    def __init__(self, harness, url):
        super().__init__()
        self.harness = harness
        self.url = url
        self.sent: list[str] = []
        self.set("readyState", self.CONNECTING)
        self.set("send", lambda this, args: self.sent.append(
            js_string(_arg(args, 0))
        ) or undefined)
        self.set("close", lambda this, args: self.server_close())
        harness.websockets.append(self)

    def server_open(self):
        self.set("readyState", self.OPEN)
        self._emit("onopen")

    def server_close(self):
        self.set("readyState", self.CLOSED)
        self._emit("onclose")
        return undefined

    def server_message(self, text: str):
        ev = JSObject({"data": text})
        self._emit("onmessage", ev)

    def _emit(self, name, ev=None):
        fn = self.get(name)
        if fn is not undefined:
            self.harness.interp.invoke(
                fn, undefined, [ev or JSObject()]
            )
        self.harness.interp.run_jobs()


class Harness:
    """Load the real assets, provide the browser, drive events from tests."""

    def __init__(self, html_paths: list[str], seed: int = 0):
        self.interp = Interp()
        self.console = install_globals(self.interp, rng_seed=seed)
        self.elements: dict[str, Element] = {}
        self.websockets: list[FakeWebSocket] = []
        self.fetches: list[tuple[str, JSObject | None]] = []  # (url, opts)
        self.fetch_routes: dict[str, object] = {}  # url -> python value/callable
        self.timers: list[tuple[float, object]] = []
        self._timer_id = 0
        self.doc_listeners: dict[str, list] = {}

        window = self.interp.global_this
        env = self.interp.global_env
        env.declare("window", window)
        env.declare("globalThis", window)

        for path in html_paths:
            with open(path, encoding="utf-8") as fh:
                html = fh.read()
            for tag, el_id in _re.findall(
                r"<(\w+)[^>]*?\bid=\"([^\"]+)\"", html
            ):
                self.elements[el_id] = Element(self, tag, el_id)

        document = JSObject({
            "getElementById": lambda this, args: self.elements.get(
                js_string(_arg(args, 0)), None
            ),
            "createElement": lambda this, args: Element(
                self, js_string(_arg(args, 0))
            ),
            "addEventListener": lambda this, args: self.doc_listeners.setdefault(
                js_string(_arg(args, 0)), []
            ).append(_arg(args, 1)) or undefined,
        })
        window.set("document", document)
        env.declare("document", document)

        location = JSObject({"protocol": "http:", "host": "localhost:8888"})
        window.set("location", location)
        env.declare("location", location)

        class WSCtor(JSObject):
            def __call__(ws_self, this, args):  # noqa: N805
                return FakeWebSocket(self, js_string(_arg(args, 0)))

        ws_ctor = WSCtor({
            "CONNECTING": 0.0, "OPEN": 1.0, "CLOSING": 2.0, "CLOSED": 3.0,
        })
        window.set("WebSocket", ws_ctor)
        env.declare("WebSocket", ws_ctor)

        def fetch(this, args):
            url = js_string(_arg(args, 0))
            opts = _arg(args, 1, None)
            self.fetches.append((url, opts if isinstance(opts, JSObject) else None))
            route = self.fetch_routes.get(url)
            if route is None:
                p = MiniPromise(self.interp)
                p._settle("rejected", "TypeError: fetch failed: " + url)
                return p
            if isinstance(route, DeferredRoute):
                return route.promise
            body = route() if callable(route) else route
            return promise_resolved(self.interp, self._response(body))

        window.set("fetch", fetch)
        env.declare("fetch", fetch)

        def set_timeout(this, args):
            fn = _arg(args, 0)
            delay = js_number(_arg(args, 1, 0.0))
            self._timer_id += 1
            self.timers.append((delay, fn, float(self._timer_id)))
            return float(self._timer_id)

        def clear_timeout(this, args):
            tid = js_number(_arg(args, 0, -1.0))
            self.timers = [t for t in self.timers if t[2] != tid]
            return undefined

        env.declare("setTimeout", set_timeout)
        env.declare("clearTimeout", clear_timeout)
        window.set("setTimeout", set_timeout)

    # -- fetch plumbing -----------------------------------------------------

    def _response(self, body) -> JSObject:
        return JSObject({
            "ok": True,
            "status": 200.0,
            "json": lambda t, a: promise_resolved(self.interp, _py_to_js(body)),
            "text": lambda t, a: promise_resolved(
                self.interp, js_string(_py_to_js(body))
            ),
        })

    def defer(self, url: str) -> "DeferredRoute":
        """Register a route whose response the TEST resolves later — lets a
        test interleave websocket frames with an in-flight fetch (the
        Series-backfill ordering contract)."""
        route = DeferredRoute(self)
        self.fetch_routes[url] = route
        return route

    # -- loading ------------------------------------------------------------

    def load_script(self, path: str):
        with open(path, encoding="utf-8") as fh:
            self.interp.run(fh.read())
        self.interp.run_jobs()

    # -- event drivers ------------------------------------------------------

    def dom_content_loaded(self):
        for fn in self.doc_listeners.get("DOMContentLoaded", []):
            self.interp.invoke(fn, undefined, [JSObject()])
        self.interp.run_jobs()

    def click(self, el_id: str):
        self.elements[el_id].fire(self.interp, "click")
        self.interp.run_jobs()

    def run_timers(self):
        """Fire every pending timer once (the 5s reconnect etc.)."""
        due, self.timers = self.timers, []
        for _delay, fn, _tid in due:
            self.interp.invoke(fn, undefined, [])
        self.interp.run_jobs()

    @property
    def ws(self) -> FakeWebSocket:
        return self.websockets[-1]

    def el(self, el_id: str) -> Element:
        return self.elements[el_id]


class DeferredRoute:
    def __init__(self, harness: Harness):
        self.harness = harness
        self.promise = MiniPromise(harness.interp)

    def resolve(self, body):
        self.promise._settle("fulfilled", self.harness._response(body))
        self.harness.interp.run_jobs()

    def reject(self, reason="fetch failed"):
        self.promise._settle("rejected", reason)
        self.harness.interp.run_jobs()


def _py_to_js(v):
    try:
        from .jsstdlib import _from_python
    except ImportError:
        from jsstdlib import _from_python  # type: ignore

    return _from_python(v)
