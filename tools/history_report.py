"""Reconstruct a run's long-horizon story from the telemetry historian's
leftovers (telemetry/historian.py) — no live process needed: point it at a
``--history`` segment directory (a SIGKILLed run's included; torn tails are
skipped by the CRC scan, never an error) or at a crash flight-recorder
bundle whose ``history`` tail the blackbox folded in.

Renders the questions fourteen cpu-only windows carried: per-metric
sparkline table (RSS / fetch RTT / per-tick stage cost), healthy/degraded
phase intervals from the persisted classifier transitions, the hours-scale
least-squares RSS slope (the soak gate's estimator over any run's
leftovers), per-phase trend medians, and run-over-run per-stage deltas
against the ``--perfGuard`` baseline stamped at the previous clean
shutdown.

Everything rendered was already ON DISK — this tool adds zero
instrumentation (the ISSUE 20 law: observability at zero added fetches).

Exit status is a CHECK, the sibling contract to tools/postmortem_report.py
and tools/freshness_report.py: 0 = a readable history (segments with at
least one valid record, or a well-formed bundle); 2 = malformed/empty.
``--json`` emits the summary as one machine-readable line.

Usage: python tools/history_report.py HISTORY_DIR_OR_BUNDLE.json [--json]
"""

from __future__ import annotations

import json
import os
import sys

try:  # runnable both as a module and as a script
    from tools.postmortem_report import MalformedBundle, load_bundle
    from twtml_tpu.telemetry import historian as _historian
except ImportError:  # pragma: no cover - script mode from repo root
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from tools.postmortem_report import MalformedBundle, load_bundle
    from twtml_tpu.telemetry import historian as _historian

SPARK_CHARS = "▁▂▃▄▅▆▇█"
SPARK_WIDTH = 48


def sparkline(values) -> str:
    vals = [float(v) for v in values][-SPARK_WIDTH:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        SPARK_CHARS[int((v - lo) / span * (len(SPARK_CHARS) - 1))]
        for v in vals
    )


def _bundle_records(doc: dict) -> "list[dict]":
    """Synthesize a record stream from a bundle's historian tail (the same
    shape read_series yields, so every derivation below is shared)."""
    hist = doc.get("history") or {}
    records: "list[dict]" = []
    for t_ms, phase in hist.get("transitions", []):
        records.append({"k": "p", "t_ms": int(t_ms), "phase": phase})
    for s in hist.get("samples", []):
        rec = dict(s)
        rec["k"] = "s"
        records.append(rec)
    records.sort(key=lambda r: r.get("t_ms", 0))
    if hist.get("run_id") is not None and records:
        records.insert(0, {
            "k": "r", "t_ms": records[0].get("t_ms", 0),
            "run_id": hist["run_id"],
            "fingerprint": hist.get("fingerprint", ""),
        })
    return records


def _load_baseline(path: "str | None") -> "dict | None":
    if not path:
        return None
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        if isinstance(doc, dict) and isinstance(doc.get("stages_ms"), dict):
            return doc
    except Exception:
        pass
    return None


def summarize(records: "list[dict]",
              baseline: "dict | None" = None) -> dict:
    samples = [r for r in records if r.get("k") == "s"]
    runs = [
        {"run_id": r.get("run_id"), "fingerprint": r.get("fingerprint", ""),
         "t_ms": r.get("t_ms")}
        for r in records if r.get("k") == "r"
    ]
    trends = _historian.phase_trends(records)
    healthy = trends.get("healthy", {}).get("stages_ms", {})
    deltas = {}
    if baseline:
        for stage, base_ms in sorted(baseline.get("stages_ms", {}).items()):
            cur = healthy.get(stage)
            if cur is None or base_ms <= 0:
                continue
            deltas[stage] = {
                "baseline_ms": base_ms,
                "current_ms": cur,
                "ratio": round(cur / base_ms, 3),
            }
    span_ms = (
        samples[-1]["t_ms"] - samples[0]["t_ms"] if len(samples) > 1 else 0
    )
    return {
        "records": len(records),
        "samples": len(samples),
        "span_minutes": round(span_ms / 60000.0, 2),
        "runs": runs,
        "phase_intervals": _historian.phase_intervals(records),
        "rss_slope_mb_per_min": round(_historian.rss_slope(records), 4),
        "trends": trends,
        "series": {
            "rss_mb": [s.get("rss_mb", 0.0) for s in samples],
            "rtt_ms": [s.get("rtt_ms", 0.0) for s in samples],
            "stage_ms": [
                round(sum(s.get("stages_ms", {}).values()), 2)
                for s in samples
            ],
        },
        "baseline": baseline,
        "baseline_deltas": deltas,
    }


def render(s: dict) -> str:
    out = [
        f"telemetry history — {s['samples']} sample(s) over "
        f"{s['span_minutes']:.1f} min ({s['records']} records)"
    ]
    for run in s["runs"]:
        out.append(
            f"  run {run['run_id']}  config {run['fingerprint'] or '?'}"
        )
    out.append("  series (oldest → newest):")
    for name, unit in (
        ("rss_mb", "MB"), ("rtt_ms", "ms"), ("stage_ms", "ms/tick")
    ):
        vals = s["series"][name]
        last = f"{vals[-1]:.1f} {unit}" if vals else "—"
        out.append(f"    {name:<10} {sparkline(vals):<{SPARK_WIDTH}} {last}")
    out.append(
        f"  host RSS slope (least squares): "
        f"{s['rss_slope_mb_per_min']:.3f} MB/min"
    )
    if s["phase_intervals"]:
        out.append("  tunnel health phases:")
        for iv in s["phase_intervals"]:
            mins = (iv["end_ms"] - iv["start_ms"]) / 60000.0
            out.append(
                f"    {iv['phase']:<9} {mins:7.1f} min  "
                f"{iv['samples']:>5} sample(s)"
            )
    for phase, t in sorted(s["trends"].items()):
        out.append(
            f"  {phase} medians: rtt {t['rtt_ms']:.1f} ms  "
            f"rss {t['rss_mb']:.0f} MB  rows/s {t['rows_per_s']:.0f}"
        )
        for stage, ms in t["stages_ms"].items():
            out.append(f"    {stage:<14} {ms:>9.3f} ms/tick")
    if s["baseline"]:
        out.append(
            f"  perfGuard baseline: run {s['baseline'].get('run_id', '?')} "
            f"({s['baseline'].get('samples', 0)} healthy samples)"
        )
        for stage, d in s["baseline_deltas"].items():
            flag = "  <-- regressed" if d["ratio"] > 1.5 else ""
            out.append(
                f"    {stage:<14} {d['baseline_ms']:>9.3f} -> "
                f"{d['current_ms']:>9.3f} ms/tick  "
                f"({d['ratio']:.2f}x){flag}"
            )
    return "\n".join(out)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    if len(args) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    target = args[0]
    baseline = None
    if os.path.isdir(target):
        records = _historian.read_series(target)
        if not records:
            print(
                f"history_report: no CRC-valid historian records in "
                f"{target}", file=sys.stderr,
            )
            return 2
        baseline = _load_baseline(
            os.path.join(target, _historian.BASELINE_NAME)
        )
    else:
        try:
            doc = load_bundle(target)
        except (OSError, MalformedBundle) as exc:
            print(f"history_report: malformed bundle: {exc}",
                  file=sys.stderr)
            return 2
        records = _bundle_records(doc)
        if not records:
            print(
                "history_report: bundle has no historian tail (the run "
                "predates the historian or ran with --history off)",
                file=sys.stderr,
            )
            return 2
        hist = doc.get("history") or {}
        baseline = hist.get("baseline")
    summary = summarize(records, baseline)
    if as_json:
        print(json.dumps(summary))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
