"""Multi-tenant model plane vs M sequential single-tenant pipelines, paired.

The regime the plane exists for: M scenario models (per-topic / per-language
/ per-A/B-arm) with per-batch telemetry. Today that costs M full pipelines —
M featurize passes, M wires, M dispatches, and above all M host fetches at
~70–100 ms RTT each (the r2 law). The tenant stack routes one shared stream
into M models inside ONE jit program with ONE stacked stats fetch per tick.

Arms (single passes round-robin in one budget window on the shared
tools/pairedbench.py harness; PAIRED per-round ratios are the verdict —
sequential arm blocks confound with the tunnel's ~10-minute health phases):

- seq{M}   : M sequential single-tenant passes — pass m featurizes the full
             stream, keeps tenant m's routed rows, and steps its own model
             with a per-batch stats fetch (today's cost of M scenarios:
             M × (featurize + wire + dispatch + fetch));
- mt{M}    : the multi-tenant plane — ONE featurize pass, host routing, one
             stacked wire, one dispatch and ONE stacked fetch per tick
             (TenantStackModel, --wirePack stacked);
- mt{M}_group: same with the coalesced one-buffer tenant wire
             (--wirePack group — the pack_ragged_group reuse).

Both arms deliver every tenant's per-batch stats to the same consume() so
the handler work matches; aggregate tweets/s = stream tweets per wall
second with ALL M tenants served.

``--modelRttMs R`` (default 0) sleeps R ms inside EVERY host fetch of both
arms — a modeled stand-in for the tunnel's measured ~70–100 ms fetch RTT on
backends where fetches are free (the CPU control), so the amortization
mechanism is demonstrable off-tunnel. Results with it are labeled
``modeled_rtt_ms`` and are NEVER a tunnel-regime verdict (the r2/r3 law:
measure in the target regime before shipping) — the first tunnel window
should run this tool with the flag at 0.

Usage: python tools/bench_tenants.py [--tenants M] [--tweets N] [--batch B]
       [--budget S] [--modelRttMs R]   — prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget, m_tenants = 65536, 2048, 180.0, 8
    model_rtt_ms = 0.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        elif args[i] == "--tenants":
            m_tenants = int(args[i + 1]); i += 2
        elif args[i] == "--modelRttMs":
            model_rtt_ms = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax
    import numpy as np

    from twtml_tpu.features.batch import (
        split_batch_tenants, tenant_route_keys,
    )
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.parallel import TenantStackModel
    from twtml_tpu.streaming.sources import SyntheticSource

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [
        statuses[i : i + batch] for i in range(0, len(statuses), batch)
    ]

    def fetch(out):
        # the ONE host fetch per tick, optionally RTT-modeled (see banner)
        host = jax.device_get(out)
        if model_rtt_ms > 0:
            time.sleep(model_rtt_ms / 1e3)
        return host

    def consume(out):
        # per-tenant per-batch handler work, identical in every arm
        float(np.asarray(out.count).sum())
        float(np.asarray(out.mse).sum())

    # ---- sequential arm: M single-tenant pipelines ------------------------
    seq_model = StreamingLinearRegressionWithSGD()

    def featurize(chunk):
        return feat.featurize_batch_ragged(
            chunk, row_bucket=batch, pre_filtered=True
        )

    def seq_pass():
        t0 = time.perf_counter()
        for m in range(m_tenants):
            seq_model.reset()
            for chunk in chunks:
                rb = featurize(chunk)
                part = split_batch_tenants(
                    rb, tenant_route_keys(rb, m_tenants), m_tenants
                )[m]
                consume(fetch(seq_model.step(part)))
        return time.perf_counter() - t0

    # ---- multi-tenant arms ------------------------------------------------
    mt = TenantStackModel(m_tenants, wire_pack="stacked")
    mt_group = TenantStackModel(m_tenants, wire_pack="group")

    def mt_pass(model):
        model.reset()
        t0 = time.perf_counter()
        for chunk in chunks:
            consume(fetch(model.step(featurize(chunk))))
        return time.perf_counter() - t0

    # warm every program (compile + completion fetch outside the window)
    warm = featurize(chunks[0])
    consume(jax.device_get(seq_model.step(
        split_batch_tenants(
            warm, tenant_route_keys(warm, m_tenants), m_tenants
        )[0]
    )))
    consume(jax.device_get(mt.step(warm)))
    consume(jax.device_get(mt_group.step(warm)))

    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )

    arms = {
        f"seq{m_tenants}": seq_pass,
        f"mt{m_tenants}": lambda: mt_pass(mt),
        f"mt{m_tenants}_group": lambda: mt_pass(mt_group),
    }
    times = run_rounds(arms, budget)

    out = {
        "regime": "multi-tenant-telemetry",
        "tenants": m_tenants,
        "batch": batch,
        "tweets": n_tweets,
        "backend": jax.default_backend(),
        "modeled_rtt_ms": model_rtt_ms,
        "rounds": len(times[f"seq{m_tenants}"]),
    }
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
        }
    # the acceptance ratio: M tenants served by one plane vs M sequential
    # single-tenant pipelines, paired per round
    out[f"mt{m_tenants}"]["paired_speedup_vs_seq"] = paired_ratio_median(
        times[f"seq{m_tenants}"], times[f"mt{m_tenants}"]
    )
    out[f"mt{m_tenants}_group"]["paired_speedup_vs_seq"] = (
        paired_ratio_median(
            times[f"seq{m_tenants}"], times[f"mt{m_tenants}_group"]
        )
    )
    out[f"mt{m_tenants}_group"]["paired_vs_stacked"] = paired_ratio_median(
        times[f"mt{m_tenants}"], times[f"mt{m_tenants}_group"]
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
