"""Chaos soak: drive the flagship replay app back-to-back under the
transport fault injector (streaming/faults.ChaosInjector) and assert the
runtime guards hold up over time — the app-level companion of the unit
chaos tests (tests/test_chaos.py) and the endurance soaks (tools/soak.py).

Each round replays the same synthetic corpus through the full linear app
(FetchPipeline, checkpoints, telemetry) with chaos active on all three
injection points: fetch latency spikes + occasional fetch errors (the
watchdog's re-issue path), dispatch delays, and a flaky dashboard (the
publish circuit breaker's open/half-open cycle — the twtweb endpoint is a
closed port, so un-dropped publishes also fail fast). The run must
SURVIVE: every round trains the full corpus, counters prove the guards
fired (retries > 0, breaker failures > 0), and zero fetch aborts occur.

r7 adds a SOURCE-chaos phase (--sourcePhase, on by default: the budget
splits between the two phases): block-ingest rounds under source.garbage
(corrupt wire bytes the parser must skip-and-count), source.burst (rate
spikes into the bounded intake queue), and source.nan (poisoned labels →
the divergence sentinel's rollback-to-verified-checkpoint path). The
contract is survive-and-recover: every round completes, rollbacks fire
and RECOVER (no sentinel abort, no fetch abort), all three rules fire,
and row losses show up in counters (rows_lost / rows_dropped_parse /
rows_shed) — never silently.

r21 adds a JOURNAL phase (--journalPhase, on by default): one
poisoned-batch storm with the durable intake journal ON against a clean
no-chaos control over the same corpus, pinned clock. The sentinel's
rollback must land as a journal REPLAY — replayed rows > 0, zero rows
lost, zero torn tails — and the storm's final weights must be BIT-EQUAL
to the unfailed control's (crash-equals-clean, ISSUE 19). The source
phase inherits the same contract: its rollbacks must replay, not count
losses.

r20 adds a FLEET phase (--fleetPhase, on by default): one lead-kill
election storm through tools/chaos_fleet.py — ``--fleetHosts`` real
lockstep worker processes, the launch lead hard-killed mid-run, the
survivors expected to elect the deterministic successor, re-form, and
finish clean with fleet-agreeing resync CRCs and counted losses. The
storm's violated invariants fold into this soak's ``failures``.

On ANY invariant failure the soak collects the crash flight recorder's
post-mortem bundle (telemetry/blackbox.py — the apps install it per round)
into ``--artifactDir`` and prints its path, so a CI chaos failure is
diagnosable after the fact instead of being a dead stdout log.

Usage: python tools/chaos_soak.py [--minutes M] [--tweets N] [--chaos SPEC]
          [--sourceChaos SPEC] [--sourcePhase on|off]
          [--fleetPhase on|off] [--fleetHosts N] [--journalPhase on|off]
          [--artifactDir DIR]
Prints one JSON line at the end; exits non-zero on any violated invariant.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# survivable defaults: delays well under the fetch deadline, errors rare
# enough that the retry budget (3) never exhausts on one batch, a mostly
# dead dashboard to cycle the breaker through open/half-open/probe.
# Triggers sized to the default round (16384 tweets / 2048 = 8 batches —
# each round re-installs the injector, resetting its call counters).
DEFAULT_CHAOS = (
    "fetch:delay=0.5@5,fetch:error@7,step:delay=0.1@3,"
    "web:error@p0.8,seed=3"
)

# source-phase defaults: one poisoned batch per round (16384/2048 = 8
# batches; @6 lands mid-round after several verified checkpoint saves), a
# corrupted parse chunk, and a block-duplication burst into the bounded
# queue. All three are survivable by design: the sentinel rolls back and
# continues, the parser skips and counts, the queue blocks the producer.
DEFAULT_SOURCE_CHAOS = (
    "source.nan@6,source.garbage@4,source.burst:rows=1@5,seed=3"
)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    minutes, n_tweets, chaos = 10.0, 16384, DEFAULT_CHAOS
    source_chaos, source_phase = DEFAULT_SOURCE_CHAOS, True
    fleet_phase, fleet_hosts = True, 2
    journal_phase = True
    artifact_dir = ""
    i = 0
    while i < len(args):
        if args[i] == "--minutes":
            minutes = float(args[i + 1]); i += 2
        elif args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--chaos":
            chaos = args[i + 1]; i += 2
        elif args[i] == "--sourceChaos":
            source_chaos = args[i + 1]; i += 2
        elif args[i] == "--sourcePhase":
            source_phase = args[i + 1] == "on"; i += 2
        elif args[i] == "--fleetPhase":
            fleet_phase = args[i + 1] == "on"; i += 2
        elif args[i] == "--journalPhase":
            journal_phase = args[i + 1] == "on"; i += 2
        elif args[i] == "--fleetHosts":
            fleet_hosts = int(args[i + 1]); i += 2
        elif args[i] == "--artifactDir":
            artifact_dir = args[i + 1]; i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    from tools.bench_suite import _status_json
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.telemetry import metrics as _metrics

    tmp = tempfile.mkdtemp(prefix="chaos-soak-")
    replay = os.path.join(tmp, "tweets.jsonl")
    with open(replay, "w") as fh:
        for s in SyntheticSource(
            total=n_tweets, seed=5, base_ms=1785320000000
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"  # closed port: fails fast when attempted
    conf_args = [
        "--source", "replay", "--replayFile", replay,
        "--seconds", "0", "--batchBucket", "2048", "--tokenBucket", "512",
        "--checkpointDir", os.path.join(tmp, "ck"), "--checkpointEvery", "4",
        "--lightning", closed, "--twtweb", closed,
        "--webTimeout", "0.5",
        # the transport phase REUSES its checkpoint dir: each round
        # restores the last round's counters and re-reads the whole file
        # on top (the endurance ledger below checks per-round deltas).
        # With the journal on, boot replay would correctly fast-forward
        # past the fully-journaled corpus and train 0 rows — so this
        # phase pins --journal off, which doubles as soak coverage for
        # the off path under transport chaos (the journal's own contract
        # has its dedicated phase)
        "--journal", "off",
        "--chaos", chaos,
    ]

    transport_s = minutes * 60.0 * (0.5 if source_phase else 1.0)
    deadline = time.time() + transport_s
    rounds, tweets, failures = 0, 0, []
    t0 = time.time()
    while time.time() < deadline:
        totals = app.run(ConfArguments().parse(list(conf_args)))
        rounds += 1
        # counters resume from the checkpoint each round, so check deltas
        if totals["count"] - tweets != n_tweets:
            failures.append(
                f"round {rounds} trained {totals['count'] - tweets} "
                f"of {n_tweets} tweets"
            )
            break
        tweets = totals["count"]

    # -- source-chaos phase (r7): block ingest + garbage/burst/nan -------
    from twtml_tpu.streaming import faults as _faults

    src_rounds, src_rollbacks = 0, 0
    if source_phase and not failures:
        _faults.uninstall_chaos()
        src_args = [
            "--source", "replay", "--replayFile", replay,
            "--ingest", "block",
            "--seconds", "0", "--batchBucket", "2048",
            "--tokenBucket", "512",
            "--maxQueueRows", str(4 * 2048),
            "--checkpointEvery", "2",
            "--lightning", closed, "--twtweb", closed,
            "--webTimeout", "0.5",
            "--chaos", source_chaos,
        ]
        deadline = time.time() + minutes * 60.0 * 0.5
        reg0 = _metrics.get_registry()
        while time.time() < deadline:
            # a FRESH checkpoint dir per round: the journal (on — the
            # sentinel's replay conversion is this phase's invariant now)
            # makes a reused dir an exact resume, which would correctly
            # train 0 new rows on round 2 — each round stands alone
            ck_src = os.path.join(tmp, f"ck-src-{src_rounds}")
            try:
                totals = app.run(ConfArguments().parse(
                    src_args + ["--checkpointDir", ck_src]
                ))
            except RuntimeError as exc:
                failures.append(
                    f"source-chaos round {src_rounds + 1} aborted: {exc}"
                )
                break
            src_rounds += 1
            if totals["count"] <= 0:
                failures.append(
                    f"source-chaos round {src_rounds} made no progress"
                )
                break
        snap = reg0.snapshot()["counters"]
        src_rollbacks = snap.get("model.rollbacks", 0)
        if src_rounds:
            if not src_rollbacks:
                failures.append("source.nan never drove a sentinel rollback")
            if snap.get("model.sentinel_aborts", 0):
                failures.append("sentinel aborted under survivable chaos")
            for rule in ("source.nan", "source.garbage", "source.burst"):
                if not snap.get(f"chaos.{rule}.injected", 0):
                    failures.append(f"{rule} never fired")
            # the sentinel's rollback is a REPLAY site now (ISSUE 19: the
            # intake journal is on — --checkpointDir implies --journal
            # auto), so a fired rollback must show replayed rows and ZERO
            # lost rows; garbled lines stay counted in rows_dropped_parse
            if not snap.get("journal.replayed_rows", 0):
                failures.append(
                    "rollbacks fired but journal.replayed_rows is 0 — "
                    "the rollback loss site stayed counted, not replayed"
                )
            if snap.get("model.rows_lost", 0):
                failures.append(
                    f"{snap['model.rows_lost']} row(s) lost to rollbacks "
                    "with the journal ON — recovery is not replay-exact"
                )
            if not snap.get("ingest.rows_dropped_parse", 0):
                failures.append(
                    "garbage fired but ingest.rows_dropped_parse is 0"
                )

    # -- journal phase (r21, ISSUE 19): crash-equals-clean ---------------
    # one poisoned-batch storm with the intake journal ON, against a
    # clean no-chaos control over the same corpus: the sentinel rollback
    # must convert into a journal replay (replayed rows > 0, ZERO rows
    # lost), and the storm's final weights must be BIT-EQUAL to the
    # control's — the whole crash-equals-clean contract in one
    # differential. The clock seam is pinned for the phase (featurize
    # freshness terms must match across the two runs).
    jr = {}
    if journal_phase and not failures:
        import numpy as np

        from twtml_tpu.checkpoint import Checkpointer

        _faults.uninstall_chaos()
        prior_now = os.environ.get("TWTML_NOW_MS")
        os.environ["TWTML_NOW_MS"] = "1785320000000"
        try:
            def jr_args(ck, spec):
                a = [
                    "--source", "replay", "--replayFile", replay,
                    "--seconds", "0", "--batchBucket", "2048",
                    "--tokenBucket", "512",
                    "--checkpointDir", os.path.join(tmp, ck),
                    "--checkpointEvery", "2",
                    "--lightning", closed, "--twtweb", closed,
                    "--webTimeout", "0.5",
                ]
                return a + (["--chaos", spec] if spec else [])

            before = _metrics.get_registry().snapshot()["counters"]
            storm = app.run(ConfArguments().parse(
                jr_args("ck-journal", "source.nan@6,seed=3")
            ))
            _faults.uninstall_chaos()
            after = _metrics.get_registry().snapshot()["counters"]
            clean = app.run(ConfArguments().parse(
                jr_args("ck-journal-clean", "")
            ))
            jr = {
                "replayed_rows": after.get("journal.replayed_rows", 0)
                - before.get("journal.replayed_rows", 0),
                "rows_lost": after.get("model.rows_lost", 0)
                - before.get("model.rows_lost", 0),
                "torn_tails": after.get("journal.torn_tails", 0)
                - before.get("journal.torn_tails", 0),
            }
            if storm["count"] != n_tweets or clean["count"] != n_tweets:
                failures.append(
                    f"journal phase trained {storm['count']} (storm) / "
                    f"{clean['count']} (control) of {n_tweets} tweets"
                )
            if not jr["replayed_rows"]:
                failures.append(
                    "journal phase: the poisoned batch never replayed"
                )
            if jr["rows_lost"]:
                failures.append(
                    f"journal phase: {jr['rows_lost']} row(s) lost — "
                    "recovery is not replay-exact"
                )
            if jr["torn_tails"]:
                failures.append(
                    f"journal phase: {jr['torn_tails']} torn tail(s) on "
                    "clean shutdown/reopen"
                )
            w_storm, m_storm = Checkpointer(
                os.path.join(tmp, "ck-journal")
            ).restore()
            w_clean, m_clean = Checkpointer(
                os.path.join(tmp, "ck-journal-clean")
            ).restore()
            jr["bit_equal"] = bool(
                m_storm["count"] == m_clean["count"]
                and np.array_equal(np.asarray(w_storm), np.asarray(w_clean))
            )
            if not jr["bit_equal"]:
                failures.append(
                    "journal phase: storm weights are not bit-equal to "
                    "the unfailed control — crash-equals-clean violated"
                )
        finally:
            if prior_now is None:
                os.environ.pop("TWTML_NOW_MS", None)
            else:
                os.environ["TWTML_NOW_MS"] = prior_now

    # -- fleet phase (r20): lead-kill election storm, real processes -----
    # one storm, not time-budgeted (~90 s at 2 hosts): the launch lead is
    # hard-killed mid-run and the survivors must elect the deterministic
    # successor, re-form, and finish clean — the whole membership
    # contract is verified from the OUTSIDE by tools/chaos_fleet.py
    # (exit codes, epoch ladder, one winner, fleet-agreeing resync CRCs,
    # counted losses), so its failures fold straight into this soak's
    fleet_res = None
    if fleet_phase and not failures:
        from tools.chaos_fleet import run_storm
        fleet_res = run_storm(
            hosts=fleet_hosts, tweets=128 * fleet_hosts,
            workdir=os.path.join(tmp, "fleet"),
        )
        failures.extend(f"fleet: {f}" for f in fleet_res["failures"])

    reg = _metrics.get_registry().snapshot()
    counters = reg["counters"]
    aborts = counters.get("fetch.aborts", 0)
    retries = counters.get("fetch.retries", 0)
    injected = counters.get("chaos.injected", 0)
    fetch_errors = counters.get("chaos.fetch.errors", 0)
    breaker_failures = counters.get("publish.web.failures", 0)
    if aborts:
        failures.append(f"{aborts} fetch abort(s) under survivable chaos")
    if not injected:
        failures.append("chaos injector never fired")
    if fetch_errors and retries < fetch_errors:
        # every injected fetch error must have been absorbed by a re-issue
        failures.append(
            f"{fetch_errors} injected fetch error(s) but only "
            f"{retries} watchdog retries"
        )

    # on any violated invariant, collect the flight recorder's post-mortem
    # bundle into the artifact dir — aborted rounds already dumped at the
    # abort funnel; force=True captures the terminal state either way
    postmortem = ""
    if failures:
        from twtml_tpu.telemetry import blackbox as _blackbox

        path = _blackbox.dump(
            f"chaos-soak invariant failure: {failures[0]}",
            out_dir=artifact_dir or tmp, force=True,
        )
        if path:
            postmortem = path
            print(f"chaos-soak post-mortem bundle: {path}", file=sys.stderr)

    print(json.dumps({
        "mode": "chaos-soak",
        "postmortem": postmortem,
        "minutes": round((time.time() - t0) / 60.0, 2),
        "rounds": rounds,
        "tweets": tweets,
        "source_rounds": src_rounds,
        "source_chaos": source_chaos if source_phase else "",
        "fleet_hosts": fleet_hosts if fleet_phase else 0,
        "fleet_elections": fleet_res["elections"] if fleet_res else 0,
        "fleet_epochs": [m for _e, m in fleet_res["epochs"]]
        if fleet_res else [],
        "sentinel_rollbacks": src_rollbacks,
        "journal": jr,
        "journal_replayed_rows": counters.get("journal.replayed_rows", 0),
        "rows_lost": counters.get("model.rows_lost", 0),
        "rows_dropped_parse": counters.get("ingest.rows_dropped_parse", 0),
        "rows_shed": counters.get("ingest.rows_shed", 0),
        "chaos": chaos,
        "chaos_injected": injected,
        "fetch_retries": retries,
        "fetch_aborts": aborts,
        "publish_failures": breaker_failures,
        "publish_dropped": counters.get("publish.web.dropped", 0),
        "series_shed": counters.get("publish.series_shed", 0),
        "health": _metrics.get_health_monitor().summary(),
        "failures": failures,
        "ok": not failures,
    }))
    if failures:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
