"""Chaos soak: drive the flagship replay app back-to-back under the
transport fault injector (streaming/faults.ChaosInjector) and assert the
runtime guards hold up over time — the app-level companion of the unit
chaos tests (tests/test_chaos.py) and the endurance soaks (tools/soak.py).

Each round replays the same synthetic corpus through the full linear app
(FetchPipeline, checkpoints, telemetry) with chaos active on all three
injection points: fetch latency spikes + occasional fetch errors (the
watchdog's re-issue path), dispatch delays, and a flaky dashboard (the
publish circuit breaker's open/half-open cycle — the twtweb endpoint is a
closed port, so un-dropped publishes also fail fast). The run must
SURVIVE: every round trains the full corpus, counters prove the guards
fired (retries > 0, breaker failures > 0), and zero fetch aborts occur.

Usage: python tools/chaos_soak.py [--minutes M] [--tweets N] [--chaos SPEC]
Prints one JSON line at the end; exits non-zero on any violated invariant.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# survivable defaults: delays well under the fetch deadline, errors rare
# enough that the retry budget (3) never exhausts on one batch, a mostly
# dead dashboard to cycle the breaker through open/half-open/probe.
# Triggers sized to the default round (16384 tweets / 2048 = 8 batches —
# each round re-installs the injector, resetting its call counters).
DEFAULT_CHAOS = (
    "fetch:delay=0.5@5,fetch:error@7,step:delay=0.1@3,"
    "web:error@p0.8,seed=3"
)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    minutes, n_tweets, chaos = 10.0, 16384, DEFAULT_CHAOS
    i = 0
    while i < len(args):
        if args[i] == "--minutes":
            minutes = float(args[i + 1]); i += 2
        elif args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--chaos":
            chaos = args[i + 1]; i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    from tools.bench_suite import _status_json
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.telemetry import metrics as _metrics

    tmp = tempfile.mkdtemp(prefix="chaos-soak-")
    replay = os.path.join(tmp, "tweets.jsonl")
    with open(replay, "w") as fh:
        for s in SyntheticSource(
            total=n_tweets, seed=5, base_ms=1785320000000
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"  # closed port: fails fast when attempted
    conf_args = [
        "--source", "replay", "--replayFile", replay,
        "--seconds", "0", "--batchBucket", "2048", "--tokenBucket", "512",
        "--checkpointDir", os.path.join(tmp, "ck"), "--checkpointEvery", "4",
        "--lightning", closed, "--twtweb", closed,
        "--webTimeout", "0.5",
        "--chaos", chaos,
    ]

    deadline = time.time() + minutes * 60.0
    rounds, tweets, failures = 0, 0, []
    t0 = time.time()
    while time.time() < deadline:
        totals = app.run(ConfArguments().parse(list(conf_args)))
        rounds += 1
        # counters resume from the checkpoint each round, so check deltas
        if totals["count"] - tweets != n_tweets:
            failures.append(
                f"round {rounds} trained {totals['count'] - tweets} "
                f"of {n_tweets} tweets"
            )
            break
        tweets = totals["count"]

    reg = _metrics.get_registry().snapshot()
    counters = reg["counters"]
    aborts = counters.get("fetch.aborts", 0)
    retries = counters.get("fetch.retries", 0)
    injected = counters.get("chaos.injected", 0)
    fetch_errors = counters.get("chaos.fetch.errors", 0)
    breaker_failures = counters.get("publish.web.failures", 0)
    if aborts:
        failures.append(f"{aborts} fetch abort(s) under survivable chaos")
    if not injected:
        failures.append("chaos injector never fired")
    if fetch_errors and retries < fetch_errors:
        # every injected fetch error must have been absorbed by a re-issue
        failures.append(
            f"{fetch_errors} injected fetch error(s) but only "
            f"{retries} watchdog retries"
        )

    print(json.dumps({
        "mode": "chaos-soak",
        "minutes": round((time.time() - t0) / 60.0, 2),
        "rounds": rounds,
        "tweets": tweets,
        "chaos": chaos,
        "chaos_injected": injected,
        "fetch_retries": retries,
        "fetch_aborts": aborts,
        "publish_failures": breaker_failures,
        "publish_dropped": counters.get("publish.web.dropped", 0),
        "series_shed": counters.get("publish.series_shed", 0),
        "health": _metrics.get_health_monitor().summary(),
        "failures": failures,
        "ok": not failures,
    }))
    if failures:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
