"""Coalesced + pipelined serving vs naive per-request serving, paired.

The regime the serving plane exists for (ISSUE 9): query traffic against a
device-resident snapshot through a transport where a host fetch is a
~70-100 ms RTT-bound REQUEST (BENCHMARKS r2/r3). Naive per-request serving
pays that round trip PER QUERY; the plane coalesces requests into one
dispatch per batch and pipelines the result fetches at depth K (the measured
6.2x-at-depth-8 trick, ``apps/common.FetchPipeline``).

Arms (single passes round-robin in one budget window on the shared
tools/pairedbench.py harness; PAIRED per-round ratios are the verdict):

- naive     : one ServingPlane per-request — batch bucket = the request's
              rows, depth 1, no admission wait: every request is its own
              featurize + dispatch + synchronous fetch (today's cost of a
              query without the plane);
- pipelined : the shipped plane — ``--batchRows`` coalescing bucket,
              ``--serveMaxWaitMs``-style admission wait, depth-``--depth``
              pipelined fetches.

Both arms serve the SAME open-loop load: N requests of R rows each submitted
as fast as possible, a pass completes when every future resolves. Sustained
QPS = N / pass seconds; per-request latencies (submit -> resolve) pool into
p50/p95/p99. An open-loop burst makes the tail latencies queue-dominated —
that is the honest shape of a load test, and the bounded p99 is reported
as such.

``--modelRttMs R`` (default 70) additionally runs BOTH arms with R ms slept
inside every host fetch — the modeled stand-in for the tunnel's measured
fetch RTT on backends where fetches are free (the CPU control), so the
amortization mechanism is demonstrable off-tunnel. Modeled numbers are
labeled and are NEVER a tunnel-regime verdict (the r2/r3 law); the first
tunnel window should run this tool with ``--modelRttMs 0`` attached to the
TPU.

Usage: python tools/bench_serving.py [--requests N] [--rowsPerRequest R]
       [--batchRows B] [--depth K] [--budget S] [--modelRttMs MS]
       — prints one JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOW_MS = 1785320000000


def build_plane(snapshot, *, batch_rows, max_wait_ms, depth, rtt_ms,
                num_text_features=1000):
    """One serving plane arm; ``rtt_ms`` > 0 wraps its fetch with the
    modeled transport RTT (slept in the fetch pool, so depth-K arms
    pipeline the sleeps exactly as the real tunnel pipelines requests)."""
    import jax

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.serving.engine import PredictEngine
    from twtml_tpu.serving.plane import ServingPlane

    engine = PredictEngine(
        num_text_features=num_text_features,
        num_tenants=snapshot.num_tenants,
    )
    if rtt_ms > 0:
        def rtt_fetch(out, _get=jax.device_get, _s=rtt_ms / 1e3):
            host = _get(out)
            time.sleep(_s)
            return host

        engine.fetch_output = rtt_fetch
    plane = ServingPlane(
        snapshot,
        num_text_features=num_text_features,
        batch_rows=batch_rows,
        max_wait_ms=max_wait_ms,
        depth=depth,
        featurizer=Featurizer(now_ms=NOW_MS),
        engine=engine,
    )
    return plane.start()


def measure(requests: int = 96, rows_per_request: int = 16,
            batch_rows: int = 256, depth: int = 8, budget: float = 60.0,
            model_rtt_ms: float = 70.0) -> dict:
    import jax
    import numpy as np

    from tools.pairedbench import paired_ratio_median, run_rounds
    from twtml_tpu.serving.snapshot import ServingSnapshot
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=requests * rows_per_request, seed=3).produce()
    )
    loads = [
        statuses[i * rows_per_request:(i + 1) * rows_per_request]
        for i in range(requests)
    ]
    rng = np.random.default_rng(7)
    weights = rng.standard_normal(1004).astype(np.float32) * 1e-3
    snapshot = ServingSnapshot(
        step=1, weights=weights, meta={"quality": {"level": "ok"}}
    )

    arm_specs = {
        "naive": dict(batch_rows=rows_per_request, max_wait_ms=0.0, depth=1,
                      rtt_ms=0.0),
        "pipelined": dict(batch_rows=batch_rows, max_wait_ms=5.0,
                          depth=depth, rtt_ms=0.0),
    }
    if model_rtt_ms > 0:
        arm_specs["naive_rtt"] = dict(
            batch_rows=rows_per_request, max_wait_ms=0.0, depth=1,
            rtt_ms=model_rtt_ms,
        )
        arm_specs["pipelined_rtt"] = dict(
            batch_rows=batch_rows, max_wait_ms=5.0, depth=depth,
            rtt_ms=model_rtt_ms,
        )
    planes = {
        name: build_plane(snapshot, **spec)
        for name, spec in arm_specs.items()
    }
    latencies: "dict[str, list[float]]" = {name: [] for name in planes}
    qps: "dict[str, list[float]]" = {name: [] for name in planes}

    def one_pass(name):
        plane = planes[name]
        lats = []
        t0 = time.perf_counter()
        futs = []
        for load in loads:
            t_sub = time.perf_counter()
            fut = plane.submit(load)
            fut.add_done_callback(
                lambda _f, t=t_sub: lats.append(time.perf_counter() - t)
            )
            futs.append(fut)
        for fut in futs:
            fut.result(timeout=600)
        dt = time.perf_counter() - t0
        latencies[name].extend(lats)
        qps[name].append(requests / dt)
        return dt

    # warm every arm outside the window (compile + first-bucket programs)
    for name in planes:
        one_pass(name)
    for d in (latencies, qps):
        for name in d:
            d[name].clear()

    arms = {name: (lambda n=name: one_pass(n)) for name in planes}
    times = run_rounds(arms, budget)

    def quantiles(values):
        vs = sorted(values)

        def q(p):
            return round(vs[min(len(vs) - 1, int(p * len(vs)))] * 1e3, 2)

        return {"p50_ms": q(0.50), "p95_ms": q(0.95), "p99_ms": q(0.99)}

    out = {
        "regime": "serving",
        "backend": jax.default_backend(),
        "requests": requests,
        "rows_per_request": rows_per_request,
        "batch_rows": batch_rows,
        "depth": depth,
        "modeled_rtt_ms": model_rtt_ms,
        "rounds": len(times["naive"]),
    }
    for name in planes:
        out[name] = {
            "qps_median": round(statistics.median(qps[name]), 1),
            "qps_best": round(max(qps[name]), 1),
            **quantiles(latencies[name]),
        }
    out["pipelined"]["paired_speedup_vs_naive"] = paired_ratio_median(
        times["naive"], times["pipelined"]
    )
    if model_rtt_ms > 0:
        out["pipelined_rtt"]["paired_speedup_vs_naive"] = paired_ratio_median(
            times["naive_rtt"], times["pipelined_rtt"]
        )
    for plane in planes.values():
        plane.stop()
    return out


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    kw = dict(requests=96, rows_per_request=16, batch_rows=256, depth=8,
              budget=60.0, model_rtt_ms=70.0)
    flags = {
        "--requests": ("requests", int),
        "--rowsPerRequest": ("rows_per_request", int),
        "--batchRows": ("batch_rows", int),
        "--depth": ("depth", int),
        "--budget": ("budget", float),
        "--modelRttMs": ("model_rtt_ms", float),
    }
    i = 0
    while i < len(args):
        if args[i] in flags:
            key, cast = flags[args[i]]
            kw[key] = cast(args[i + 1])
            i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")
    print(json.dumps(measure(**kw)))


if __name__ == "__main__":
    main()
