"""Render a crash flight-recorder bundle (telemetry/blackbox.py) into the
post-mortem summary an on-call engineer wants first: why the run died, what
the guards saw on the way down, which host was gating, and where the last
verified checkpoint is.

Exit status is a CHECK, exactly like tools/trace_report.py: 0 = a
well-formed bundle; 2 = malformed (missing required keys, unparseable JSON,
wrong kind). CI's post-mortem smoke step and tools/chaos_soak.py gate on
it. ``--json`` re-emits the validated summary as one machine-readable line.

Usage: python tools/postmortem_report.py BUNDLE.json [--json] [--events N]
"""

from __future__ import annotations

import json
import sys
from collections import Counter

try:  # runnable both as a module and as a script
    from twtml_tpu.telemetry.blackbox import BUNDLE_KIND, REQUIRED_KEYS
except ImportError:  # pragma: no cover - script mode from repo root
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from twtml_tpu.telemetry.blackbox import BUNDLE_KIND, REQUIRED_KEYS


class MalformedBundle(ValueError):
    pass


def load_bundle(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    if not text.strip():
        raise MalformedBundle("empty bundle file")
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as exc:
        raise MalformedBundle(f"not JSON: {exc}") from exc
    if not isinstance(doc, dict):
        raise MalformedBundle("bundle is not a JSON object")
    missing = [k for k in REQUIRED_KEYS if k not in doc]
    if missing:
        raise MalformedBundle(f"missing required keys: {missing}")
    if doc.get("kind") != BUNDLE_KIND:
        raise MalformedBundle(f"not a {BUNDLE_KIND} bundle: {doc.get('kind')!r}")
    if not isinstance(doc["events"], list):
        raise MalformedBundle("events is not a list")
    return doc


def summarize(doc: dict, tail_events: int = 12) -> dict:
    events = doc["events"]
    kinds = Counter(e.get("kind", "?") for e in events if isinstance(e, dict))
    counters = (doc.get("metrics") or {}).get("counters", {})
    guard_counters = {
        k: v for k, v in counters.items()
        if k.startswith((
            "fetch.retries", "fetch.aborts", "model.rollbacks",
            "model.sentinel_aborts", "lockstep.", "chaos.injected",
            "ingest.rows_shed", "trace.dropped_events",
        ))
    }
    hosts = doc.get("hosts") or {}
    history = doc.get("history") or {}
    hist_samples = history.get("samples") or []
    return {
        "reason": doc["reason"],
        "time_unix": doc["time_unix"],
        "process_index": doc.get("process_index", 0),
        "app": (doc.get("config") or {}).get("_appName")
        or (doc.get("config") or {}).get("appName", ""),
        "checkpoint": (doc.get("notes") or {}).get("last_checkpoint"),
        "events": len(events),
        "events_dropped": doc.get("events_dropped", 0),
        "event_kinds": dict(kinds),
        "guard_counters": guard_counters,
        "health": doc.get("health") or {},
        "straggler": {
            "host": hosts.get("straggler", -1),
            "stage": hosts.get("stage", ""),
            "skew_ms": hosts.get("skew_ms", 0.0),
        } if hosts else None,
        # the minutes BEFORE death (ISSUE 20): the historian tail the
        # blackbox folded in — RSS/RTT trajectory and phase flips leading
        # up to the crash, not just the event ring
        "history": {
            "run_id": history.get("run_id"),
            "samples": len(hist_samples),
            "transitions": len(history.get("transitions") or []),
            "rss_mb": [s.get("rss_mb", 0.0) for s in hist_samples],
            "rtt_ms": [s.get("rtt_ms", 0.0) for s in hist_samples],
            "last_phase": (
                hist_samples[-1].get("phase", "") if hist_samples else ""
            ),
        } if hist_samples else None,
        "tail": events[-tail_events:],
    }


def render(s: dict) -> str:
    out = [
        f"post-mortem: {s['reason']}",
        f"  process {s['process_index']}"
        + (f" · app {s['app']}" if s["app"] else "")
        + f" · t={s['time_unix']}",
        f"  last checkpoint: {s['checkpoint'] or '(none recorded)'}",
        f"  events in ring: {s['events']} (+{s['events_dropped']} dropped)",
    ]
    if s["event_kinds"]:
        kinds = ", ".join(
            f"{k}={v}" for k, v in sorted(s["event_kinds"].items())
        )
        out.append(f"  event kinds: {kinds}")
    if s["guard_counters"]:
        out.append("  guard counters:")
        for k, v in sorted(s["guard_counters"].items()):
            out.append(f"    {k} = {v}")
    health = s["health"]
    if health:
        out.append(
            f"  tunnel: {health.get('phase', '?')} "
            f"(rtt {health.get('rtt_ms', 0)} ms, "
            f"{health.get('transitions', 0)} transitions)"
        )
    if s["straggler"] and s["straggler"]["host"] >= 0:
        st = s["straggler"]
        out.append(
            f"  lockstep straggler: host {st['host']} · {st['stage']} "
            f"(tick skew {st['skew_ms']} ms)"
        )
    if s.get("history"):
        h = s["history"]
        rss = h["rss_mb"]
        rss_arc = (
            f"{rss[0]:.0f} -> {rss[-1]:.0f} MB" if rss else "?"
        )
        out.append(
            f"  history tail (run {h['run_id']}): {h['samples']} sample(s)"
            f" before death · rss {rss_arc} · last phase "
            f"{h['last_phase'] or '?'} · {h['transitions']} phase flip(s)"
            " — full timeline: tools/history_report.py on the bundle or"
            " the run's history directory"
        )
    out.append("  last events:")
    for ev in s["tail"]:
        kind = ev.get("kind", "?") if isinstance(ev, dict) else "?"
        rest = {
            k: v for k, v in ev.items() if k not in ("kind", "t")
        } if isinstance(ev, dict) else {}
        out.append(f"    [{ev.get('t', '?')}] {kind} {json.dumps(rest)[:120]}")
    return "\n".join(out)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    tail = 12
    if "--events" in args:
        i = args.index("--events")
        tail = int(args[i + 1])
        del args[i : i + 2]
    if len(args) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        summary = summarize(load_bundle(args[0]), tail_events=tail)
    except (OSError, MalformedBundle) as exc:
        print(f"postmortem_report: malformed bundle: {exc}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(summary))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
