"""Churn-proof elastic fleet storm driver (r20, ISSUE 17): launch an
N-host virtual lockstep fleet (CPU/gloo subprocesses of the real linear
app) under ONE fleet-wide ``--chaos`` spec — follower kills, LEAD kills,
sub-threshold pauses — and verify the elastic membership plane's whole
contract from the outside:

- exit codes: every ``peer.kill`` victim leaves with the chaos exit code
  (77), every survivor finishes clean — no aborts under survivable churn;
- epoch ladder: every reform's ``elastic epoch E formed`` line agrees
  across every member that logged it (one committed view per epoch);
- elections: each dead LEAD produces exactly one ``WON the election``
  winner fleet-wide (the deterministic successor — lowest live uid of the
  committed view — see streaming/membership.py);
- bit-matching continuations: every reform's resync CRC
  (``elastic resync: ... state crc``) is IDENTICAL on every member that
  joined that reform — the fleet restored the same verified bytes;
- counted losses: a killed replay-shard host's undeliverable rows show up
  in ``rows_lost_estimate`` on a survivor — never silent.

The driver is self-contained: it re-execs itself as the per-host worker
(``--worker``), so it needs nothing from tests/. The 8-host churn test
(tests/test_elastic_multiprocess.py, ``slow``) and the chaos-soak fleet
phase (tools/chaos_soak.py --fleetPhase) both drive ``run_storm``; CI's
election smoke runs the CLI's 2-host lead-kill default.

Usage: python tools/chaos_fleet.py [--hosts N] [--tweets T] [--chaos SPEC]
          [--workdir DIR] [--timeout S]
Prints one JSON line; exits non-zero on any violated invariant.
"""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOW_MS = 1785320000000
CLOSED = "http://127.0.0.1:9"  # closed port: telemetry Try paths, no DNS
PEER_KILL_EXIT_CODE = 77  # streaming/faults.py, asserted not imported:
# the driver must not import jax-adjacent modules before its workers fork

# the 2-host lead-kill smoke the CLI runs by default (CI election smoke):
# the launch lead dies at tick 4, the sole survivor must elect itself
DEFAULT_CHAOS = "peer.kill:uid=0:tick=4"


def _worker(argv: "list[str]") -> None:
    """Per-host entry (re-exec target): configure a CPU/gloo jax runtime
    sized by the driver, then run the REAL linear app with its own CLI —
    the same launch shape as tests/app_worker.py, owned by the tool."""
    pid, nprocs, port, ndev = (
        int(argv[0]), int(argv[1]), int(argv[2]), int(argv[3])
    )
    app_args = list(argv[5:])  # argv[4] is the app name ("linear")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from twtml_tpu.utils.backend import set_cpu_device_count_hint

    set_cpu_device_count_hint(ndev)
    app_args += [
        "--master", f"twtml://127.0.0.1:{port}",
        "--numProcesses", str(nprocs), "--processId", str(pid),
    ]
    from twtml_tpu.apps import linear_regression

    linear_regression.main(app_args)


def _free_port_range(span: int = 10) -> int:
    """A base port with ``span`` consecutive free ports: elastic reserves
    base (epoch-0 compat), base+1 (beacon), base+2+e (epoch e)."""
    for cand in range(29500, 61000, span + 3):
        socks, ok = [], True
        for off in range(span):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", cand + off))
                socks.append(s)
            except OSError:
                ok = False
                break
        for s in socks:
            s.close()
        if ok:
            return cand
    raise RuntimeError("no contiguous free port range found")


def _killed_uids(chaos: str, hosts: int) -> "list[int]":
    """The uids a fleet-wide ``--chaos`` spec hard-kills (peer.kill
    clauses; a selector-free kill takes the whole fleet)."""
    killed: "set[int]" = set()
    for clause in chaos.split(","):
        if not clause.strip().startswith("peer.kill"):
            continue
        m = re.search(r":uid=(\d+)", clause)
        killed.update([int(m.group(1))] if m else range(hosts))
    return sorted(killed)


def run_storm(
    hosts: int = 8,
    tweets: int = 1024,
    chaos: str = DEFAULT_CHAOS,
    workdir: "str | None" = None,
    batch_bucket: int = 16,
    token_bucket: int = 64,
    checkpoint_every: int = 2,
    ndev: int = 1,
    timeout_s: float = 600.0,
    seed: int = 5,
) -> dict:
    """Launch the fleet, apply the storm, collect and verify. Returns a
    result dict with ``ok``/``failures`` plus the parsed evidence (epoch
    ladder, election winners, per-reform CRC rounds, counted pauses)."""
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    workdir = workdir or tempfile.mkdtemp(prefix="chaos-fleet-")
    os.makedirs(workdir, exist_ok=True)
    replay = os.path.join(workdir, "tweets.jsonl")
    with open(replay, "w") as fh:
        for s in SyntheticSource(
            total=tweets, seed=seed, base_ms=NOW_MS
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")

    base = _free_port_range()
    env = dict(
        os.environ, PYTHONPATH=REPO, TWTML_NOW_MS=str(NOW_MS),
        TWTML_LOCKSTEP_TIMEOUT_S="5", TWTML_ELASTIC_RESCUE_GRACE_S="2",
        # a loaded box can delay the rank-0 candidate's bind past the
        # default 0.3s stagger and hand the election to a higher rank —
        # widen the per-rank window so the storm's winner is deterministic
        TWTML_ELASTIC_ELECT_STAGGER_S="1.0",
    )
    args = [
        "linear", "--source", "replay", "--replayFile", replay,
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", str(batch_bucket),
        "--tokenBucket", str(token_bucket),
        "--checkpointDir", os.path.join(workdir, "ck"),
        "--checkpointEvery", str(checkpoint_every),
        "--elastic", "on", "--lightning", CLOSED, "--twtweb", CLOSED,
        "--chaos", chaos,
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(i), str(hosts), str(base), str(ndev)] + args,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in range(hosts)
    ]
    outs, errs, rcs = [], [], []
    try:
        for p in procs:
            try:
                o, e = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                o, e = p.communicate()
                e += "\n[chaos_fleet] HOST TIMED OUT and was killed"
            outs.append(o)
            errs.append(e)
            rcs.append(p.returncode)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, e in enumerate(errs):
        with open(os.path.join(workdir, f"host-{i}.stderr"), "w") as fh:
            fh.write(e)

    killed = _killed_uids(chaos, hosts)
    failures: "list[str]" = []
    for uid, rc in enumerate(rcs):
        want = PEER_KILL_EXIT_CODE if uid in killed else 0
        if rc != want:
            failures.append(
                f"host {uid} exited {rc} (wanted {want}); tail: "
                f"{errs[uid][-500:]!r}"
            )

    # -- epoch ladder: one committed view per epoch, fleet-wide ----------
    per_epoch: "dict[int, set[str]]" = {}
    for e in errs:
        for num, members in re.findall(
            r"elastic epoch (\d+) formed: \d+ host\(s\) \[([^\]]*)\]", e
        ):
            per_epoch.setdefault(int(num), set()).add(members)
    epochs = []
    for num in sorted(per_epoch):
        views = per_epoch[num]
        if len(views) != 1:
            failures.append(f"epoch {num} formed with DIVERGENT views {views}")
        epochs.append(
            (num, [int(u) for u in next(iter(views)).split(",") if u.strip()])
        )

    # -- elections: one winner per dead lead, deterministic successor ----
    winners = [
        int(u) for e in errs for u in re.findall(r"uid (\d+) WON the election", e)
    ]
    expect_elections = 1 if 0 in killed else 0
    if len(winners) != expect_elections:
        failures.append(
            f"{len(winners)} election win(s) {winners} for "
            f"{expect_elections} dead lead(s)"
        )

    # -- bit-matching continuations: per-reform CRCs agree fleet-wide ----
    crc_per_host = [
        re.findall(r"elastic resync: .* state crc ([0-9a-f]+)", e)
        for e in errs
    ]
    rounds = max((len(c) for c in crc_per_host), default=0)
    crc_rounds = [
        [c[k] for c in crc_per_host if len(c) > k] for k in range(rounds)
    ]
    for k, crcs in enumerate(crc_rounds):
        if len(set(crcs)) != 1:
            failures.append(f"reform {k + 1} resync CRCs diverged: {crcs}")
    reforms = sum(1 for num, _m in epochs if num >= 1)  # epoch 0 is the
    # initial formation: it synchronizes state but logs no resync line
    if len(crc_rounds) < reforms:
        failures.append(
            f"{reforms} reform(s) but only {len(crc_rounds)} "
            f"resync round(s) logged"
        )

    # -- counted losses: a dead replay shard is never silently dropped --
    if killed and not any("rows_lost_estimate" in e for e in errs):
        failures.append(
            "hosts were killed but no survivor counted rows_lost_estimate"
        )

    # -- replay-exact reforms (ISSUE 19): on every reform, each survivor's
    # journal replay re-covers EXACTLY what the rescue threw away — its
    # own discarded in-flight rows plus its share of the rolled-back
    # post-checkpoint progress (global rows, evenly sharded across the
    # pre-reform members). The journal is on in every storm
    # (--checkpointDir implies --journal auto), so a missing replay line
    # means a loss site stayed counted instead of converted.
    replayed_rows = 0
    for uid, e in enumerate(errs):
        resyncs = re.findall(
            r"elastic resync: state from the lead's [a-z ]+ "
            r"\(count=\d+, batches=\d+, state crc [0-9a-f]+\)"
            r"(?: — (\d+) row\(s\) of post-checkpoint progress "
            r"rolled back)?", e,
        )
        replays = [
            int(r) for r in re.findall(
                r"journal: replayed (\d+) row\(s\) from cursor \d+ "
                r"after elastic", e,
            )
        ]
        resets = e.count("journal: reset on rejoin")
        if len(replays) + resets != len(resyncs):
            failures.append(
                f"host {uid}: {len(resyncs)} reform resync(s) but "
                f"{len(replays)} journal replay(s) + {resets} rejoin "
                f"reset(s) — a loss site stayed counted"
            )
            continue
        discarded = sum(
            int(r) for r in re.findall(
                r"elastic rescue: discarded \d+ in-flight.*?"
                r"\(~(\d+) row\(s\)\)", e,
            )
        )
        # each resync's rolled-back rows are global; this host's share is
        # 1/len(pre-reform members) (even synthetic shards, all-padding
        # ticks excluded from counts). epochs[k] is the view REFORM k+1
        # left — resync k's old view.
        rolled_share = sum(
            int(rolled or 0) // len(epochs[k][1])
            for k, rolled in enumerate(resyncs)
            if k < len(epochs)
        )
        if replays and sum(replays) != rolled_share + discarded:
            failures.append(
                f"host {uid}: replayed {sum(replays)} row(s) but the "
                f"rescue threw away {rolled_share + discarded} "
                f"(rolled share {rolled_share} + discarded {discarded}) "
                f"— recovery is not replay-exact"
            )
        replayed_rows += sum(replays)

    pauses = sum(e.count("chaos: peer.pause stalling") for e in errs)
    return {
        "mode": "chaos-fleet",
        "hosts": hosts,
        "tweets": tweets,
        "chaos": chaos,
        "workdir": workdir,
        "rcs": rcs,
        "killed": killed,
        "epochs": epochs,
        "elections": len(winners),
        "winners": winners,
        "crc_rounds": crc_rounds,
        "replayed_rows": replayed_rows,
        "pauses": pauses,
        "failures": failures,
        "ok": not failures,
    }


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] == "--worker":
        _worker(args[1:])
        return
    hosts, tweets, chaos = 2, 256, DEFAULT_CHAOS
    workdir, timeout_s = None, 600.0
    i = 0
    while i < len(args):
        if args[i] == "--hosts":
            hosts = int(args[i + 1]); i += 2
        elif args[i] == "--tweets":
            tweets = int(args[i + 1]); i += 2
        elif args[i] == "--chaos":
            chaos = args[i + 1]; i += 2
        elif args[i] == "--workdir":
            workdir = args[i + 1]; i += 2
        elif args[i] == "--timeout":
            timeout_s = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")
    res = run_storm(
        hosts=hosts, tweets=tweets, chaos=chaos, workdir=workdir,
        timeout_s=timeout_s,
    )
    print(json.dumps(res))
    if not res["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
