"""Render the freshness plane's story from a crash flight-recorder bundle
(telemetry/blackbox.py): how stale the data was end to end (event-time lag
percentiles from tweet ``created_at_ms`` to fetch delivery, the low
watermark), which seam-to-seam edge dominated the critical path, whether the
``--freshnessSloMs`` / ``--servingStaleSloS`` gates fired on the way down,
and how fast host RSS was growing — the "was the pipeline keeping up?"
post-mortem an on-call engineer asks first.

Everything rendered here was already IN the bundle: the freshness gauges and
critical-path counters ride the metrics-registry snapshot the recorder dumps,
and the SLO breach episodes are blackbox events — this tool adds zero
instrumentation, it only reads (the ISSUE 16 law: observability at zero
added fetches).

Exit status is a CHECK, exactly like tools/postmortem_report.py (whose
bundle validity contract is IMPORTED, so the two tools can never disagree
on well-formedness): 0 = a well-formed bundle, freshness telemetry present
or not; 2 = malformed. ``--json`` emits the summary as one machine-readable
line.

Usage: python tools/freshness_report.py BUNDLE.json [--json]
"""

from __future__ import annotations

import json
import sys

try:  # runnable both as a module and as a script
    from tools.postmortem_report import MalformedBundle, load_bundle
except ImportError:  # pragma: no cover - script mode from repo root
    import os

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from tools.postmortem_report import MalformedBundle, load_bundle

# blackbox event kinds the two SLO planes emit (telemetry/freshness.py,
# serving/plane.py) — the report's breach-episode tail filters on these
BREACH_KINDS = ("freshness_slo_breach", "serving_stale_breach")

CRITICAL_PREFIX = "freshness.critical."


def summarize(doc: dict, tail_events: int = 8) -> dict:
    metrics = doc.get("metrics") or {}
    gauges = metrics.get("gauges") or {}
    counters = metrics.get("counters") or {}
    hists = metrics.get("histograms") or {}
    critical = {
        k[len(CRITICAL_PREFIX):-len(".ticks")]: int(v)
        for k, v in counters.items()
        if k.startswith(CRITICAL_PREFIX) and k.endswith(".ticks")
    }
    breaches = [
        e for e in doc.get("events", [])
        if isinstance(e, dict) and e.get("kind") in BREACH_KINDS
    ]
    lag_hist = hists.get("freshness.event_lag_ms") or {}
    return {
        "reason": doc.get("reason", ""),
        "event_lag_p50_ms": gauges.get("freshness.event_lag_p50_ms"),
        "event_lag_p95_ms": gauges.get("freshness.event_lag_p95_ms"),
        "event_lag_p99_ms": gauges.get("freshness.event_lag_p99_ms"),
        "publish_lag_p95_ms": gauges.get("freshness.publish_lag_p95_ms"),
        "watermark_lag_ms": gauges.get("freshness.watermark_lag_ms"),
        "event_lag_batches": int(lag_hist.get("count", 0)),
        "critical_ticks": critical,
        "critical": max(critical, key=critical.get) if critical else "",
        "slo_breaches": int(counters.get("freshness.slo_breaches", 0)),
        "slo_checkpoints": int(counters.get("freshness.slo_checkpoints", 0)),
        "serving_stale_breaches": int(counters.get("serve.stale_breaches", 0)),
        "snapshot_age_s": gauges.get("serving.snapshot_age_s"),
        "ingest_event_lag_ms": gauges.get("ingest.event_time_lag_ms"),
        "rss_slope_mb_per_min": gauges.get("host.rss_slope_mb_per_min"),
        "breach_events": breaches[-tail_events:],
    }


def _ms(v) -> str:
    return "—" if v is None else f"{float(v):.0f} ms"


def render(summary: dict) -> str:
    out = [f"freshness post-mortem — run ended: {summary['reason'] or '?'}"]
    if summary["event_lag_p95_ms"] is None and not summary["critical_ticks"]:
        out.append(
            "  (no freshness telemetry in this bundle — the run predates the "
            "plane or ran with --freshness off)"
        )
        return "\n".join(out)
    out.append(
        "  event-time lag (created_at → delivery): "
        f"p50 {_ms(summary['event_lag_p50_ms'])}  "
        f"p95 {_ms(summary['event_lag_p95_ms'])}  "
        f"p99 {_ms(summary['event_lag_p99_ms'])}  "
        f"over {summary['event_lag_batches']} batches"
    )
    out.append(
        f"  low watermark lag: {_ms(summary['watermark_lag_ms'])}   "
        f"publish lag p95: {_ms(summary['publish_lag_p95_ms'])}"
    )
    if summary["critical_ticks"]:
        ticks = sorted(
            summary["critical_ticks"].items(), key=lambda kv: -kv[1]
        )
        total = sum(v for _, v in ticks) or 1
        out.append("  critical-path edges (batches dominated):")
        for edge, n in ticks:
            out.append(f"    {edge:<12} {n:>8}  ({100.0 * n / total:.0f}%)")
    out.append(
        f"  freshness SLO: {summary['slo_breaches']} breach episode(s), "
        f"{summary['slo_checkpoints']} forced checkpoint(s)"
    )
    if summary["snapshot_age_s"] is not None:
        out.append(
            f"  serving: snapshot age {float(summary['snapshot_age_s']):.1f} s, "
            f"{summary['serving_stale_breaches']} stale episode(s)"
        )
    if summary["ingest_event_lag_ms"] is not None:
        out.append(
            f"  ingest event-time lag (sampled): "
            f"{_ms(summary['ingest_event_lag_ms'])}"
        )
    if summary["rss_slope_mb_per_min"] is not None:
        out.append(
            f"  host RSS slope: "
            f"{float(summary['rss_slope_mb_per_min']):.2f} MB/min"
        )
    for e in summary["breach_events"]:
        out.append(f"  breach event: {json.dumps(e, sort_keys=True)}")
    return "\n".join(out)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    if len(args) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        doc = load_bundle(args[0])
    except (OSError, MalformedBundle) as exc:
        print(f"freshness_report: malformed bundle: {exc}", file=sys.stderr)
        return 2
    summary = summarize(doc)
    if as_json:
        print(json.dumps(summary))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
