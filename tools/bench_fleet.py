"""Aggregate fleet QPS + pooled p99 vs fleet size, paired (ISSUE 11).

The regime the read fleet exists for: open-loop predict traffic through a
front-door router over N serve replicas, on a transport where every result
fetch is a ~70-100 ms RTT-bound REQUEST (BENCHMARKS r2/r3). A single
replica's throughput ceiling in that regime is its in-flight fetch budget
(``--depth`` pipelined fetches / RTT); a fleet multiplies that budget by N
— IF the router and the one-core host don't bind first. This bench
measures which it is.

Arms (single passes round-robin in one budget window on the shared
tools/pairedbench.py harness; PAIRED per-round ratios are the verdict):

- fleet1 / fleet2 / fleet4: a REAL router front door (aiohttp server +
  FleetRouter, policy least-p99) over 1/2/4 in-process replicas — each a
  full ServingPlane behind its own HTTP server, exactly the apps/serve
  stack. Every arm serves the same open-loop load: ``--requests`` requests
  of ``--rowsPerRequest`` rows fired from ``--clients`` threads through
  the router; a pass completes when every response arrives. Aggregate
  QPS = requests / pass seconds; per-request latencies pool into p99.

``--modelRttMs R`` (default 70) runs a second arm set with R ms slept
inside every replica's host fetch — the modeled stand-in for the tunnel's
fetch RTT on backends where fetches are free (the CPU control, which is
fetch-unbound and shows the one-core HOST floor instead). Modeled numbers
are labeled and are NEVER a tunnel-regime verdict (the r2/r3 law); the
first tunnel window should run this attached to the TPU with
``--modelRttMs 0``.

Usage: python tools/bench_fleet.py [--requests N] [--rowsPerRequest R]
       [--clients C] [--depth K] [--budget S] [--modelRttMs MS]
       [--sizes 1,2,4] — prints one JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time
from concurrent.futures import ThreadPoolExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOW_MS = 1785320000000


class FleetArm:
    """One fleet size: N replica planes+servers behind a real router
    front door. Built once, reused across rounds (arms own their warmup —
    the pairedbench contract)."""

    def __init__(self, size, *, rows_per_request, depth, rtt_ms, tmp_dir):
        import jax

        from twtml_tpu.features.featurizer import Featurizer
        from twtml_tpu.serving.engine import PredictEngine
        from twtml_tpu.serving.fleet import FleetRouter
        from twtml_tpu.serving.plane import ServingPlane
        from twtml_tpu.serving.snapshot import ServingSnapshot
        from twtml_tpu.web.cache import ApiCache
        from twtml_tpu.web.server import Server

        import numpy as np

        rng = np.random.default_rng(7)
        weights = rng.standard_normal(1004).astype(np.float32) * 1e-3
        snapshot = ServingSnapshot(
            step=1, weights=weights, meta={"quality": {"level": "ok"}}
        )
        self.size = size
        self.planes = []
        self.servers = []
        urls = []
        for i in range(size):
            engine = PredictEngine(num_text_features=1000)
            if rtt_ms > 0:
                def rtt_fetch(out, _get=jax.device_get, _s=rtt_ms / 1e3):
                    host = _get(out)
                    time.sleep(_s)
                    return host

                engine.fetch_output = rtt_fetch
            plane = ServingPlane(
                snapshot,
                num_text_features=1000,
                # one dispatch per request: the per-replica ceiling is then
                # cleanly depth/RTT, which is what fleet size multiplies
                batch_rows=rows_per_request,
                max_wait_ms=0.0,
                depth=depth,
                featurizer=Featurizer(now_ms=NOW_MS),
                engine=engine,
            ).start()
            server = Server(
                port=0, host="127.0.0.1",
                cache=ApiCache(backup_file=os.path.join(
                    tmp_dir, f"replica-{rtt_ms}-{size}-{i}.json"
                )),
            ).attach_serving(plane)
            server.start_background()
            urls.append(f"http://127.0.0.1:{server._runner.addresses[0][1]}")
            self.planes.append(plane)
            self.servers.append(server)
        self.router = FleetRouter(urls, policy="p99", timeout=120.0)
        self.front = Server(
            port=0, host="127.0.0.1",
            cache=ApiCache(backup_file=os.path.join(
                tmp_dir, f"router-{rtt_ms}-{size}.json"
            )),
        ).attach_fleet(self.router)
        self.front.start_background()
        self.url = f"http://127.0.0.1:{self.front._runner.addresses[0][1]}"

    def stop(self):
        self.front.stop()
        self.router.stop()
        for server in self.servers:
            server.stop()
        for plane in self.planes:
            plane.stop()


def measure(requests: int = 192, rows_per_request: int = 16,
            clients: int = 64, depth: int = 4, budget: float = 60.0,
            model_rtt_ms: float = 70.0, sizes=(1, 2, 4)) -> dict:
    import tempfile

    import jax

    from tools.pairedbench import paired_ratio_median, run_rounds
    from twtml_tpu.serving.client import ServingClient
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=requests * rows_per_request, seed=3).produce()
    )
    loads = []
    for i in range(requests):
        chunk = statuses[i * rows_per_request:(i + 1) * rows_per_request]
        loads.append([{
            "text": s.retweeted_status.text,
            "followers_count": s.retweeted_status.followers_count,
            "favourites_count": s.retweeted_status.favourites_count,
            "friends_count": s.retweeted_status.friends_count,
            "created_at_ms": s.retweeted_status.created_at_ms,
            "retweet_count": s.retweeted_status.retweet_count,
        } for s in chunk])

    tmp_dir = tempfile.mkdtemp(prefix="twtml-bench-fleet-")
    rtt_modes = [0.0]
    if model_rtt_ms > 0:
        rtt_modes.append(model_rtt_ms)
    arms_objs: dict[str, FleetArm] = {}
    for rtt in rtt_modes:
        for size in sizes:
            name = f"fleet{size}" + ("_rtt" if rtt > 0 else "")
            arms_objs[name] = FleetArm(
                size, rows_per_request=rows_per_request, depth=depth,
                rtt_ms=rtt, tmp_dir=tmp_dir,
            )
    latencies: dict[str, list] = {n: [] for n in arms_objs}
    qps: dict[str, list] = {n: [] for n in arms_objs}
    pool = ThreadPoolExecutor(max_workers=clients)

    def one_pass(name):
        arm = arms_objs[name]
        client = ServingClient(arm.url, timeout=300.0, retries=0)
        lats = []

        def one(load):
            t_sub = time.perf_counter()
            client.predict(load)
            lats.append(time.perf_counter() - t_sub)

        t0 = time.perf_counter()
        futs = [pool.submit(one, load) for load in loads]
        for fut in futs:
            fut.result(timeout=600)
        dt = time.perf_counter() - t0
        latencies[name].extend(lats)
        qps[name].append(requests / dt)
        return dt

    # warm every arm outside the window (compile + route + first buckets)
    for name in arms_objs:
        one_pass(name)
    for d in (latencies, qps):
        for name in d:
            d[name].clear()

    arms = {name: (lambda n=name: one_pass(n)) for name in arms_objs}
    times = run_rounds(arms, budget)

    def quantiles(values):
        vs = sorted(values)

        def q(p):
            return round(vs[min(len(vs) - 1, int(p * len(vs)))] * 1e3, 2)

        return {"p50_ms": q(0.50), "p99_ms": q(0.99)}

    out = {
        "regime": "fleet",
        "backend": jax.default_backend(),
        "requests": requests,
        "rows_per_request": rows_per_request,
        "clients": clients,
        "depth": depth,
        "modeled_rtt_ms": model_rtt_ms,
        "sizes": list(sizes),
        "rounds": len(times[next(iter(arms_objs))]),
    }
    for name in arms_objs:
        out[name] = {
            "qps_median": round(statistics.median(qps[name]), 1),
            "qps_best": round(max(qps[name]), 1),
            **quantiles(latencies[name]),
        }
    base = f"fleet{sizes[0]}"
    for size in sizes[1:]:
        out[f"fleet{size}"]["paired_speedup_vs_fleet1"] = (
            paired_ratio_median(times[base], times[f"fleet{size}"])
        )
        if model_rtt_ms > 0:
            out[f"fleet{size}_rtt"]["paired_speedup_vs_fleet1"] = (
                paired_ratio_median(
                    times[base + "_rtt"], times[f"fleet{size}_rtt"]
                )
            )
    for arm in arms_objs.values():
        arm.stop()
    pool.shutdown(wait=False)
    return out


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    kw = dict(requests=192, rows_per_request=16, clients=64, depth=4,
              budget=60.0, model_rtt_ms=70.0, sizes=(1, 2, 4))
    flags = {
        "--requests": ("requests", int),
        "--rowsPerRequest": ("rows_per_request", int),
        "--clients": ("clients", int),
        "--depth": ("depth", int),
        "--budget": ("budget", float),
        "--modelRttMs": ("model_rtt_ms", float),
        "--sizes": ("sizes", lambda v: tuple(
            int(x) for x in v.split(",") if x
        )),
    }
    i = 0
    while i < len(args):
        if args[i] in flags:
            key, cast = flags[args[i]]
            kw[key] = cast(args[i + 1])
            i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")
    print(json.dumps(measure(**kw)))


if __name__ == "__main__":
    main()
