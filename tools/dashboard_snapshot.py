"""Render a dashboard snapshot artifact WITHOUT a browser (VERDICT r3 #8).

The reference's README leads with a dashboard screenshot
(`/root/reference/README.md:3`, `doc/graph.png`); this image has no browser
or JS runtime, so the snapshot is produced the same way the dashboard is
TESTED (tests/test_dashboard_js.py): the REAL shipped assets
(web/assets/index.html + js/{api,chart,index}.js, byte-untouched) execute
on the in-repo JS interpreter (tools/jsmini.py) against the stub DOM
(tools/jsdom.py), fed Stats/Series frames from a REAL training run of the
flagship model. The stub canvas records every draw call chart.js makes;
this tool replays those calls into SVG — so the chart in the artifact is
literally what the shipped chart code drew, and the counter values are
what the shipped counter code wrote into the DOM.

Usage: python tools/dashboard_snapshot.py [--out doc/dashboard.svg]
"""

from __future__ import annotations

import html
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ASSETS = os.path.join(REPO, "twtml_tpu", "web", "assets")


def real_training_frames(batches: int = 36, batch: int = 64):
    """Run the flagship model over the synthetic stream and emit the same
    per-batch Stats/Series wire frames the app publishes
    (apps/linear_regression.py handle → telemetry/web_client.py)."""
    import numpy as np

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.utils import round_half_up

    statuses = list(
        SyntheticSource(total=batches * batch, seed=11,
                        base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    model = StreamingLinearRegressionWithSGD()
    # the session-opening Config frame the app publishes first
    # (telemetry/session_stats.py) — it carries the session id the footer
    # shows; no Lightning host here, so no viz iframes
    frames: list = [{"jsonClass": "Config", "id": "r4-snapshot", "host": "",
                     "viz": []}]
    total = 0
    for i in range(0, len(statuses), batch):
        fb = feat.featurize_batch_units(
            statuses[i : i + batch], row_bucket=batch, pre_filtered=True
        )
        out = model.step(fb)
        n = int(out.count)
        total += n
        valid = np.asarray(fb.mask).astype(bool)
        real = np.asarray(fb.label)[valid]
        pred = np.asarray(out.predictions)[valid]
        frames.append({
            "jsonClass": "Stats", "count": total, "batch": n,
            "mse": round_half_up(float(out.mse)),
            "realStddev": round_half_up(float(out.real_stdev)),
            "predStddev": round_half_up(float(out.pred_stdev)),
        })
        frames.append({
            "jsonClass": "Series",
            "real": [float(x) for x in real[:10]],
            "pred": [float(x) for x in pred[:10]],
            "realStddev": round_half_up(float(out.real_stdev)),
            "predStddev": round_half_up(float(out.pred_stdev)),
        })
    return frames


def run_dashboard(frames):
    """Boot the real dashboard assets on the jsdom harness, feed the frames
    over the (stub) websocket, and return (harness, styled canvas calls)."""
    from tools.jsdom import Harness

    h = Harness([os.path.join(ASSETS, "index.html")])
    h.fetch_routes["/api/stats"] = {
        "jsonClass": "Stats", "count": 0, "batch": 0, "mse": 0,
        "realStddev": 0, "predStddev": 0,
    }
    h.fetch_routes["/api/series"] = []
    for name in ("api.js", "chart.js", "index.js"):
        h.load_script(os.path.join(ASSETS, "js", name))
    h.dom_content_loaded()

    # record style/width PROPERTY SETS interleaved with the draw calls (the
    # test recorder only captures method calls; SVG needs the colors)
    ctx = h.el("livechart").ctx
    original_set = ctx.set

    def recording_set(self, key, value):
        if key in ("strokeStyle", "fillStyle", "lineWidth", "font"):
            self.calls.append(("_set", (key, value)))
        return original_set(key, value)

    ctx.set = types.MethodType(recording_set, ctx)

    h.ws.server_open()
    ctx.calls.clear()  # keep only the fully-fed final redraws
    for fr in frames:
        h.ws.server_message(json.dumps(fr))
    return h, ctx.calls


def canvas_calls_to_svg(calls, x_scale: float = 1.0):
    """Replay recorded canvas ops into SVG elements, scaling x coordinates
    at emission (an x-only GROUP transform would stretch the text glyphs).
    Only the ops chart.js uses are supported (the stub records exactly
    those)."""
    # keep only the ops of the LAST full redraw (chart.js clears first)
    last_clear = max(
        (i for i, c in enumerate(calls) if c[0] == "clearRect"), default=-1
    )
    # styles set before the final clear still apply: replay them all, but
    # emit shapes only after the final clearRect
    out = []
    style = {"strokeStyle": "#888", "fillStyle": "#888", "lineWidth": 1.0}
    path: list = []
    for i, (op, args) in enumerate(calls):
        if op == "_set":
            style[args[0]] = args[1]
            continue
        if i < last_clear:
            continue
        if op == "beginPath":
            path = []
        elif op == "moveTo" or op == "lineTo":
            path.append((float(args[0]) * x_scale, float(args[1])))
        elif op == "stroke" and path:
            pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in path)
            out.append(
                f'<polyline points="{pts}" fill="none" '
                f'stroke="{style["strokeStyle"]}" '
                f'stroke-width="{float(style.get("lineWidth", 1.0)):g}" '
                f'stroke-linejoin="round" />'
            )
            path = []
        elif op == "fillRect":
            x, y, w, hh = (float(a) for a in args[:4])
            out.append(
                f'<rect x="{x * x_scale:g}" y="{y:g}" width="{w:g}" '
                f'height="{hh:g}" fill="{style["fillStyle"]}" />'
            )
        elif op == "fillText":
            out.append(
                f'<text x="{float(args[1]) * x_scale:g}" '
                f'y="{float(args[2]):g}" '
                f'fill="{style["fillStyle"]}" font-size="12" '
                f'font-family="system-ui, sans-serif">'
                f"{html.escape(str(args[0]))}</text>"
            )
    return "\n    ".join(out)


def build_svg(h, calls) -> str:
    canvas = h.el("livechart")
    cw = float(canvas.get("width") or 800) or 800
    ch = float(canvas.get("height") or 360) or 360
    labels = [
        ("tweets total", "count"), ("batch size", "batch"), ("mse", "mse"),
        ("stdev real", "realStddev"), ("stdev predicted", "predStddev"),
    ]
    tiles = []
    tile_w, gap, x0, y0 = 186, 12, 20, 64
    for i, (label, el_id) in enumerate(labels):
        x = x0 + i * (tile_w + gap)
        value = html.escape(h.el(el_id).text or "0")
        tiles.append(f"""
    <g>
      <rect x="{x}" y="{y0}" width="{tile_w}" height="64" rx="8"
            fill="rgba(128,128,128,0.08)" stroke="rgba(128,128,128,0.25)"/>
      <text x="{x + 14}" y="{y0 + 22}" font-size="11" letter-spacing="0.6"
            fill="#777" font-family="system-ui, sans-serif">{label.upper()}</text>
      <text x="{x + 14}" y="{y0 + 50}" font-size="24" fill="#222"
            font-family="system-ui, sans-serif">{value}</text>
    </g>""")
    conn = html.escape(h.el("conn").text or "?")
    width = x0 * 2 + len(labels) * (tile_w + gap) - gap
    chart_y = y0 + 64 + 24
    height = chart_y + ch + 56
    scale = (width - 2 * x0) / cw
    chart_svg = canvas_calls_to_svg(calls, x_scale=scale)
    return f"""<svg xmlns="http://www.w3.org/2000/svg" width="{width}" height="{height:.0f}"
     viewBox="0 0 {width} {height:.0f}" font-family="system-ui, sans-serif">
  <rect width="100%" height="100%" fill="white"/>
  <text x="20" y="36" font-size="22" fill="#222">twitter-stream-ml</text>
  <rect x="{width - 96}" y="18" width="58" height="24" rx="12"
        fill="{'#2e7d32' if conn == 'live' else '#999'}"/>
  <text x="{width - 67}" y="34" font-size="12" fill="white"
        text-anchor="middle">{conn}</text>
  {''.join(tiles)}
  <g transform="translate({x0},{chart_y})">
    <rect x="0" y="0" width="{cw * scale:g}" height="{ch:g}" rx="8"
          fill="none" stroke="rgba(128,128,128,0.25)"/>
    {chart_svg}
  </g>
  <text x="20" y="{height - 20:.0f}" font-size="11" fill="#999">
    session {html.escape(h.el("session").text or "—")} — snapshot: the shipped
    dashboard assets executed on the in-repo JS interpreter over a real
    training run (tools/dashboard_snapshot.py)</text>
</svg>
"""


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    out_path = os.path.join(REPO, "doc", "dashboard.svg")
    i = 0
    while i < len(args):
        if args[i] == "--out":
            out_path = args[i + 1]; i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")
    frames = real_training_frames()
    h, calls = run_dashboard(frames)
    svg = build_svg(h, calls)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as fh:
        fh.write(svg)
    print(out_path)


if __name__ == "__main__":
    main()
