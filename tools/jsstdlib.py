"""jsmini standard library: member dispatch + global builtins.

Implements the JavaScript built-ins the dashboard assets use (see
tools/jsmini.py for scope/why): String/Array/Number methods, JSON, Math,
Promise (reactions run on the interpreter's job queue — the harness drains
it between events, standing in for the browser's microtask checkpoint),
Date, console, and the callable wrappers Number()/String()/Boolean().
Host functions follow the (this, args) -> value convention.
"""

from __future__ import annotations

import json as _json
import math as _math
import random as _random
import re as _re
import time as _time

try:
    from .jsmini import (
        Interp,
        JSFunction,
        JSObject,
        JSRegex,
        JSThrow,
        js_number,
        js_string,
        js_truthy,
        strict_equals,
        undefined,
    )
except ImportError:  # script import
    from jsmini import (  # type: ignore
        Interp,
        JSFunction,
        JSObject,
        JSRegex,
        JSThrow,
        js_number,
        js_string,
        js_truthy,
        strict_equals,
        undefined,
    )


def _arg(args, i, default=undefined):
    return args[i] if i < len(args) else default


# ---------------------------------------------------------------------------
# Promise — resolutions run as interpreter jobs

class MiniPromise:
    def __init__(self, interp: Interp):
        self.interp = interp
        self.state = "pending"
        self.value = undefined
        self.reactions: list = []  # (on_ok, on_err, next_promise)

    # -- internal ----------------------------------------------------------

    def _settle(self, state, value):
        if self.state != "pending":
            return
        if state == "fulfilled" and isinstance(value, MiniPromise):
            value._chain_into(self)
            return
        self.state = state
        self.value = value
        for reaction in self.reactions:
            self._schedule(reaction)
        self.reactions = []

    def _chain_into(self, outer: "MiniPromise"):
        self.then_callbacks(
            lambda v: outer._settle("fulfilled", v),
            lambda e: outer._settle("rejected", e),
        )

    def then_callbacks(self, ok, err):
        nxt = MiniPromise(self.interp)
        reaction = (ok, err, nxt)
        if self.state == "pending":
            self.reactions.append(reaction)
        else:
            self._schedule(reaction)
        return nxt

    def _schedule(self, reaction):
        ok, err, nxt = reaction
        state, value = self.state, self.value

        def job():
            try:
                if state == "fulfilled":
                    result = ok(value) if ok else value
                    nxt._settle("fulfilled", result)
                else:
                    if err:
                        nxt._settle("fulfilled", err(value))
                    else:
                        nxt._settle("rejected", value)
            except JSThrow as exc:
                nxt._settle("rejected", exc.value)

        self.interp.enqueue_job(job)

    # -- JS-facing methods -------------------------------------------------

    def js_then(self, this, args):
        on_ok = _arg(args, 0, None)
        on_err = _arg(args, 1, None)

        def wrap(fn):
            if fn is None or fn is undefined:
                return None
            return lambda v: self.interp.invoke(fn, undefined, [v])

        return self.then_callbacks(wrap(on_ok), wrap(on_err))

    def js_catch(self, this, args):
        return self.js_then(this, [undefined, _arg(args, 0, None)])

    def js_finally(self, this, args):
        fn = _arg(args, 0, None)

        def run(v):
            if fn is not None and fn is not undefined:
                self.interp.invoke(fn, undefined, [])
            return v

        def run_err(e):
            if fn is not None and fn is not undefined:
                self.interp.invoke(fn, undefined, [])
            raise JSThrow(e)

        return self.then_callbacks(run, run_err)


def promise_resolved(interp, value) -> MiniPromise:
    p = MiniPromise(interp)
    p._settle("fulfilled", value)
    return p


def promise_rejected(interp, value) -> MiniPromise:
    p = MiniPromise(interp)
    p._settle("rejected", value)
    return p


# ---------------------------------------------------------------------------
# member dispatch

def get_member(interp: Interp, obj, name):
    if isinstance(obj, JSObject):
        value = obj.get(name)
        if value is not undefined:
            return value
        if name == "hasOwnProperty":
            return lambda this, args: js_string(_arg(args, 0)) in obj.props
        return undefined
    if isinstance(obj, list):
        return _array_member(interp, obj, name)
    if isinstance(obj, str):
        return _string_member(interp, obj, name)
    if isinstance(obj, float):
        return _number_member(interp, obj, name)
    if isinstance(obj, bool):
        return _number_member(interp, js_number(obj), name)
    if isinstance(obj, MiniPromise):
        return {
            "then": obj.js_then, "catch": obj.js_catch, "finally": obj.js_finally,
        }.get(name, undefined)
    if isinstance(obj, JSFunction):
        if name == "prototype":
            return obj.prototype
        if name == "name":
            return obj.name
        if name == "call":
            return lambda this, args: obj.call(_arg(args, 0), list(args[1:]))
        if name == "apply":
            return lambda this, args: obj.call(
                _arg(args, 0), list(_arg(args, 1, []) or [])
            )
        if name == "bind":
            def bind(this, args):
                b_this = _arg(args, 0)
                pre = list(args[1:])
                return lambda t2, a2: obj.call(b_this, pre + list(a2))

            return bind
        custom = getattr(obj, "js_" + name, None)
        if custom is not None:
            return custom
        return undefined
    if isinstance(obj, JSRegex):
        return {
            "source": obj.source, "flags": obj.flags,
            "test": lambda this, args: bool(
                obj.pattern.search(js_string(_arg(args, 0)))
            ),
        }.get(name, undefined)
    if callable(obj):  # host function: no members the assets need
        return undefined
    if obj is undefined or obj is None:
        raise JSThrow(
            f"TypeError: cannot read properties of {js_string(obj)} "
            f"(reading '{name}')"
        )
    return undefined


def _array_member(interp: Interp, arr: list, name):
    if name == "length":
        return float(len(arr))

    def method(fn):
        return fn

    if name == "push":
        def push(this, args):
            arr.extend(args)
            return float(len(arr))
        return push
    if name == "pop":
        return lambda this, args: arr.pop() if arr else undefined
    if name == "shift":
        return lambda this, args: arr.pop(0) if arr else undefined
    if name == "unshift":
        def unshift(this, args):
            arr[0:0] = list(args)
            return float(len(arr))
        return unshift
    if name == "splice":
        def splice(this, args):
            start = int(js_number(_arg(args, 0, 0.0)))
            if start < 0:
                start = max(len(arr) + start, 0)
            count = (
                len(arr) - start
                if len(args) < 2
                else max(int(js_number(args[1])), 0)
            )
            removed = arr[start : start + count]
            arr[start : start + count] = list(args[2:])
            return removed
        return splice
    if name == "slice":
        def slice_(this, args):
            start = int(js_number(_arg(args, 0, 0.0)))
            end = len(arr) if len(args) < 2 else int(js_number(args[1]))
            return arr[slice(start, end)]
        return slice_
    if name == "concat":
        def concat(this, args):
            out = list(arr)
            for a in args:
                if isinstance(a, list):
                    out.extend(a)
                else:
                    out.append(a)
            return out
        return concat
    if name == "join":
        def join(this, args):
            sep = js_string(_arg(args, 0, ","))
            return sep.join(
                "" if x is undefined or x is None else js_string(x) for x in arr
            )
        return join
    if name == "indexOf":
        def index_of(this, args):
            target = _arg(args, 0)
            for i, x in enumerate(arr):
                if strict_equals(x, target):
                    return float(i)
            return -1.0
        return index_of
    if name == "includes":
        def includes(this, args):
            target = _arg(args, 0)
            return any(strict_equals(x, target) for x in arr)
        return includes
    if name == "forEach":
        def for_each(this, args):
            fn = args[0]
            for i, x in enumerate(list(arr)):
                interp.invoke(fn, undefined, [x, float(i), arr])
            return undefined
        return for_each
    if name == "map":
        def map_(this, args):
            fn = args[0]
            return [
                interp.invoke(fn, undefined, [x, float(i), arr])
                for i, x in enumerate(list(arr))
            ]
        return map_
    if name == "filter":
        def filter_(this, args):
            fn = args[0]
            return [
                x for i, x in enumerate(list(arr))
                if js_truthy(interp.invoke(fn, undefined, [x, float(i), arr]))
            ]
        return filter_
    if name == "find":
        def find(this, args):
            fn = args[0]
            for i, x in enumerate(list(arr)):
                if js_truthy(interp.invoke(fn, undefined, [x, float(i), arr])):
                    return x
            return undefined
        return find
    if name == "some":
        def some(this, args):
            fn = args[0]
            return any(
                js_truthy(interp.invoke(fn, undefined, [x, float(i), arr]))
                for i, x in enumerate(list(arr))
            )
        return some
    if name == "every":
        def every(this, args):
            fn = args[0]
            return all(
                js_truthy(interp.invoke(fn, undefined, [x, float(i), arr]))
                for i, x in enumerate(list(arr))
            )
        return every
    if name == "reduce":
        def reduce_(this, args):
            fn = args[0]
            items = list(arr)
            if len(args) >= 2:
                acc = args[1]
                start = 0
            else:
                acc = items[0]
                start = 1
            for i in range(start, len(items)):
                acc = interp.invoke(fn, undefined, [acc, items[i], float(i), arr])
            return acc
        return reduce_
    if name == "reverse":
        def reverse(this, args):
            arr.reverse()
            return arr
        return reverse
    if name == "sort":
        def sort(this, args):
            import functools

            if args and args[0] is not undefined:
                fn = args[0]
                arr.sort(key=functools.cmp_to_key(
                    lambda a, b: (lambda r: -1 if r < 0 else (1 if r > 0 else 0))(
                        js_number(interp.invoke(fn, undefined, [a, b]))
                    )
                ))
            else:
                arr.sort(key=js_string)
            return arr
        return sort
    if name == "toString":
        return lambda this, args: js_string(arr)
    return undefined


def _string_member(interp: Interp, s: str, name):
    if name == "length":
        return float(len(s))
    if name == "replace":
        def replace(this, args):
            pat, repl = _arg(args, 0), _arg(args, 1)

            def do_one(match_text):
                if isinstance(repl, JSFunction) or callable(repl):
                    return js_string(interp.invoke(repl, undefined, [match_text]))
                return js_string(repl)

            if isinstance(pat, JSRegex):
                count = 0 if pat.global_ else 1
                return pat.pattern.sub(lambda m: do_one(m.group(0)), s, count=count)
            target = js_string(pat)
            if isinstance(repl, JSFunction) or callable(repl):
                return s.replace(target, do_one(target), 1)
            return s.replace(target, js_string(repl), 1)
        return replace
    if name == "split":
        def split(this, args):
            sep = _arg(args, 0)
            if sep is undefined:
                return [s]
            sep_s = js_string(sep)
            if sep_s == "":
                return list(s)
            return s.split(sep_s)
        return split
    simple = {
        "trim": lambda this, args: s.strip(),
        "toLowerCase": lambda this, args: s.lower(),
        "toUpperCase": lambda this, args: s.upper(),
        "toString": lambda this, args: s,
        "charAt": lambda this, args: (
            s[int(js_number(_arg(args, 0, 0.0)))]
            if 0 <= int(js_number(_arg(args, 0, 0.0))) < len(s) else ""
        ),
        "charCodeAt": lambda this, args: (
            float(ord(s[int(js_number(_arg(args, 0, 0.0)))]))
            if 0 <= int(js_number(_arg(args, 0, 0.0))) < len(s)
            else float("nan")
        ),
        "indexOf": lambda this, args: float(s.find(js_string(_arg(args, 0)))),
        "includes": lambda this, args: js_string(_arg(args, 0)) in s,
        "startsWith": lambda this, args: s.startswith(js_string(_arg(args, 0))),
        "endsWith": lambda this, args: s.endswith(js_string(_arg(args, 0))),
        "slice": lambda this, args: s[
            slice(
                int(js_number(_arg(args, 0, 0.0))),
                None if len(args) < 2 else int(js_number(args[1])),
            )
        ],
        "substring": lambda this, args: s[
            max(int(js_number(_arg(args, 0, 0.0))), 0):
            (len(s) if len(args) < 2 else max(int(js_number(args[1])), 0))
        ],
        "repeat": lambda this, args: s * int(js_number(_arg(args, 0, 0.0))),
        "padStart": lambda this, args: s.rjust(
            int(js_number(_arg(args, 0, 0.0))), js_string(_arg(args, 1, " "))
        ),
    }
    return simple.get(name, undefined)


def _number_member(interp: Interp, x: float, name):
    if name == "toString":
        def to_string(this, args):
            if not args or args[0] is undefined:
                return js_string(x)
            radix = int(js_number(args[0]))
            n = int(x)
            if n == 0:
                return "0"
            digits = "0123456789abcdefghijklmnopqrstuvwxyz"
            neg, n = n < 0, abs(n)
            out = []
            while n:
                out.append(digits[n % radix])
                n //= radix
            return ("-" if neg else "") + "".join(reversed(out))
        return to_string
    if name == "toLocaleString":
        def to_locale(this, args):
            if x.is_integer():
                return f"{int(x):,}"
            return f"{x:,.3f}"
        return to_locale
    if name == "toFixed":
        return lambda this, args: f"{x:.{int(js_number(_arg(args, 0, 0.0)))}f}"
    return undefined


# ---------------------------------------------------------------------------
# globals

def install_globals(interp: Interp, rng_seed: int = 0):
    """Declare the engine-level builtins (no DOM — tools/jsdom.py adds the
    browser environment on top)."""
    env = interp.global_env
    rng = _random.Random(rng_seed)

    math_obj = JSObject({
        "random": lambda this, args: rng.random(),
        "floor": lambda this, args: float(_math.floor(js_number(_arg(args, 0)))),
        "ceil": lambda this, args: float(_math.ceil(js_number(_arg(args, 0)))),
        "round": lambda this, args: float(_math.floor(js_number(_arg(args, 0)) + 0.5)),
        "abs": lambda this, args: abs(js_number(_arg(args, 0))),
        "sqrt": lambda this, args: _math.sqrt(js_number(_arg(args, 0))),
        "pow": lambda this, args: js_number(_arg(args, 0)) ** js_number(_arg(args, 1)),
        "min": lambda this, args: (
            min((js_number(a) for a in args), default=float("inf"))
        ),
        "max": lambda this, args: (
            max((js_number(a) for a in args), default=float("-inf"))
        ),
        "PI": _math.pi,
    })
    env.declare("Math", math_obj)

    def json_stringify(this, args):
        return _json.dumps(_to_python(_arg(args, 0)), separators=(",", ":"))

    def json_parse(this, args):
        try:
            return _from_python(_json.loads(js_string(_arg(args, 0))))
        except Exception:
            raise JSThrow("SyntaxError: Unexpected token in JSON")

    env.declare("JSON", JSObject({
        "stringify": json_stringify, "parse": json_parse,
    }))

    def number_call(this, args):
        return js_number(_arg(args, 0, 0.0))

    number_obj = JSObject({
        "isInteger": lambda this, args: isinstance(_arg(args, 0), float)
        and float(_arg(args, 0)).is_integer(),
        "isFinite": lambda this, args: isinstance(_arg(args, 0), float)
        and _math.isfinite(_arg(args, 0)),
        "parseFloat": lambda this, args: js_number(_arg(args, 0)),
        "MAX_SAFE_INTEGER": float(2**53 - 1),
    })

    class CallableNumber(JSObject):
        def __call__(self, this, args):
            return number_call(this, args)

    num = CallableNumber(number_obj.props)
    env.declare("Number", num)
    env.declare("String", lambda this, args: js_string(_arg(args, 0, "")))
    env.declare("Boolean", lambda this, args: js_truthy(_arg(args, 0)))
    env.declare("parseInt", lambda this, args: _parse_int(args))
    env.declare("parseFloat", lambda this, args: js_number(_arg(args, 0)))
    env.declare("isNaN", lambda this, args: _math.isnan(js_number(_arg(args, 0))))
    env.declare("NaN", float("nan"))
    env.declare("Infinity", float("inf"))

    class CallableArray(JSObject):
        def __call__(self, this, args):  # Array(n) / Array(a, b, c)
            if len(args) == 1 and isinstance(args[0], float):
                return [undefined] * int(args[0])
            return list(args)

    env.declare("Array", CallableArray({
        "isArray": lambda this, args: isinstance(_arg(args, 0), list),
        "from": lambda this, args: list(_arg(args, 0, []) or []),
    }))

    env.declare("Object", JSObject({
        "keys": lambda this, args: list(_arg(args, 0).props)
        if isinstance(_arg(args, 0), JSObject) else [],
        "values": lambda this, args: list(_arg(args, 0).props.values())
        if isinstance(_arg(args, 0), JSObject) else [],
        "assign": lambda this, args: _object_assign(args),
        "entries": lambda this, args: [
            [k, v] for k, v in _arg(args, 0).props.items()
        ] if isinstance(_arg(args, 0), JSObject) else [],
    }))

    def promise_ctor(this, args):
        p = MiniPromise(interp)
        executor = _arg(args, 0)
        resolve = lambda t, a: p._settle("fulfilled", _arg(a, 0))  # noqa: E731
        reject = lambda t, a: p._settle("rejected", _arg(a, 0))  # noqa: E731
        interp.invoke(executor, undefined, [resolve, reject])
        return p

    class CallablePromise(JSObject):
        def __call__(self, this, args):
            return promise_ctor(this, args)

    env.declare("Promise", CallablePromise({
        "resolve": lambda this, args: promise_resolved(interp, _arg(args, 0)),
        "reject": lambda this, args: promise_rejected(interp, _arg(args, 0)),
    }))

    def date_ctor(this, args):
        obj = JSObject({
            "_ms": float(_time.time() * 1000) if not args else js_number(args[0]),
        })
        obj.set("getTime", lambda t, a: obj.get("_ms"))
        obj.set(
            "toLocaleTimeString",
            lambda t, a: _time.strftime(
                "%H:%M:%S", _time.localtime(obj.get("_ms") / 1000.0)
            ),
        )
        obj.set("toISOString", lambda t, a: _time.strftime(
            "%Y-%m-%dT%H:%M:%S", _time.gmtime(obj.get("_ms") / 1000.0)
        ))
        return obj

    class CallableDate(JSObject):
        def __call__(self, this, args):
            return date_ctor(this, args)

    env.declare("Date", CallableDate({
        "now": lambda this, args: float(_time.time() * 1000),
    }))

    console_log: list[str] = []

    def log_fn(level):
        def log(this, args):
            console_log.append(level + ": " + " ".join(js_string(a) for a in args))
            return undefined
        return log

    env.declare("console", JSObject({
        "log": log_fn("log"), "error": log_fn("error"),
        "warn": log_fn("warn"), "info": log_fn("info"),
    }))
    return console_log


def _parse_int(args):
    s = js_string(_arg(args, 0)).strip()
    radix = int(js_number(_arg(args, 1, 10.0)))
    m = _re.match(r"[+-]?[0-9a-zA-Z]+", s)
    if not m:
        return float("nan")
    try:
        return float(int(m.group(0), radix))
    except ValueError:
        # parse the longest valid prefix
        text = m.group(0)
        for end in range(len(text), 0, -1):
            try:
                return float(int(text[:end], radix))
            except ValueError:
                continue
        return float("nan")


def _object_assign(args):
    target = _arg(args, 0)
    for src in args[1:]:
        if isinstance(src, JSObject):
            target.props.update(src.props)
    return target


def _to_python(v):
    if v is undefined:
        return None
    if isinstance(v, JSObject):
        return {k: _to_python(x) for k, x in v.props.items()
                if not (isinstance(x, JSFunction) or callable(x) or x is undefined)}
    if isinstance(v, list):
        return [_to_python(x) for x in v]
    if isinstance(v, float) and v.is_integer() and abs(v) < 2**53:
        return int(v)
    return v


def _from_python(v):
    if v is None:
        return None
    if isinstance(v, dict):
        return JSObject({k: _from_python(x) for k, x in v.items()})
    if isinstance(v, list):
        return [_from_python(x) for x in v]
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    return v
