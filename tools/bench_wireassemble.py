"""One-pass wire assembly verdict (ISSUE 14): ``--wireAssemble`` off vs
on, paired, on the host chain the r2/r3 ladder says to shrink.

The question: the numpy pack pipeline touches the wire bytes 3-5 times on
the ONE usable host core (stack/contiguous copies, offsets→deltas, codec
encode, final concatenate); the fused C emitter
(native/wireassemble.cpp) lays the FINAL buffer down in one sweep into a
pooled arena lease. How much host does that buy — on the pack stage
alone, and diluted across the full host chain (bytes → packed wire)?

Method: the house harness only (tools/pairedbench.py) — interleaved
single passes, paired per-round ratios (each pair shares a tunnel-phase
window), byte parity asserted per window (the assembler may never change
the wire). Three windows per regime (object / block ingest):

- **pack stage** — pack-only passes (k=1 flat + K-group coalesced),
  numpy vs fused: the assembler's whole timed delta. Target ≥1.5×.
- **host chain** — the full host side (block: raw JSONL bytes → native
  wire parse → featurize → pack; object: Status list → featurize →
  pack), numpy vs fused: the production dilution. Target ≥1.25×, with
  the honest-miss floor being featurize+parse (arm-identical work the
  assembler cannot touch).
- **CPU control + modeled upload** — the chain ratio is wire-neutral by
  construction (identical bytes both arms), so the modeled window adds
  EXACT upload arithmetic wire_bytes/BW across the measured 45-70 MB/s
  envelope to show the end-to-end dilution an upload-bound tunnel pays.

Pack-only arms retire each lease immediately (nothing is in flight), so
the arms measure the steady state: recycled arena buffers, zero fresh
allocations after warmup.

Usage: python tools/bench_wireassemble.py [--regime object|block|both]
       [--tweets N] [--batch B] [--k K] [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the tunnel's measured upload-bandwidth envelope (BENCHMARKS.md r2)
UPLOAD_MBS_SWEEP = (45.0, 55.0, 70.0)


def _statuses(n_tweets: int):
    from twtml_tpu.streaming.sources import SyntheticSource

    return list(SyntheticSource(total=n_tweets, seed=3).produce())


def _block_data(statuses) -> bytes:
    from tools.bench_suite import _status_json

    return (
        "\n".join(json.dumps(_status_json(s)) for s in statuses) + "\n"
    ).encode("utf-8")


def _featurize_object(statuses, batch):
    from twtml_tpu.features.featurizer import Featurizer

    feat = Featurizer(now_ms=1785320000000)
    return [
        feat.featurize_batch_ragged(
            statuses[i : i + batch], row_bucket=batch, pre_filtered=True
        )
        for i in range(0, len(statuses), batch)
    ]


def _featurize_block(data: bytes, batch):
    from twtml_tpu.features import native
    from twtml_tpu.features.blocks import ParsedBlock, iter_row_chunks
    from twtml_tpu.features.featurizer import Featurizer

    feat = Featurizer(now_ms=1785320000000)
    parsed = native.parse_tweet_block_wire(data, 0, 10**9)
    if parsed is None:
        raise SystemExit("block regime needs the native wire parser")
    block = ParsedBlock(*parsed[:4])
    return [
        feat.featurize_parsed_block(b, row_bucket=batch, ragged=True)
        for b in iter_row_chunks([block], batch)
    ]


def _uniform_groups(batches, k: int):
    from collections import Counter

    sig = lambda b: (b.units.shape, b.units.dtype, b.row_len)  # noqa: E731
    modal, _n = Counter(sig(b) for b in batches).most_common(1)[0]
    same = [b for b in batches if sig(b) == modal]
    groups = [same[i : i + k] for i in range(0, len(same) - k + 1, k)]
    if not groups:
        raise SystemExit("no signature-uniform group; raise --tweets")
    return groups


def _retire(pb) -> None:
    lease = getattr(pb, "_lease", None)
    if lease is not None:
        lease.retire()  # pack-only: nothing is in flight


def _assert_parity(batches, groups) -> None:
    """The assembler may never change the wire: byte + layout parity of
    both pack forms, asserted once per window."""
    import numpy as np

    from twtml_tpu.features import assemble
    from twtml_tpu.features.batch import pack_batch, pack_ragged_group

    for fn in (
        lambda: pack_batch(batches[0]),
        lambda: pack_ragged_group(groups[0]),
    ):
        with assemble.forced("off"):
            ref = fn()
        with assemble.forced("on"):
            got = fn()
        assert got.layout == ref.layout, "assembled layout diverged"
        assert np.array_equal(got.buffer, ref.buffer), (
            "assembled wire bytes diverged"
        )


def _pack_window(batches, groups, budget_s: float) -> dict:
    """Pack-stage-only window: numpy vs fused over the identical batch
    sequence (k=1 flat packs + K-group coalesced packs per pass), raw and
    codec wires. The floor the honest-miss rule measures against: the
    fused pass is ONE memcpy of the wire bytes (source fields → packed
    destination — the minimum any pack can do), so ``memcpy_floor_s`` is
    that byte volume at the host's measured copy bandwidth, taken from
    the fastest fused pass."""
    from tools.pairedbench import paired_ratio_median, run_rounds
    from twtml_tpu.features import assemble
    from twtml_tpu.features.batch import (
        pack_batch, pack_ragged_group, wire_nbytes,
    )

    pass_bytes = {"n": 0}

    def arm(mode, codec):
        def run():
            with assemble.forced(mode):
                t0 = time.perf_counter()
                total = 0
                for b in batches:
                    pb = pack_batch(b, codec=codec)
                    total += wire_nbytes(pb)
                    _retire(pb)
                for g in groups:
                    pb = pack_ragged_group(g, codec=codec)
                    total += wire_nbytes(pb)
                    _retire(pb)
                pass_bytes["n"] = total
                return time.perf_counter() - t0

        return run

    arms = {
        "numpy_raw": arm("off", None),
        "fused_raw": arm("on", None),
        "numpy_codec": arm("off", "dict"),
        "fused_codec": arm("on", "dict"),
    }
    for run in arms.values():
        run()  # warmup: page in, fill the arena pool, build the LUT
    times = run_rounds(arms, budget_s)
    return {
        "rounds": len(times["numpy_raw"]),
        "paired_fused_vs_numpy_raw": paired_ratio_median(
            times["numpy_raw"], times["fused_raw"]
        ),
        "paired_fused_vs_numpy_codec": paired_ratio_median(
            times["numpy_codec"], times["fused_codec"]
        ),
        "pack_ms_median": {
            n: round(statistics.median(ts) * 1e3, 3)
            for n, ts in times.items()
        },
        "wire_bytes_per_pass": pass_bytes["n"],
        # the one-copy floor: the fastest fused raw pass IS a single
        # memcpy of the wire plus call overhead — the denominator of any
        # honest pack-ratio ceiling claim
        "memcpy_floor_s": round(min(times["fused_raw"]), 5),
    }


def _chain_window(
    regime: str, statuses, data, batch: int, k: int, budget_s: float
) -> dict:
    """Full-host-chain window: bytes (or Status objects) → featurize →
    packed wire, numpy vs fused — the production dilution of the pack win,
    plus the modeled upload-bound ratios (identical wire bytes both arms,
    so upload only DILUTES; the envelope shows by how much)."""
    from tools.pairedbench import paired_ratio_median, paired_ratios, run_rounds
    from twtml_tpu.features import assemble
    from twtml_tpu.features.batch import pack_ragged_group, wire_nbytes

    wire_bytes = {"n": 0}

    def one_pass():
        batches = (
            _featurize_object(statuses, batch)
            if regime == "object"
            else _featurize_block(data, batch)
        )
        groups = _uniform_groups(batches, k)
        total = 0
        for g in groups:
            pb = pack_ragged_group(g)
            total += wire_nbytes(pb)
            _retire(pb)
        wire_bytes["n"] = total
        return len(groups)

    def arm(mode):
        def run():
            with assemble.forced(mode):
                t0 = time.perf_counter()
                n_groups = one_pass()
                dt = time.perf_counter() - t0
            return dt, n_groups

        return run

    arms = {"numpy": arm("off"), "fused": arm("on")}
    for run in arms.values():
        run()
    times = run_rounds(arms, budget_s)
    rec = {
        "rounds": len(times["numpy"]),
        "paired_fused_vs_numpy": paired_ratio_median(
            times["numpy"], times["fused"]
        ),
        "chain_s_median": {
            n: round(statistics.median(ts), 4) for n, ts in times.items()
        },
        "wire_bytes_per_pass": wire_bytes["n"],
        "paired_upload_bound": {},
    }
    for mbs in UPLOAD_MBS_SWEEP:
        up = wire_bytes["n"] / (mbs * 1e6)
        rec["paired_upload_bound"][str(int(mbs))] = round(
            statistics.median(paired_ratios(
                [t + up for t in times["numpy"]],
                [t + up for t in times["fused"]],
            )), 3,
        )
    return rec


def measure(
    regime: str, n_tweets: int, batch: int, k: int, budget_s: float
) -> dict:
    import jax

    from twtml_tpu.features import assemble
    from twtml_tpu.features.arena import get_arena
    from twtml_tpu.telemetry import metrics as _metrics

    statuses = _statuses(n_tweets)
    data = _block_data(statuses) if regime == "block" else b""
    batches = (
        _featurize_object(statuses, batch)
        if regime == "object"
        else _featurize_block(data, batch)
    )
    groups = _uniform_groups(batches, k)
    _assert_parity(batches, groups)
    rec = {
        "regime": regime, "tweets": n_tweets, "batch": batch, "k": k,
        "backend": jax.devices()[0].platform,
        "assembler_available": assemble.available(),
        "pack_stage": _pack_window(batches, groups, budget_s),
        "host_chain": _chain_window(
            regime, statuses, data, batch, k, budget_s
        ),
        "arena": get_arena().stats(),
        "assembled_native_packs": _metrics.get_registry().counter(
            "wire.assembled_native"
        ).snapshot(),
    }
    return rec


def main() -> None:
    args = sys.argv[1:]

    def opt(name, default, cast):
        if name in args:
            return cast(args[args.index(name) + 1])
        return default

    regime = opt("--regime", "both", str)
    n_tweets = opt("--tweets", 65536, int)
    batch = opt("--batch", 8192, int)
    k = opt("--k", 4, int)
    budget = opt("--budget", 60.0, float)
    regimes = ["object", "block"] if regime == "both" else [regime]
    out = [measure(r, n_tweets, batch, k, budget) for r in regimes]
    print(json.dumps(out if len(out) > 1 else out[0]))


if __name__ == "__main__":
    main()
