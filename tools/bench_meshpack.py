"""Mesh-packed ragged wire: paired packed-vs-unpacked on the SHARDED model
(VERDICT r4 #1b's measured-number bar — the +11.4% one-buffer win must be
measured, not assumed, on the mesh path that now ships it).

Arms (single passes round-robin in one window; the phase-robust comparison
is the paired per-round ratio):

- unpacked: ``model.step(ragged_batch)`` — the shard-aligned ragged arrays
  placed per step (4 host arrays on the wire);
- packed:   ``model.step(model.pack_for_wire(ragged_batch))`` — the shipped
  default: one per-shard-segmented buffer, row-sharded over the data axis.

Both arms pay their full host cost in-loop (alignment, packing, placement),
exactly as the app does; final-batch mse is asserted bit-identical between
arms every round.

Two regimes matter (run both, record both):
- the TUNNEL with a 1-device mesh (`--devices 1` on the TPU backend): the
  transport regime where the single-device pack won +11.4% — this drives
  `ParallelSGDModel.pack_for_wire`'s exact code over the real wire;
- the 8-device CPU mesh (`--cpu --devices 8`, a virtual-device switch like
  the test conftest's — the host sitecustomize pins the tunnel platform, so
  env vars alone don't flip it): local transfers are ~free, so neutral is
  the expected honest result — the mesh pack is transport-motivated, and
  this arm bounds its local-backend overhead.

Usage: python tools/bench_meshpack.py [--devices N] [--tweets N] [--batch B]
       [--budget S] [--cpu]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget, devices, cpu = 65536, 16384, 240.0, 1, False
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        elif args[i] == "--devices":
            devices = int(args[i + 1]); i += 2
        elif args[i] == "--cpu":
            cpu = True; i += 1
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    if cpu:
        from twtml_tpu.utils import force_virtual_cpu_devices

        if not force_virtual_cpu_devices(devices):
            raise SystemExit("--cpu: a backend is already initialized")

    import jax

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.streaming.sources import SyntheticSource

    if len(jax.devices()) < devices:
        raise SystemExit(
            f"--devices {devices} but only {len(jax.devices())} present"
        )
    mesh = make_mesh(num_data=devices)

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]
    r_batches = [
        feat.featurize_batch_ragged(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]

    import numpy as np

    from twtml_tpu.models.sgd import NUM_NUMBER_FEATURES

    model = ParallelSGDModel(mesh)
    zeros = np.zeros(
        (model.num_text_features + NUM_NUMBER_FEATURES,), np.float32
    )

    def unpacked_pass():
        model.set_initial_weights(zeros)
        for rb in r_batches:
            out = model.step(rb)
        return float(out.mse)

    def packed_pass():
        model.set_initial_weights(zeros)
        for rb in r_batches:
            out = model.step(model.pack_for_wire(rb))
        return float(out.mse)

    mse_u = unpacked_pass()  # warm both programs (per ragged layout the
    mse_p = packed_pass()    # corpus produces)
    if mse_u != mse_p:
        raise SystemExit(f"arms diverge: unpacked {mse_u} packed {mse_p}")

    t_unpacked, t_packed = [], []
    t_end = time.perf_counter() + budget
    while time.perf_counter() < t_end:
        t0 = time.perf_counter(); mu = unpacked_pass()
        t1 = time.perf_counter(); mp = packed_pass()
        t2 = time.perf_counter()
        if mu != mp:
            raise SystemExit(f"arms diverge: unpacked {mu} packed {mp}")
        t_unpacked.append(t1 - t0)
        t_packed.append(t2 - t1)

    out = {
        "regime": "mesh-packed ragged wire", "devices": devices,
        "batch": batch, "tweets": n_tweets,
        "backend": jax.default_backend(), "rounds": len(t_unpacked),
        "final_mse_bit_identical": True,
    }
    for name, ts in (("unpacked", t_unpacked), ("packed", t_packed)):
        out[name] = {
            "tweets_per_sec_best": round(n_tweets / min(ts), 1),
            "tweets_per_sec_median": round(n_tweets / statistics.median(ts), 1),
        }
    out["packed"]["paired_speedup_vs_unpacked"] = round(
        statistics.median([u / p for u, p in zip(t_unpacked, t_packed)]), 3
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
