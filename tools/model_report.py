"""Render the model-quality history stamped into verified checkpoints
(ISSUE 8): every cadence/final/forced save records the model watcher's
snapshot (level, drift z, loss trend, norms, last mse) in the checkpoint
meta (apps/common.AppCheckpoint._save), so a checkpoint directory carries
the promotion-gate substrate the future serving plane reads — "is THIS
snapshot healthy enough to serve?" — without replaying anything.

Exit status is a CHECK, exactly like tools/postmortem_report.py: 0 = a
readable checkpoint directory whose archives parse; 2 = malformed (missing
directory, no checkpoints, or an archive whose meta is unreadable).
Checkpoints saved before the quality stamp existed render as "(unstamped)"
and do not fail the check. ``--json`` emits the history as one
machine-readable line.

``--gate`` (ISSUE 9) turns the report into the PROMOTION GATE: exit 0 when
the newest VERIFIED checkpoint is servable (finite + quality level <= warn),
1 when it is not (alert-stamped, quarantined-only, or no verified archive at
all), 2 on a malformed directory. The predicate is IMPORTED from
``twtml_tpu.serving.snapshot`` — the exact function the serving plane's
promoter runs — so an ops script's yes/no and the server can never disagree.

Usage: python tools/model_report.py CHECKPOINT_DIR [--json] [--gate]
"""

from __future__ import annotations

import json
import os
import re
import sys

import numpy as np

_CKPT_RE = re.compile(r"^(quarantine-)?ckpt-(\d+)\.npz$")


class MalformedHistory(ValueError):
    pass


def load_history(directory: str) -> list[dict]:
    """Per-checkpoint meta rows (oldest first), quarantined archives
    included and flagged — a post-mortem wants to see the diverged save's
    stamp too."""
    if not os.path.isdir(directory):
        raise MalformedHistory(f"not a checkpoint directory: {directory!r}")
    names = sorted(
        n for n in os.listdir(directory) if _CKPT_RE.match(n)
    )
    if not names:
        raise MalformedHistory(f"no checkpoint archives in {directory!r}")
    rows = []
    for name in names:
        path = os.path.join(directory, name)
        try:
            with np.load(path) as data:
                meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
        except Exception as exc:
            raise MalformedHistory(
                f"unreadable checkpoint meta in {name}: {exc}"
            ) from exc
        if not isinstance(meta, dict) or "step" not in meta:
            raise MalformedHistory(f"checkpoint {name} meta has no step")
        rows.append({
            "name": name,
            "quarantined": bool(_CKPT_RE.match(name).group(1)),
            "step": int(meta["step"]),
            "count": int(meta.get("count", 0)),
            "finite": bool(meta.get("finite", True)),
            "quality": meta.get("quality"),
        })
    return rows


def render(rows: list[dict]) -> str:
    out = [
        "checkpoint quality history "
        f"({len(rows)} archive{'s' if len(rows) != 1 else ''}):",
        f"  {'step':>10}  {'rows':>10}  {'health':<7} "
        f"{'drift z':>8}  {'trend':>7}  {'w-norm':>10}  {'mse':>10}",
    ]
    for r in rows:
        q = r["quality"]
        flag = " QUARANTINED" if r["quarantined"] else ""
        if not q:
            out.append(
                f"  {r['step']:>10}  {r['count']:>10}  (unstamped)" + flag
            )
            continue
        trend = float(q.get("loss_trend", 0.0))
        out.append(
            f"  {r['step']:>10}  {r['count']:>10}  {q.get('level', '?'):<7} "
            f"{float(q.get('drift_score', 0.0)):>8.2f}  "
            f"{trend * 100:>+6.1f}%  "
            f"{float(q.get('weight_norm', 0.0)):>10.2f}  "
            f"{float(q.get('mse', -1.0)):>10.2f}" + flag
        )
    stamped = [r for r in rows if r["quality"]]
    if stamped:
        last = stamped[-1]["quality"]
        out.append(
            f"  latest stamped: step {stamped[-1]['step']} — "
            f"{last.get('level', '?')} (drift z "
            f"{float(last.get('drift_score', 0.0)):.2f}, "
            f"{int(last.get('episodes', 0))} drift episodes over "
            f"{int(last.get('ticks', 0))} ticks)"
        )
    else:
        out.append("  (no quality stamps — run with --modelWatch on)")
    return "\n".join(out)


def gate(directory: str, as_json: bool = False) -> int:
    """The promotion gate: 0 = the newest verified checkpoint is servable,
    1 = it is not, 2 = malformed directory. Runs the serving plane's OWN
    predicate (``twtml_tpu.serving.snapshot`` — jax-free import)."""
    from twtml_tpu.serving.snapshot import load_servable

    # malformed directories stay exit 2 (the report contract); a directory
    # that parses but holds nothing servable is a clean "no" (exit 1)
    try:
        load_history(directory)
    except (OSError, MalformedHistory) as exc:
        print(f"model_report: malformed history: {exc}", file=sys.stderr)
        return 2
    snapshot, reason = load_servable(directory)
    verdict = {
        "promotable": snapshot is not None,
        "reason": reason,
        "step": snapshot.step if snapshot is not None else None,
        "tenants": snapshot.num_tenants if snapshot is not None else 0,
    }
    if as_json:
        print(json.dumps(verdict))
    elif snapshot is not None:
        print(f"PROMOTABLE: step {snapshot.step} — {reason}")
    else:
        print(f"NOT PROMOTABLE: {reason}")
    return 0 if snapshot is not None else 1


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    as_gate = "--gate" in args
    args = [a for a in args if a not in ("--json", "--gate")]
    if len(args) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    if as_gate:
        return gate(args[0], as_json=as_json)
    try:
        rows = load_history(args[0])
    except (OSError, MalformedHistory) as exc:
        print(f"model_report: malformed history: {exc}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(rows))
    else:
        print(render(rows))
    return 0


if __name__ == "__main__":
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    raise SystemExit(main())
