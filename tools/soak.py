"""Endurance soak: alternate the flagship configs back-to-back on the chip
and assert numeric bit-stability — the r2/r3 reliability evidence
(BENCHMARKS.md "Endurance soaks").

Each round runs, on the SAME process/models: the dense ragged-wire pipeline
at the r4 headline operating point (batch 16384) and the 2^18 int8-Gram
config at its r4 operating point (batch 3072, ragged). Every pass resets
weights and streams the identical corpus, so the final-batch mse must be
BIT-IDENTICAL on every pass — any drift, leak-induced slowdown, or
transport wedge fails loudly.

r17 additions (ISSUE 14):

- the RSS **slope** (least-squares MB/min over per-pass samples of the
  live VmRSS) joins the JSON line, and ``--maxRssSlopeMbPerMin X`` turns
  the soak into a CI/ops GATE: exit 1 when the slope breaches X — RSS
  flatness becomes assertable instead of eyeballed.
- ``--arena <on|off>`` toggles the pooled wire-buffer arena
  (features/arena.py): the soak retires each pass's pack leases at the
  pass's completion fetch (every dispatch has provably executed by then),
  so arena-on reuses the same destination buffers pass over pass while
  arena-off is the pre-r17 fresh-allocation control arm. The two slopes,
  recorded side by side, are the arena's RSS evidence (BENCHMARKS.md
  "One-pass wire assembly (r17)").

r22 addition (ISSUE 20): the soak feeds the telemetry historian — one
``historian.sample()`` per pass into ``--historyDir`` (default
``soak_history/`` in the repo root; ``--historyDir off`` disables). The
segments are the soak's durable black box: a SIGKILLed soak leaves CRC-
valid frames behind, and ``tools/history_report.py soak_history/``
reconstructs the RSS slope and tunnel-phase intervals from the leftovers
alone. The JSON line reports the segment-derived slope next to the
in-process one — the two estimators must agree, which is the historian's
own correctness check.

Usage: python tools/soak.py [--minutes M] [--tweets N]
       [--arena on|off] [--wireAssemble auto|on|off]
       [--maxRssSlopeMbPerMin X] [--configs both|dense|hash2e18]
       [--historyDir DIR|off]
Prints one JSON line at the end (exit 1 on a slope breach).

``--configs dense`` keeps only the dense ragged arm — the wire-heavy
config whose uploaded bytes drive the axon retention, and the one a
cpu-only control window can actually cycle (the 2^18 Gram step is
minutes per pass on the one-core host; on the chip it is ~21 ms).
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _slope_mb_per_min(samples: "list[tuple[float, float]]") -> float:
    """Least-squares RSS slope over (seconds, MB) samples — robust to the
    sawtooth a GC'd process shows, unlike endpoint deltas. Shared with the
    live ``host.rss_slope_mb_per_min`` gauge (utils/rss.py) so the soak
    report and the dashboard agree on the math."""
    from twtml_tpu.utils.rss import slope_mb_per_min

    return slope_mb_per_min(samples)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    minutes, n_tweets = 15.0, 65536
    arena_on, assemble_mode = True, "auto"
    max_slope = None
    configs = "both"
    history_dir = os.path.join(REPO, "soak_history")
    i = 0
    while i < len(args):
        if args[i] == "--minutes":
            minutes = float(args[i + 1]); i += 2
        elif args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--arena":
            arena_on = args[i + 1] == "on"; i += 2
        elif args[i] == "--wireAssemble":
            assemble_mode = args[i + 1]; i += 2
        elif args[i] == "--maxRssSlopeMbPerMin":
            max_slope = float(args[i + 1]); i += 2
        elif args[i] == "--configs":
            configs = args[i + 1]; i += 2
        elif args[i] == "--historyDir":
            history_dir = None if args[i + 1] == "off" else args[i + 1]
            i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.features import arena as _arena, assemble as _assemble
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.utils.benchloop import _run_once
    from twtml_tpu.utils.rss import rss_mb

    _assemble.configure(assemble_mode)
    _arena.set_enabled(arena_on)

    # durable long-horizon record (ISSUE 20): one historian sample per
    # pass; the segments survive a SIGKILL and history_report reconstructs
    # phase intervals + RSS slope from the leftovers alone
    from twtml_tpu.telemetry import historian as _historian
    from twtml_tpu.utils.runid import config_fingerprint, next_run_id

    if history_dir:
        _historian.configure(
            history_dir, max_mb=64,
            run_id=next_run_id(),
            fingerprint=config_fingerprint({
                "tool": "soak", "tweets": n_tweets, "configs": configs,
                "arena": arena_on, "wire_assemble": assemble_mode,
            }),
        )

    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    # per-pass pack leases, retired at the pass's completion fetch (every
    # dispatch has executed by then — the arena's retire-on-delivery rule)
    pass_leases: list = []

    def arm(f_text, batch, l2):
        feat = Featurizer(num_text_features=f_text, now_ms=1785320000000)
        chunks = [
            statuses[i : i + batch] for i in range(0, len(statuses), batch)
        ]

        def fz(c):
            pb = feat.featurize_batch_ragged(
                c, row_bucket=batch, pre_filtered=True, pack=True
            )
            lease = getattr(pb, "_lease", None)
            if lease is not None:
                pass_leases.append(lease)
            return pb

        model = StreamingLinearRegressionWithSGD(
            num_text_features=f_text, l2_reg=l2
        )
        float(model.step(fz(chunks[0])).mse)  # warm
        return model, fz, chunks

    arms = {}
    if configs in ("both", "dense"):
        # the r4 operating points (BENCHMARKS.md "r4 operating point")
        arms["dense_ragged_b16384"] = arm(1000, 16384, 0.0)
    if configs in ("both", "hash2e18"):
        arms["hash2e18_ragged_b3072"] = arm(2**18, 3072, 0.1)
    if not arms:
        raise SystemExit(f"unknown --configs {configs!r}")
    from twtml_tpu.utils.rss import RssWatchdog

    reference_mse: dict[str, float] = {}
    passes = {k: 0 for k in arms}
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # the same guard the app loops run (utils/rss.py): sample every pass,
    # warn with the axon-client diagnosis + checkpoint-restart workaround
    # as growth crosses each threshold — the soak records whether it fired
    watchdog = RssWatchdog(sample_every=1)
    t_start = time.perf_counter()
    t_end = t_start + minutes * 60
    rss_samples: "list[tuple[float, float]]" = [(0.0, rss_mb())]
    while time.perf_counter() < t_end:
        for name, (model, fz, chunks) in arms.items():
            model.reset()
            pass_leases.clear()
            _, last = _run_once(model, fz, chunks, prefetch=True)
            mse = float(last.mse)
            # completion fetch done ⇒ every dispatch consumed its wire:
            # the pass's leases retire to the pool (arena-on) or no-op
            for lease in pass_leases:
                lease.retire()
            pass_leases.clear()
            if name not in reference_mse:
                reference_mse[name] = mse
            elif mse != reference_mse[name]:
                raise SystemExit(
                    f"NUMERIC DRIFT in {name} pass {passes[name]}: "
                    f"{mse} != {reference_mse[name]}"
                )
            passes[name] += 1
            watchdog.tick()
            rss_samples.append(
                (time.perf_counter() - t_start, rss_mb())
            )
            _historian.sample()  # no-op when --historyDir off
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    slope = round(_slope_mb_per_min(rss_samples), 3)
    breach = max_slope is not None and slope > max_slope
    # segment-derived slope: re-read what actually hit disk and run the
    # same estimator over it — the historian's own durability check (a
    # disagreement means samples were lost or mis-framed)
    history_slope = None
    if history_dir:
        _historian.stamp_baseline()  # clean soak end → next run gets deltas
        _historian.uninstall()
        history_slope = round(
            _historian.rss_slope(_historian.read_series(history_dir)), 3
        )
    from twtml_tpu.features.arena import get_arena

    print(json.dumps({
        "soak_minutes": minutes,
        "tweets_per_pass": n_tweets,
        "passes": passes,
        "tweets_total": sum(passes.values()) * n_tweets,
        "final_mse": reference_mse,
        "bit_identical": True,
        "rss_growth_mb": round((rss1 - rss0) / 1024, 1),
        "rss_slope_mb_per_min": slope,
        "rss_slope_gate_mb_per_min": max_slope,
        "rss_slope_breach": breach,
        "rss_samples": len(rss_samples),
        "arena": "on" if arena_on else "off",
        "wire_assemble": assemble_mode,
        "arena_stats": get_arena().stats(),
        "rss_watchdog_warnings": watchdog.warn_count,
        "history_dir": history_dir,
        "history_rss_slope_mb_per_min": history_slope,
        "backend": jax.default_backend(),
    }))
    if breach:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
