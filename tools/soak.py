"""Endurance soak: alternate the flagship configs back-to-back on the chip
and assert numeric bit-stability — the r2/r3 reliability evidence
(BENCHMARKS.md "Endurance soaks").

Each round runs, on the SAME process/models: the dense ragged-wire pipeline
at the r4 headline operating point (batch 16384) and the 2^18 int8-Gram
config at its r4 operating point (batch 3072, ragged). Every pass resets
weights and streams the identical corpus, so the final-batch mse must be
BIT-IDENTICAL on every pass — any drift, leak-induced slowdown, or
transport wedge fails loudly.

Usage: python tools/soak.py [--minutes M] [--tweets N]
Prints one JSON line at the end.
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    minutes, n_tweets = 15.0, 65536
    i = 0
    while i < len(args):
        if args[i] == "--minutes":
            minutes = float(args[i + 1]); i += 2
        elif args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.utils.benchloop import _run_once

    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())

    def arm(f_text, batch, l2):
        feat = Featurizer(num_text_features=f_text, now_ms=1785320000000)
        chunks = [
            statuses[i : i + batch] for i in range(0, len(statuses), batch)
        ]

        def fz(c):
            return feat.featurize_batch_ragged(
                c, row_bucket=batch, pre_filtered=True, pack=True
            )

        model = StreamingLinearRegressionWithSGD(
            num_text_features=f_text, l2_reg=l2
        )
        float(model.step(fz(chunks[0])).mse)  # warm
        return model, fz, chunks

    arms = {
        # the r4 operating points (BENCHMARKS.md "r4 operating point")
        "dense_ragged_b16384": arm(1000, 16384, 0.0),
        "hash2e18_ragged_b3072": arm(2**18, 3072, 0.1),
    }
    from twtml_tpu.utils.rss import RssWatchdog

    reference_mse: dict[str, float] = {}
    passes = {k: 0 for k in arms}
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # the same guard the app loops run (utils/rss.py): sample every pass,
    # warn with the axon-client diagnosis + checkpoint-restart workaround
    # as growth crosses each threshold — the soak records whether it fired
    watchdog = RssWatchdog(sample_every=1)
    t_end = time.perf_counter() + minutes * 60
    while time.perf_counter() < t_end:
        for name, (model, fz, chunks) in arms.items():
            model.reset()
            _, last = _run_once(model, fz, chunks, prefetch=True)
            mse = float(last.mse)
            if name not in reference_mse:
                reference_mse[name] = mse
            elif mse != reference_mse[name]:
                raise SystemExit(
                    f"NUMERIC DRIFT in {name} pass {passes[name]}: "
                    f"{mse} != {reference_mse[name]}"
                )
            passes[name] += 1
            watchdog.tick()
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    print(json.dumps({
        "soak_minutes": minutes,
        "tweets_per_pass": n_tweets,
        "passes": passes,
        "tweets_total": sum(passes.values()) * n_tweets,
        "final_mse": reference_mse,
        "bit_identical": True,
        "rss_growth_mb": round((rss1 - rss0) / 1024, 1),
        "rss_watchdog_warnings": watchdog.warn_count,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
