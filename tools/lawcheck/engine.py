"""Walker, baseline, reporting, and the CLI contract.

Exit codes (stable, used by CI and tests/test_lawcheck.py):

- 0 — clean: no findings beyond suppressions and the baseline
- 1 — findings: at least one non-baselined, non-suppressed violation
- 2 — malformed: the CHECKER's inputs are broken (unparsable target file,
  reasonless/unknown-rule suppression, corrupt baseline) — failing loud
  beats reporting "clean" off unreadable inputs

The baseline file (``tools/lawcheck/baseline.json``) holds grandfathered
finding fingerprints. Target state: EMPTY — fix, don't baseline. Stale
entries (baselined findings that no longer fire) are reported so the file
shrinks monotonically.
"""

from __future__ import annotations

import ast
import json
import os
import sys

from .findings import Finding, Malformed
from .rules import FileContext, RepoContext, all_rules, rule_ids
from .suppress import scan as scan_suppressions

_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "node_modules", "doc"}
_DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def iter_py_files(root: str) -> list[str]:
    out: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in _SKIP_DIRS
        )
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return out


class Report:
    def __init__(self) -> None:
        self.findings: list[Finding] = []
        self.malformed: list[Malformed] = []
        self.suppressed: list[Finding] = []
        self.baselined: list[Finding] = []
        self.stale_baseline: list[str] = []

    @property
    def exit_code(self) -> int:
        if self.malformed:
            return 2
        if self.findings:
            return 1
        return 0

    def to_json(self) -> dict:
        return {
            "findings": [f.to_json() for f in self.findings],
            "malformed": [m.to_json() for m in self.malformed],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "stale_baseline": list(self.stale_baseline),
            "exit_code": self.exit_code,
        }


def _load_baseline(path: str, report: Report) -> set[str]:
    if not os.path.exists(path):
        return set()
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        entries = data["findings"]
        if not isinstance(entries, list) or not all(
            isinstance(e, str) for e in entries
        ):
            raise ValueError("'findings' must be a list of fingerprints")
    except Exception as exc:
        report.malformed.append(Malformed(
            os.path.relpath(path, repo_root()).replace(os.sep, "/"), 0,
            f"unreadable baseline: {exc}",
        ))
        return set()
    return set(entries)


def run_repo(root: str | None = None,
             baseline_path: str | None = None) -> Report:
    root = root or repo_root()
    baseline_path = baseline_path or _DEFAULT_BASELINE
    report = Report()
    known = rule_ids()
    rules = all_rules()

    contexts: list[FileContext] = []
    suppressions = {}
    for abspath in iter_py_files(root):
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            with open(abspath, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=rel)
        except (OSError, SyntaxError, ValueError) as exc:
            report.malformed.append(Malformed(
                rel, getattr(exc, "lineno", 0) or 0,
                f"cannot parse target file: {exc}",
            ))
            continue
        contexts.append(FileContext(rel, source, tree, source.splitlines()))
        sup = scan_suppressions(rel, source, known)
        report.malformed.extend(sup.malformed)
        suppressions[rel] = sup

    raw: list[Finding] = []
    repo_ctx = RepoContext(root, contexts)
    for rule in rules:
        for ctx in contexts:
            raw.extend(rule.check(ctx))
        raw.extend(rule.check_repo(repo_ctx))

    baseline = _load_baseline(baseline_path, report)
    seen_fingerprints: set[str] = set()
    deduped: dict[tuple, Finding] = {
        (f.rule, f.path, f.line): f for f in raw
    }
    for f in sorted(deduped.values(), key=lambda f: (f.path, f.line, f.rule)):
        seen_fingerprints.add(f.fingerprint)
        sup = suppressions.get(f.path)
        if sup is not None and sup.covers(f.line, f.rule):
            report.suppressed.append(f)
        elif f.fingerprint in baseline:
            report.baselined.append(f)
        else:
            report.findings.append(f)
    report.stale_baseline = sorted(baseline - seen_fingerprints)
    return report


def write_baseline(report: Report, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {
                "_comment": (
                    "grandfathered lawcheck findings — target state is "
                    "EMPTY: fix, don't baseline"
                ),
                "findings": sorted(
                    f.fingerprint
                    for f in report.findings + report.baselined
                ),
            },
            fh, indent=2,
        )
        fh.write("\n")


def _print_human(report: Report, out) -> None:
    for m in report.malformed:
        print(m.render(), file=out)
    for f in report.findings:
        print(f.render(), file=out)
    for fp in report.stale_baseline:
        print(f"note: stale baseline entry (no longer fires): {fp}",
              file=out)
    bits = [f"{len(report.findings)} finding(s)",
            f"{len(report.malformed)} malformed",
            f"{len(report.suppressed)} suppressed",
            f"{len(report.baselined)} baselined"]
    print("lawcheck: " + ", ".join(bits), file=out)


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m tools.lawcheck",
        description=(
            "Static analyzer for this repo's measured transport/parity "
            "laws (exit 0 clean / 1 findings / 2 malformed)"
        ),
    )
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: this checkout)")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: tools/lawcheck/"
                             "baseline.json)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings to the baseline file "
                             "(for grandfathering; target state is empty)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print every rule with the measured law it "
                             "encodes")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.title}")
            print(f"       law: {rule.law}")
        return 0

    report = run_repo(root=args.root, baseline_path=args.baseline)
    if args.write_baseline:
        write_baseline(
            report, args.baseline or _DEFAULT_BASELINE
        )
        print(f"baseline written "
              f"({len(report.findings) + len(report.baselined)} entries)")
        return 0
    if args.json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        _print_human(report, sys.stdout)
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
