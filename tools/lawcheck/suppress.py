"""Inline suppression comments: ``lawcheck: disable=TWxxx -- reason``.

A suppression silences named rules on ITS OWN line only, and the trailing
reason is mandatory — the whole point of the law checker is that every
deviation from a measured law carries its justification next to the code
(the same discipline BENCHMARKS.md applies to honest misses). A reasonless
suppression, an unknown rule id, or a malformed comment body is a
``Malformed`` record (exit 2), not a silent no-op: a typo'd suppression
that silently failed to apply would surface as a phantom finding, and one
that silently applied too broadly would hide real ones.

Grammar (one comment per line, after any code; one or more rule ids,
comma-separated, then ``--`` and the reason)::

    X = X.at[idx].set(v)  # lawcheck: disable=TW004 -- bounded K-sized scatter
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from .findings import Malformed

# the marker is permissive (any "lawcheck:" comment is inspected) so typos
# like "disable TW004" are caught as malformed instead of silently ignored
_MARKER = re.compile(r"#\s*lawcheck:\s*(?P<body>.*)$")
_DISABLE = re.compile(
    r"^disable=(?P<rules>[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
    r"(?:\s+--\s*(?P<reason>.*))?$"
)


@dataclass
class Suppressions:
    """Per-file map of line -> set of rule ids suppressed on that line."""

    by_line: dict[int, set[str]] = field(default_factory=dict)
    malformed: list[Malformed] = field(default_factory=list)

    def covers(self, line: int, rule: str) -> bool:
        return rule in self.by_line.get(line, ())


def scan(path: str, source: str, known_rules: frozenset[str]) -> Suppressions:
    out = Suppressions()
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _MARKER.search(text)
        if not m:
            continue
        body = m.group("body").strip()
        d = _DISABLE.match(body)
        if not d:
            out.malformed.append(Malformed(
                path, lineno,
                f"unrecognized lawcheck comment {body!r} — expected "
                "'disable=TWxxx[,TWyyy] -- reason'",
            ))
            continue
        reason = (d.group("reason") or "").strip()
        if not reason:
            out.malformed.append(Malformed(
                path, lineno,
                "suppression without a reason — every deviation from a "
                "measured law must carry its justification "
                "('disable=TW004 -- why this site is exempt')",
            ))
            continue
        rules = {r.strip() for r in d.group("rules").split(",")}
        unknown = sorted(rules - known_rules)
        if unknown:
            out.malformed.append(Malformed(
                path, lineno,
                f"suppression names unknown rule(s) {', '.join(unknown)} — "
                "see 'python -m tools.lawcheck --list-rules'",
            ))
            continue
        out.by_line.setdefault(lineno, set()).update(rules)
    return out
