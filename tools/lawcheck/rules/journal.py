"""Journal law: intake rows are journaled at ONE seam only.

The durable intake journal (streaming/journal.py, ISSUE 19) is replay-exact
ONLY because every row crosses exactly one append point — the post-parse,
pre-featurize seam in streaming/context.py. A second append site would
double-journal rows (replayed twice after a rollback → double-train), and an
append *after* featurize would journal rows a crash between the seam and the
step could lose. TW009 pins the seam the same way TW002 pins the fetch
seams.
"""

from __future__ import annotations

import ast

from ..findings import Finding
from . import FileContext, Rule
from .transport import dotted, import_aliases


class TW009JournalSeam(Rule):
    id = "TW009"
    title = "journal append outside the blessed intake seam"
    law = (
        "the intake journal is replay-exact only if rows are appended at "
        "exactly ONE seam (post-parse, pre-featurize: FeatureStream."
        "_process and StreamingContext._run_batch_aligned call journal."
        "record_intake); any other append site double-journals rows or "
        "journals them at a point a crash can tear away from the trained "
        "state (streaming/journal.py docstring; ISSUE 19)"
    )
    # the seam callers and the implementation itself
    SEAM_FILES = frozenset({
        "twtml_tpu/streaming/context.py",
        "twtml_tpu/streaming/journal.py",
    })

    def check(self, ctx: FileContext):
        if not ctx.path.startswith("twtml_tpu/"):
            return []
        if ctx.path in self.SEAM_FILES:
            return []
        aliases = import_aliases(ctx.tree)
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            path = dotted(node.func, aliases)
            if path.endswith("record_intake"):
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    "journal.record_intake outside the blessed intake seam "
                    "— " + self.law,
                ))
            elif path.endswith(".append") and "journal" in path.lower():
                # direct IntakeJournal.append through a journal-named handle
                # (e.g. _journal.get().append(...)) — same law, no detour
                # around the record_intake hook
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    "direct journal .append() outside the blessed intake "
                    "seam — " + self.law,
                ))
        return findings
