"""Host-side laws: silent exception swallows, wall-clock determinism.

TW005 — the reference's Try semantics *require* swallowing on telemetry
publish paths (a sick dashboard must never kill the pipeline — that is
Try-parity, PARITY.md), but the same ``except Exception: pass`` pattern
anywhere else is how lost rows, wedged threads, and dead guards hide. The
rule flags broad handlers that neither re-raise nor make any call (no log,
no counter, no fallback work); Try-parity modules are exempt by an
explicit per-file allowlist.

TW006 — PR 4's sentinel acceptance test holds only because runs are
replayable: the ``TWTML_NOW_MS`` env seam pins every clock that feeds
features or batch identity (features/featurizer.py). Lockstep, sentinel,
and serving code reading ``time.time()``/``datetime.now()`` directly
bypasses the seam and breaks bit-replay of the exact paths whose
correctness is proven by replay.
"""

from __future__ import annotations

import ast

from ..findings import Finding
from . import FileContext, Rule


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            return True
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """No re-raise and no call AT ALL in the handler body: nothing is
    logged, counted, or recovered — the failure simply vanishes."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Call)):
            return False
    return True


class TW005SilentSwallow(Rule):
    id = "TW005"
    title = "silent broad exception swallow outside Try-parity modules"
    law = (
        "reference Try semantics require swallowing ONLY on telemetry "
        "publish paths (a sick sink must never kill the pipeline — "
        "PARITY.md Try-parity); anywhere else a silent 'except Exception' "
        "is how lost rows and wedged guards hide. Log it, count it, or "
        "narrow it; per-file exemptions are for publish paths only"
    )
    # Try-parity exempt: modules whose JOB is to swallow publish/telemetry
    # failures, mirroring the reference's Try wrapping (PARITY.md).
    # session_stats/web_client/lightning are the publish paths themselves;
    # trace/blackbox/metrics sinks must never kill the pipeline either.
    TRY_PARITY_FILES = frozenset({
        "twtml_tpu/telemetry/session_stats.py",
        "twtml_tpu/telemetry/web_client.py",
        "twtml_tpu/telemetry/lightning.py",
        "twtml_tpu/telemetry/trace.py",
        "twtml_tpu/telemetry/blackbox.py",
        "twtml_tpu/telemetry/metrics.py",
    })

    def check(self, ctx: FileContext):
        if not ctx.path.startswith("twtml_tpu/"):
            return []
        if ctx.path in self.TRY_PARITY_FILES:
            return []
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) and (
                _is_silent(node)
            ):
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    "broad except swallows silently (no raise, no log, no "
                    "counter) — " + self.law,
                ))
        return findings


class TW006WallClock(Rule):
    id = "TW006"
    title = "raw wall clock in lockstep/sentinel/serving code"
    law = (
        "PR 4's sentinel acceptance test (poisoned run bit-equals clean "
        "run minus the poisoned batch) and the serving parity tests hold "
        "only under the TWTML_NOW_MS determinism seam; direct "
        "time.time()/datetime.now() in these paths breaks bit-replay — "
        "use utils/clock.now_ms()/now_s() (time.monotonic() for pure "
        "intervals is fine and not flagged)"
    )
    # the deterministic-replay surfaces: the lockstep scheduler, the
    # sentinel/delivery layer, and the serving plane
    SCOPE = (
        "twtml_tpu/streaming/context.py",
        "twtml_tpu/apps/common.py",
        "twtml_tpu/apps/serve.py",
        "twtml_tpu/serving/",
    )
    _WALL_CLOCK = frozenset({
        "time.time", "datetime.now", "datetime.utcnow",
        "datetime.datetime.now", "datetime.datetime.utcnow",
    })

    def check(self, ctx: FileContext):
        if not any(
            ctx.path == s or (s.endswith("/") and ctx.path.startswith(s))
            for s in self.SCOPE
        ):
            return []
        from .transport import dotted

        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and dotted(node.func) in (
                self._WALL_CLOCK
            ):
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    f"{dotted(node.func)}() in deterministic-replay code "
                    "bypasses the TWTML_NOW_MS seam — " + self.law,
                ))
        return findings
