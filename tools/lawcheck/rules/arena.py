"""TW008 — the pooled wire arena is a paid-for law (r17).

The measured facts: the host has ONE usable core and host work sits right
under tunnel uploads on the r2/r3 bottleneck ladder, and host RSS grows
∝ uploaded bytes through the axon tunnel client (~4-6 MB per 65k-tweet
pass — transfer-buffer retention, BENCHMARKS.md r3 soak). Fresh per-tick
wire-destination buffers pay both: allocator churn on the packing core,
and ever-new pages for the client to retain. r17's arena
(``twtml_tpu/features/arena.py``) fixes this by leasing pooled
destination buffers that retire when the batch's stats fetch delivers —
so a fresh wire-sized allocation in the pack hot path is a regression,
not a style choice.

The rule: inside the pack-path functions of the scoped modules (function
names starting with ``pack_`` or ``try_assemble``, plus the pipelines'
``_group_wire``), a direct ``np.empty``/``np.zeros``/``bytearray`` call
or a ``np.concatenate`` without an ``out=`` destination is a finding —
the destination must come from the arena (``lease_wire`` /
``_finish_pack``). Ground-truth helpers that build intermediate field
views (``np.stack``/``np.ascontiguousarray``) are not flagged: the law
covers the FINAL wire buffer, the one the transport client retains.
"""

from __future__ import annotations

import ast

from ..findings import Finding
from . import FileContext, Rule


class TW008WireArena(Rule):
    id = "TW008"
    title = "fresh wire-buffer allocation in the pack hot path (no arena)"
    law = (
        "host RSS grows ∝ uploaded bytes (axon transfer-buffer retention, "
        "BENCHMARKS.md r3 soak) and the one-core host pays allocator "
        "churn for every per-tick wire buffer; pack-path destination "
        "buffers must lease from twtml_tpu/features/arena.py "
        "(lease_wire / _finish_pack), retiring on fetch delivery"
    )
    # the pack/dispatch hot path: every module that builds a wire buffer
    # the transport client will see — r18 extended the law one rung up
    # the ladder to the fused featurize emitters (features/
    # featurize_native.py: the one-pass fill's destination arrays are
    # wire-adjacent and per-tick, so a fresh allocation there is the
    # same regression class)
    SCOPE = (
        "twtml_tpu/features/batch.py",
        "twtml_tpu/features/assemble.py",
        "twtml_tpu/features/featurize_native.py",
        "twtml_tpu/apps/common.py",
        "twtml_tpu/parallel/sharding.py",
        "twtml_tpu/parallel/distributed.py",
        "twtml_tpu/parallel/tenants.py",
    )
    _ALLOC = frozenset({
        "np.empty", "np.zeros", "numpy.empty", "numpy.zeros", "bytearray",
    })

    @staticmethod
    def _pack_functions(tree: ast.AST):
        for node in ast.walk(tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and (
                node.name.startswith("pack_")
                or node.name.startswith("try_assemble")
                or node.name.startswith("try_fill")
                or node.name in ("_group_wire", "_lease_views")
            ):
                yield node

    def check(self, ctx: FileContext):
        if ctx.path not in self.SCOPE:
            return []
        from .transport import dotted

        findings: list[Finding] = []
        for fn in self._pack_functions(ctx.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if name in self._ALLOC:
                    findings.append(Finding(
                        self.id, ctx.path, node.lineno,
                        f"{name}() allocates a fresh buffer inside pack-"
                        f"path function {fn.name}() — lease it from the "
                        "arena instead; " + self.law,
                    ))
                elif name in ("np.concatenate", "numpy.concatenate") and (
                    not any(kw.arg == "out" for kw in node.keywords)
                ):
                    findings.append(Finding(
                        self.id, ctx.path, node.lineno,
                        f"np.concatenate() without out= inside pack-path "
                        f"function {fn.name}() materializes a fresh wire "
                        "buffer — concatenate into an arena lease "
                        "(_finish_pack); " + self.law,
                    ))
        return findings
