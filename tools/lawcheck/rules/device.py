"""Device-program law: no scatter in jitted step code (the Gram densify).

The one XLA trap that cost a full benchmark round: a [B*L]-update scatter
into the [B, 2^18] feature space runs ~220 ns/update SERIALIZED on this
backend — the 2^18 sparse config only became viable when ops/gram.py
replaced 50 scatters per batch with one [B, B] Gram matmul (one-hot
two-level matmul densify, ~21 ms/step). Any ``.at[...].add/.set`` that
creeps back into step code silently reopens that cliff, and nothing at
runtime would flag it — the program still produces correct bits, just
hundreds of times slower.
"""

from __future__ import annotations

import ast

from ..findings import Finding
from . import FileContext, Rule

_SCATTER_METHODS = frozenset({
    "add", "set", "mul", "multiply", "divide", "min", "max", "power",
    "apply", "get",
})


class TW004Scatter(Rule):
    id = "TW004"
    title = "indexed-update scatter in jitted step code"
    law = (
        "a [B*L]-update scatter into [B, 2^18] runs ~220 ns/update "
        "serialized on this backend; ops/gram.py's one-hot two-level "
        "matmul densify replaced it (one [B,B] Gram matmul per batch, "
        "~21 ms/step at 2^18) — scatters must not creep back into step "
        "code (BENCHMARKS.md 'XLA perf traps'; CLAUDE.md). Bounded "
        "small-domain scatters (K centers, fixed columns) are exempt via "
        "an inline suppression stating the bound"
    )

    def check(self, ctx: FileContext):
        if not (ctx.path.startswith("twtml_tpu/ops/")
                or ctx.path.startswith("twtml_tpu/models/")):
            return []
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            # X.at[idx].add(v): Call(func=Attribute(value=Subscript(
            #   value=Attribute(attr='at')), attr='add'))
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SCATTER_METHODS
                    and isinstance(node.func.value, ast.Subscript)
                    and isinstance(node.func.value.value, ast.Attribute)
                    and node.func.value.value.attr == "at"):
                continue
            findings.append(Finding(
                self.id, ctx.path, node.lineno,
                f".at[...].{node.func.attr}() indexed update in step code "
                "— " + self.law,
            ))
        return findings
