"""Rule registry. One rule per measured law; ids are stable (baseline and
suppression comments reference them), so retired rules must not be reused.

A rule is either per-file (``check(FileContext) -> list[Finding]``) or
repo-level (``check_repo(RepoContext) -> list[Finding]``, for laws that
relate files to each other, like flag/doc sync). Rules never import jax:
the checker must run in milliseconds with no backend side effects.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass


@dataclass
class FileContext:
    path: str  # repo-relative posix
    source: str
    tree: ast.AST
    lines: list[str]


@dataclass
class RepoContext:
    root: str  # absolute repo root
    files: "list[FileContext]"  # every scanned python file, parsed

    def get(self, path: str) -> "FileContext | None":
        for f in self.files:
            if f.path == path:
                return f
        return None


class Rule:
    id: str = ""
    title: str = ""  # one line, shown by --list-rules and cited in docs
    law: str = ""  # the measured fact this encodes, with its source doc

    def check(self, ctx: FileContext):  # per-file rules override
        return []

    def check_repo(self, repo: RepoContext):  # repo-level rules override
        return []


def all_rules() -> "list[Rule]":
    from .arena import TW008WireArena
    from .device import TW004Scatter
    from .docs import TW007FlagDocs
    from .historian import TW010HistorianSeam
    from .host import TW005SilentSwallow, TW006WallClock
    from .journal import TW009JournalSeam
    from .transport import TW001BackendInit, TW002FetchSeam, TW003ThreadPut

    return [
        TW001BackendInit(),
        TW002FetchSeam(),
        TW003ThreadPut(),
        TW004Scatter(),
        TW005SilentSwallow(),
        TW006WallClock(),
        TW007FlagDocs(),
        TW008WireArena(),
        TW009JournalSeam(),
        TW010HistorianSeam(),
    ]


def rule_ids() -> frozenset[str]:
    return frozenset(r.id for r in all_rules())
