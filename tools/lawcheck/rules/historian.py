"""Historian law: telemetry samples are taken at ONE seam only.

The telemetry historian (telemetry/historian.py, ISSUE 20) is zero-cost by
construction ONLY because ``historian.sample()`` runs at the existing
stats-publish cadence — it snapshots registry/health/stage views that
publish tick already computed. A second sampling site would either pay new
snapshot work on a hot path or, worse, tempt a caller into fetching device
state "for the historian" — the exact failure mode the counted-fetch tests
exist to prevent. TW010 pins the seam the same way TW009 pins the journal's
intake seam.
"""

from __future__ import annotations

import ast

from ..findings import Finding
from . import FileContext, Rule
from .transport import dotted, import_aliases


class TW010HistorianSeam(Rule):
    id = "TW010"
    title = "historian sampling outside the blessed publish seam"
    law = (
        "the telemetry historian adds zero fetches/collectives only "
        "because historian.sample() is called from exactly ONE seam — "
        "SessionStats.publish_metrics, which has already computed every "
        "view the sample snapshots; any other sampling site pays new "
        "snapshot work on a hot path or invites a device fetch the "
        "counted-fetch law forbids (telemetry/historian.py docstring; "
        "ISSUE 20)"
    )
    # the seam caller and the implementation itself
    SEAM_FILES = frozenset({
        "twtml_tpu/telemetry/session_stats.py",
        "twtml_tpu/telemetry/historian.py",
    })

    def check(self, ctx: FileContext):
        if not ctx.path.startswith("twtml_tpu/"):
            return []
        if ctx.path in self.SEAM_FILES:
            return []
        aliases = import_aliases(ctx.tree)
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            path = dotted(node.func, aliases)
            # match the module hook (historian.sample / _historian.sample)
            # and the instance method through a historian-named handle
            # (historian.get().sample()) — but not random.sample and
            # friends: the receiver must be historian-flavored
            if path.endswith(".sample") and "histor" in path.lower():
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    "historian.sample() outside the blessed publish seam "
                    "— " + self.law,
                ))
        return findings
