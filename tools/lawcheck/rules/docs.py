"""Flag/doc sync (TW007): the CLI surface and the docs must agree.

PARITY.md is judged against SURVEY.md line by line, and every PR's flags
are part of its reviewed surface — a ``--flag`` that exists but is
documented nowhere is unusable (and unreviewable), while a doc that names
a flag that no longer parses sends operators into ``printUsage(1)``. Both
directions are pure static facts, so they are checked here: every flag
registered in ``twtml_tpu/config.py``'s parser must appear in README.md or
SCALING.md, and every ``--flag`` a doc mentions must exist somewhere in
the repo's parsers (config.py, or a tools/ script's argument handling).
"""

from __future__ import annotations

import ast
import os
import re

from ..findings import Finding
from . import RepoContext, Rule

_FLAG_RE = re.compile(r"--[A-Za-z][A-Za-z0-9-]*")
_CONFIG = "twtml_tpu/config.py"
_DOCS = ("README.md", "SCALING.md")
# docs the rule searches for registered flags, beyond the two canonical
# ones: a flag documented only in BENCHMARKS/CLAUDE does NOT count as
# documented (operators read README/SCALING), but a doc-mentioned flag is
# resolved against every scanned python file
_GENERIC_DOC_FLAGS = frozenset({
    # conventional long options of third-party tools mentioned in docs
    # (pytest/pip/git examples); not part of this repo's surface
    "--help", "--version",
})


class TW007FlagDocs(Rule):
    id = "TW007"
    title = "--flag registered but undocumented, or documented but gone"
    law = (
        "the flag surface is part of the reviewed parity surface "
        "(PARITY.md is checked against SURVEY.md line by line); every "
        "--flag registered in config.py must appear in README.md or "
        "SCALING.md, and every --flag the docs mention must exist in a "
        "parser (config.py or a tools/ script)"
    )

    def registered_flags(self, repo: RepoContext) -> dict[str, int]:
        """--flag -> registration line, from the string constants inside
        config.py's ``parse`` method (the ground-truth flag surface)."""
        ctx = repo.get(_CONFIG)
        if ctx is None:
            return {}
        out: dict[str, int] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
                node.name == "parse"
            ):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str
                    ) and _FLAG_RE.fullmatch(sub.value):
                        out.setdefault(sub.value, sub.lineno)
        return out

    def known_flag_universe(self, repo: RepoContext) -> set[str]:
        """Every --flag string that appears in any scanned python source:
        config.py registrations plus tools/ arg handling (argparse strings,
        manual sys.argv matching) — the set a doc mention must resolve
        against."""
        universe: set[str] = set()
        for f in repo.files:
            universe.update(_FLAG_RE.findall(f.source))
        return universe

    def check_repo(self, repo: RepoContext):
        findings: list[Finding] = []
        registered = self.registered_flags(repo)
        if not registered:
            findings.append(Finding(
                self.id, _CONFIG, 0,
                "could not extract any registered --flags from config.py's "
                "parse() — the rule's ground truth moved; update "
                "tools/lawcheck/rules/docs.py",
            ))
            return findings

        doc_text: dict[str, list[str]] = {}
        for doc in _DOCS:
            p = os.path.join(repo.root, doc)
            if os.path.exists(p):
                with open(p, "r", encoding="utf-8") as fh:
                    doc_text[doc] = fh.read().splitlines()

        # direction 1: registered flag must appear in README or SCALING
        all_doc_flags: set[str] = set()
        for doc, lines in doc_text.items():
            for text in lines:
                all_doc_flags.update(_FLAG_RE.findall(text))
        for flag, lineno in sorted(registered.items()):
            if flag == "--help":
                continue  # self-documenting via printUsage
            if flag not in all_doc_flags:
                findings.append(Finding(
                    self.id, _CONFIG, lineno,
                    f"{flag} is registered in config.py but documented in "
                    "neither README.md nor SCALING.md — " + self.law,
                ))

        # direction 2: doc-mentioned flag must exist in some parser
        universe = self.known_flag_universe(repo) | _GENERIC_DOC_FLAGS
        for doc, lines in doc_text.items():
            seen: set[str] = set()
            for lineno, text in enumerate(lines, start=1):
                for flag in _FLAG_RE.findall(text):
                    if flag in universe or flag in seen:
                        continue
                    seen.add(flag)  # one finding per doc per flag
                    findings.append(Finding(
                        self.id, doc, lineno,
                        f"{flag} is mentioned here but exists in no parser "
                        "(config.py or any scanned script) — " + self.law,
                    ))
        return findings
