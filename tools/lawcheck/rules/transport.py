"""Transport laws: backend-init timing, fetch seams, thread-side puts.

These three rules encode the measured facts that shaped every transport
design in this repo (BENCHMARKS.md "Measurement integrity" + r2/r3
transport sections; CLAUDE.md restates them as working rules):

- the conftest/driver must pin the virtual mesh BEFORE any backend init,
  so no module may touch the backend at import time (TW001);
- every host fetch is a ~70-100 ms RTT-bound round trip, so fetches flow
  ONLY through the counted seams that pipeline and meter them (TW002);
- ``jax.device_put`` from a non-main thread collapses tunnel throughput
  (the r2 put-collapse), so no thread-target/executor-submitted code may
  reach a put (TW003).
"""

from __future__ import annotations

import ast

from ..findings import Finding
from . import FileContext, Rule

# jax APIs whose CALL initializes (or requires) a live backend
_BACKEND_FNS = frozenset({
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.default_backend", "jax.device_put",
    "jax.device_get", "jax.process_index", "jax.process_count",
    "jax.live_arrays",
})


def import_aliases(tree: ast.AST) -> dict[str, str]:
    """Map local names to the dotted module path they are bound to, for
    jax-family imports anywhere in the file (module scope or inline)."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    out[(a.asname or a.name.split(".")[0])] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
        elif isinstance(node, ast.ImportFrom) and node.module and (
            node.module == "jax" or node.module.startswith("jax.")
        ):
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def dotted(node: ast.expr, aliases: dict[str, str] | None = None) -> str:
    """Best-effort dotted path of an expression ("jax.numpy.zeros",
    "self._worker"); alias-expanded when ``aliases`` is given."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        head = node.id
        if aliases and head in aliases:
            head = aliases[head]
        parts.append(head)
    elif isinstance(node, ast.Call):
        # chained call like jnp.zeros(8).block_until_ready(): recurse into
        # the call's own callee so the chain still resolves
        inner = dotted(node.func, aliases)
        parts.append(f"{inner}()")
    else:
        return ""
    return ".".join(reversed(parts))


class TW001BackendInit(Rule):
    id = "TW001"
    title = "module-scope jax backend initialization"
    law = (
        "tests/conftest.py pins the 8-device virtual CPU mesh BEFORE any "
        "jax backend init, and the driver entry does the same via "
        "utils/backend.py; a module-scope jax.devices()/device_put/jnp "
        "array construction initializes the backend at import time, "
        "silently breaking the mesh pin for every later test/run "
        "(CLAUDE.md tests rule; utils/backend.py docstring)"
    )
    # the two places whose JOB is pre-init backend configuration
    ALLOW = frozenset({"tests/conftest.py", "twtml_tpu/utils/backend.py"})

    def check(self, ctx: FileContext):
        if ctx.path in self.ALLOW:
            return []
        aliases = import_aliases(ctx.tree)
        findings: list[Finding] = []
        for stmt in self._import_time_statements(ctx.tree):
            for node in self._calls_outside_defs(stmt):
                self._check_call(node, aliases, findings, ctx)
        return findings

    def _import_time_statements(self, tree):
        """Module-level statements plus class bodies (both execute at
        import), recursing through module-level if/try/with/for blocks."""
        out = []

        def visit(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.ClassDef):
                    visit(stmt.body)
                    continue
                if isinstance(stmt, (ast.If, ast.Try, ast.With, ast.For, ast.While)):
                    out.append(stmt)  # headers/bodies below are filtered
                    visit(getattr(stmt, "body", []))
                    visit(getattr(stmt, "orelse", []))
                    visit(getattr(stmt, "finalbody", []))
                    for h in getattr(stmt, "handlers", []):
                        visit(h.body)
                    continue
                out.append(stmt)
        visit(tree.body)
        return out

    def _calls_outside_defs(self, stmt):
        """Call nodes in a statement, not descending into nested defs or
        lambdas (those run later) or nested block statements (already
        visited separately)."""
        calls = []
        stack = [stmt]
        while stack:
            node = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                if node is stmt and isinstance(child, (ast.If, ast.Try, ast.With,
                                                       ast.For, ast.While)):
                    continue  # its statements were collected on their own
                if isinstance(child, ast.Call):
                    calls.append(child)
                stack.append(child)
        return calls

    def _check_call(self, node, aliases, findings, ctx):
        path = dotted(node.func, aliases)
        if not path.startswith("jax"):
            return
        if path in _BACKEND_FNS or path.startswith("jax.numpy.") or (
            path.startswith("jax.random.")
        ):
            findings.append(Finding(
                self.id, ctx.path, node.lineno,
                f"import-time call to {path}() initializes the jax backend "
                "before the conftest/driver mesh pin — " + self.law,
            ))


class TW002FetchSeam(Rule):
    id = "TW002"
    title = "host fetch outside the blessed counted seams"
    law = (
        "every host fetch is a ~70-100 ms RTT round trip through the "
        "tunnel, and block_until_ready is NOT a cheap sync (matmuls "
        "'finish' in us; a per-step sync with uploads in flight costs "
        "~70 ms EACH) — all fetches must flow through the counted seams "
        "(apps/common.FetchPipeline, benchloop.measure_pipeline/"
        "measure_passes) so the one-fetch-per-tick law stays countable "
        "(BENCHMARKS.md 'Measurement integrity'; CLAUDE.md)"
    )
    # the seam implementations themselves; tests/ and tools/ are out of
    # scope by construction (counting tests monkeypatch device_get, benches
    # build measurement arms)
    SEAM_FILES = frozenset({
        "twtml_tpu/apps/common.py",
        "twtml_tpu/utils/benchloop.py",
    })

    def check(self, ctx: FileContext):
        if not ctx.path.startswith("twtml_tpu/"):
            return []
        if ctx.path in self.SEAM_FILES:
            return []
        aliases = import_aliases(ctx.tree)
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            path = dotted(node.func, aliases)
            if path == "jax.device_get" or path.endswith(".device_get") and (
                path.startswith("jax")
            ):
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    "jax.device_get outside the blessed fetch seams — "
                    + self.law,
                ))
            elif isinstance(node.func, ast.Attribute) and (
                node.func.attr == "block_until_ready"
            ):
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    ".block_until_ready() outside the blessed fetch seams "
                    "— " + self.law,
                ))
        return findings


class TW003ThreadPut(Rule):
    id = "TW003"
    title = "device_put reachable from a thread target"
    law = (
        "jax.device_put from a non-main thread collapses tunnel upload "
        "throughput (the r2 put-collapse; concurrent device_GETs pipeline "
        "6.2x at depth 8, but puts stay main-thread — BENCHMARKS.md r2/r3 "
        "transport facts; CLAUDE.md)"
    )

    def check(self, ctx: FileContext):
        if not (ctx.path.startswith("twtml_tpu/")
                or ctx.path.startswith("tools/")
                or ctx.path in ("bench.py", "__graft_entry__.py")):
            return []
        aliases = import_aliases(ctx.tree)
        findings: list[Finding] = []
        module_funcs = {
            s.name: s for s in ctx.tree.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        class_methods: dict[str, dict[str, ast.AST]] = {
            s.name: {
                m.name: m for m in s.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for s in ctx.tree.body if isinstance(s, ast.ClassDef)
        }

        # walk with scope tracking: (enclosing class name, local func defs)
        def visit(node, cls: str | None, local_funcs: list[dict]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, local_funcs)
                    continue
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested = {
                        s.name: s for s in ast.walk(child)
                        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and s is not child
                    }
                    visit(child, cls, local_funcs + [nested])
                    continue
                if isinstance(child, ast.Call):
                    self._check_spawn(
                        child, cls, local_funcs, module_funcs,
                        class_methods, aliases, findings, ctx,
                    )
                visit(child, cls, local_funcs)

        visit(ctx.tree, None, [])
        return findings

    def _spawn_target(self, call: ast.Call, aliases) -> ast.expr | None:
        """The callable expression a spawn site hands to another thread:
        ``threading.Thread(target=X)`` or ``<executor>.submit(X, ...)``."""
        path = dotted(call.func, aliases)
        if path.endswith("Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    return kw.value
            return None
        if isinstance(call.func, ast.Attribute) and call.func.attr == "submit":
            return call.args[0] if call.args else None
        return None

    def _check_spawn(self, call, cls, local_funcs, module_funcs,
                     class_methods, aliases, findings, ctx):
        target = self._spawn_target(call, aliases)
        if target is None:
            return
        # unwrap functools.partial(f, ...)
        if isinstance(target, ast.Call) and dotted(
            target.func, aliases
        ).endswith("partial") and target.args:
            target = target.args[0]
        offender = self._target_reaches_put(
            target, cls, local_funcs, module_funcs, class_methods, aliases,
        )
        if offender:
            findings.append(Finding(
                self.id, ctx.path, call.lineno,
                f"thread/executor target reaches jax.device_put via "
                f"{offender} — " + self.law,
            ))

    def _resolve(self, expr, cls, local_funcs, module_funcs, class_methods):
        """Callable expression -> function AST node, same module only."""
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Name):
            for scope in reversed(local_funcs):
                if expr.id in scope:
                    return scope[expr.id]
            return module_funcs.get(expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ) and expr.value.id == "self" and cls:
            return class_methods.get(cls, {}).get(expr.attr)
        return None

    def _has_put(self, fn, aliases) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                p = dotted(node.func, aliases)
                if p == "device_put" or p.endswith(".device_put"):
                    return True
        return False

    def _target_reaches_put(self, target, cls, local_funcs, module_funcs,
                            class_methods, aliases) -> str | None:
        # direct handle: submit(jax.device_put, x)
        tpath = dotted(target, aliases)
        if tpath == "device_put" or tpath.endswith(".device_put"):
            return tpath
        fn = self._resolve(target, cls, local_funcs, module_funcs, class_methods)
        if fn is None:
            return None
        name = getattr(fn, "name", "<lambda>")
        if self._has_put(fn, aliases):
            return f"{name}()"
        # one level deep: same-module callees of the target
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = self._resolve(
                node.func, cls, local_funcs, module_funcs, class_methods
            )
            if callee is not None and callee is not fn and self._has_put(
                callee, aliases
            ):
                return f"{name}() -> {getattr(callee, 'name', '<lambda>')}()"
        return None
