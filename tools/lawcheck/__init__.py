"""Law-checker: a repo-specific static analyzer for the measured laws.

Nine PRs of benchmarking bought a set of *measured* transport/parity
invariants — one counted fetch per tick, main-thread-only ``device_put``
(the r2 throughput collapse), no scatter into 2^18 (the ~220 ns/update XLA
serialization trap), Try-parity on publish paths, no module-scope backend
init before the conftest mesh pin, the ``TWTML_NOW_MS`` determinism seam,
and flag/doc sync. Each was enforced only by convention and a handful of
runtime counting tests; a single unreviewed call site could silently
reintroduce a failure mode that cost a benchmark round to discover. This
package enforces them over the AST, in CI, before any TPU window is spent.

One rule per law (``python -m tools.lawcheck --list-rules``); every finding
message cites the BENCHMARKS.md/CLAUDE.md fact it encodes. Pure stdlib
(``ast``), no jax import, no third-party deps.

Usage::

    python -m tools.lawcheck            # exit 0 clean / 1 findings / 2 malformed
    python -m tools.lawcheck --json     # machine-readable findings
    # lawcheck: disable=TW004 -- <reason>   (inline, reason REQUIRED)

The checked-in baseline (``tools/lawcheck/baseline.json``) exists for
grandfathered findings and is kept EMPTY on purpose: fix, don't baseline.
"""

from .engine import main, run_repo  # noqa: F401
from .findings import Finding, Malformed  # noqa: F401
