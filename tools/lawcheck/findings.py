"""Finding/Malformed records and their wire forms.

A ``Finding`` is one rule violation at one source location; its
``fingerprint`` (``RULE:path:line``) is the baseline key. ``Malformed`` is
a defect in the *checking machinery itself* — an unparsable target file, a
suppression comment without the required reason, an unknown rule id in a
suppression, a corrupt baseline — and maps to exit code 2: a law checker
that cannot read its inputs must fail loudly, not report "clean".
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    rule: str  # "TW001".."TW007"
    path: str  # repo-relative posix path ("" for repo-level rules)
    line: int  # 1-based; 0 for repo-level findings with no anchor line
    message: str  # states the violation AND cites the measured law

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass(frozen=True)
class Malformed:
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: MALFORMED {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": "MALFORMED",
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }
