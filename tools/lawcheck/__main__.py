"""``python -m tools.lawcheck`` — the CI gate entry point."""

from .engine import main

raise SystemExit(main())
