"""Benchmark suite — one measurement per BASELINE.md target config.

BASELINE.md lists five configs to measure (the reference publishes no
numbers, so every baseline is measured, not copied):

  1. replay_linear     — streaming linear regression on a replayed
                         (deterministic synthetic) tweet stream
  2. twitter_live      — same on the live Twitter stream (needs OAuth creds
                         + network; reported as skipped when absent)
  3. logistic_sentiment— streaming logistic regression, lexicon sentiment
                         labels (BASELINE config #3)
  4. hashing_2e18_l2   — 2^18-dim HashingTF featurizer + L2-regularized SGD,
                         the sparse gather/scatter path (config #4)
  5. sharded_dp4       — 4-way data-parallel mesh, per-shard stream +
                         in-program psum gradient reduce (config #5; virtual
                         CPU mesh when <4 real chips are attached)
  6. sharded_dp4_logistic — the logistic learner on the same 4-way mesh
                         (sentiment labels; non-least-squares residual
                         through the sharded step)
  7. sharded_2e18_2d   — config #4's 2^18 feature space on the 2D
                         (data × model) mesh: feature-sharded weights, the
                         Gram dual loop's per-batch collective schedule
                         (SURVEY §5.7's long-context analog, distributed)

Each config runs in its own subprocess (clean jax backend state) and prints
one JSON line: {"config", "tweets_per_sec", "seconds", "batches", "final_metric",
"backend", "skipped"?}. The headline single-number benchmark stays bench.py.

Usage: python tools/bench_suite.py [--tweets N] [--batch B] [--json out.jsonl]
       [--configs name,name,...]   (default: all)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CONFIGS = [
    "replay_linear",
    "twitter_live",
    "logistic_sentiment",
    "hashing_2e18_l2",
    "sharded_dp4",
    "sharded_dp4_logistic",
    "sharded_2e18_2d",
    "multi_tenant_m8",
    "serving_qps",
    "wire_codec",
    "featurize",
]


def _status_json(s) -> dict:
    """Status → the wire-format tweet JSON object (recursive on retweets)."""
    d = {
        "text": s.text,
        "retweet_count": s.retweet_count,
        "user": {
            "followers_count": s.followers_count,
            "favourites_count": s.favourites_count,
            "friends_count": s.friends_count,
        },
        "timestamp_ms": str(s.created_at_ms),
        "lang": s.lang or "en",
    }
    if s.retweeted_status is not None:
        d["retweeted_status"] = _status_json(s.retweeted_status)
    return d


def _pipeline_rate(model, feat, statuses, batch_size, row_multiple=1, shard=None,
                   ragged=False, pack=True):
    """The shared double-buffered pipeline (utils/benchloop.py), with the
    suite's per-config featurizer/shard hooks. ``pack=False`` hands the
    model the UNPACKED ragged batch — models that build their own wire at
    the step boundary (the tenant plane's routed stack) need it raw."""
    from twtml_tpu.utils.benchloop import measure_pipeline

    chunks = [statuses[i : i + batch_size] for i in range(0, len(statuses), batch_size)]

    def featurize(chunk):
        # units wire format → bigram hashing on device (ops/text_hash.py);
        # ragged = concatenated units, no pad bytes, shipped as ONE packed
        # buffer (features/batch.py — both measured wins, BENCHMARKS.md)
        b = (
            feat.featurize_batch_ragged(
                chunk, row_bucket=batch_size, pre_filtered=True,
                row_multiple=row_multiple, pack=pack,
            )
            if ragged
            else feat.featurize_batch_units(
                chunk, row_bucket=batch_size, pre_filtered=True,
                row_multiple=row_multiple,
            )
        )
        return shard(b) if shard else b

    # best-of-3: the tunnel to the accelerator jitters (see bench.py)
    out = measure_pipeline(model, featurize, chunks, repeats=3)
    return {
        "tweets_per_sec": round(out["tweets_per_sec"], 1),
        "seconds": round(out["seconds"], 3),
        "batches": out["batches"],
        "final_metric": round(out["final_mse"], 3),
    }


def run_config(name: str, n_tweets: int, batch_size: int = 0) -> dict:
    """``batch_size`` 0 = the per-config r4 operating point (the dict
    below; 2048 where no sweep moved it); an explicit value is honored
    everywhere."""
    explicit_batch = batch_size > 0
    # per-config r4 operating points (paired sweeps, BENCHMARKS.md "r4
    # operating point"): the upload-bound transport rewards larger batches
    # once per-batch fixed costs dominate — block ingest (#1) measured
    # 1.155x paired at b8192 vs b2048; the object-ingest dense pipeline
    # (#3 shares the headline's profile) 1.62x at b16384. Mesh configs
    # keep 2048 (program validation on a virtual CPU mesh, not a speed
    # claim).
    # Explicit --batch always wins; default batches cap at n_tweets/4 so
    # a small-corpus run still measures a multi-chunk pipeline instead of
    # one half-padding batch.
    # (config #4 stays at 2048: the b3072 long-pass win inverts at the
    # suite's shorter pass shape — an A/B/A/B suite run measured b2048
    # 139-154k vs b3072 118-123k in one window, and 65536 divides 2048
    # exactly; b3072 remains the LONG-pass operating point, re-checkable
    # via tools/bench_2e18.py's b3072 arm)
    if not explicit_batch:
        batch_size = {
            "replay_linear": 8192,
            "logistic_sentiment": 16384,
        }.get(name, 2048)
        batch_size = max(256, min(batch_size, n_tweets // 4 or batch_size))
    import jax

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import SyntheticSource

    out: dict = {"config": name}

    if name == "twitter_live":
        from twtml_tpu.config import ConfArguments, get_property, set_property

        conf = ConfArguments().parse(["--source", "twitter"])
        creds = [
            get_property("twitter4j.oauth." + k)
            for k in ("consumerKey", "consumerSecret", "accessToken", "accessTokenSecret")
        ]
        from twtml_tpu.apps import linear_regression as app

        if all(creds):
            # Live measurement: run the real app for ~6 batches and report
            # its observed ingest rate (bounded by the stream, not compute).
            t0 = time.perf_counter()
            totals = app.run(conf, max_batches=6)
            dt = time.perf_counter() - t0
            return {
                **out,
                "tweets_per_sec": round(totals["count"] / dt, 1),
                "seconds": round(dt, 3),
                "batches": totals["batches"],
                "backend": jax.default_backend(),
            }
        # No creds/egress on this rig: measure the SAME TwitterSource →
        # train path against an in-process v1.1-protocol server (the full
        # native stack — OAuth1 signing, chunked HTTP decode, line
        # reassembly, Status parse — is exercised for real; only the remote
        # endpoint is local). Tagged mode=local-protocol so it is never
        # read as a real-Twitter number. (VERDICT r2 #6)
        from tools.localstream import LocalV11StreamServer
        from twtml_tpu import config as _twtml_config
        from twtml_tpu.streaming.twitter import TwitterSource

        lines = [
            json.dumps(_status_json(s))
            for s in SyntheticSource(total=n_tweets, seed=3).produce()
        ]
        # 3 corpus replays per window (the server replays on reconnect):
        # a one-corpus window is RAMP-dominated — the fetch pipeline's
        # fill/drain tails and first-batch costs weighed ~2× at 32 batches
        # (33k) vs 96 (68k) in the same r5 probe window — and the steady
        # state is what the config claims
        n_batches = max(1, 3 * (n_tweets // batch_size))
        # snapshot the process-global property table: the fake bench creds
        # + local streamBaseURL must not leak past this measurement (a
        # later twitter_live call would mistake them for REAL creds)
        saved_props = dict(_twtml_config._SYSTEM_PROPERTIES)
        try:
            with LocalV11StreamServer(lines) as server:
                for k in ("consumerKey", "consumerSecret",
                          "accessToken", "accessTokenSecret"):
                    set_property("twitter4j.oauth." + k, "bench-" + k)
                set_property("twitter4j.streamBaseURL", server.url)
                live_args = [
                    "--source", "twitter", "--seconds", "0",
                    "--batchBucket", str(batch_size), "--tokenBucket", "128",
                    "--lightning", "http://127.0.0.1:9",
                    "--twtweb", "http://127.0.0.1:9",
                ]
                conf = ConfArguments().parse(live_args)

                # stage rate: the protocol path alone (connect → chunked
                # decode → reassemble → parse), no training attached
                src = TwitterSource.from_properties()
                got: list = []
                t0 = time.perf_counter()
                for s in src.produce():
                    got.append(s)
                    if len(got) >= n_tweets:
                        break
                protocol_s = time.perf_counter() - t0

                # the REAL app main (LinearRegression.scala:44 analog) over
                # the same stream. The rate is computed over the app's OWN
                # post-warmup streaming window (totals["stream_seconds"]):
                # the compile warmup runs before ssc.start (warmup_compile),
                # and per-batch stats ride the app's default FetchPipeline —
                # counting startup in the denominator made r3's full-app
                # number ~6k while the stages ran 34-79k (VERDICT r3 #4).
                # Best-of-3 app runs (each reconnects and replays the
                # server's stream): this is a single-pass measurement
                # otherwise, and the tunnel's multi-second stall bursts
                # land INSIDE one window often enough to fake a 100×
                # regression (a full-suite run recorded 140 s for a window
                # that re-measures at ~3 s)
                def best_of_3(run_conf):
                    best = None
                    for _ in range(3):
                        t0 = time.perf_counter()
                        totals = app.run(run_conf, max_batches=n_batches)
                        dt = time.perf_counter() - t0
                        stream_s = totals.get("stream_seconds") or dt
                        rec = (stream_s, dt, totals)
                        if best is None or stream_s < best[0]:
                            best = rec
                    return best

                stream_s, dt, totals = best_of_3(conf)

                # r5 (VERDICT r4 #9): the same app over the same stream
                # with LIVE BLOCK INGEST — raw lines batch into the native
                # C parser (BlockTwitterSource), deleting the per-line
                # json.loads + Status assembly that was the full-app vs
                # protocol-stage gap
                # same flags as the object arm + the one under test — the
                # two arms must stay comparable
                conf_block = ConfArguments().parse(
                    live_args + ["--ingest", "block"]
                )
                blk_stream_s, _blk_dt, blk_totals = best_of_3(conf_block)
        finally:
            _twtml_config._SYSTEM_PROPERTIES.clear()
            _twtml_config._SYSTEM_PROPERTIES.update(saved_props)
        return {
            **out,
            "mode": "local-protocol",
            "tweets_per_sec": round(totals["count"] / stream_s, 1),
            "protocol_tweets_per_sec": round(len(got) / protocol_s, 1),
            "block_tweets_per_sec": round(
                blk_totals["count"] / blk_stream_s, 1
            ),
            "seconds": round(stream_s, 3),
            "startup_seconds": round(dt - stream_s, 3),
            "batches": totals["batches"],
            "block_batches": blk_totals["batches"],
            "backend": jax.default_backend(),
        }

    if (
        name == "sharded_2e18_2d"
        and n_tweets > 2048
        and jax.default_backend() == "cpu"
    ):
        # program validation, not a speed number: the 2^18 Gram build on a
        # virtual CPU mesh runs ~150 tweets/s — cap the sample so a full
        # suite invocation doesn't stall ~20 min on this one config
        n_tweets = 2048
        out["note"] = "cpu program validation; sample capped at 2048 tweets"

    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())

    if name == "replay_linear":
        # the BASELINE config is a replayed-tweet FILE source: materialize
        # the synthetic stream to .jsonl once, then measure the real ingest
        # path end-to-end — native block parse → featurize → fused step.
        # The three stages run PIPELINED per pass (VERDICT r1 #4): a worker
        # thread owns the C parser (ctypes releases the GIL), a prefetch
        # thread featurizes the next chunk, and the main thread keeps every
        # device interaction (device_put off-main collapses the transport).
        import queue
        import tempfile
        import threading

        from twtml_tpu.features.blocks import iter_row_chunks, merge_blocks
        from twtml_tpu.models import StreamingLinearRegressionWithSGD
        from twtml_tpu.streaming.sources import BlockReplayFileSource
        from twtml_tpu.utils.benchloop import measure_passes

        feat = Featurizer(now_ms=1785320000000)
        model = StreamingLinearRegressionWithSGD()
        with tempfile.NamedTemporaryFile(
            "w", suffix=".jsonl", delete=False
        ) as fh:
            for s in statuses:
                fh.write(json.dumps(_status_json(s)) + "\n")
            path = fh.name
        try:
            block = merge_blocks(list(BlockReplayFileSource(path).produce()))
            rows = block.rows
            if rows == 0:
                return {
                    **out, "tweets_per_sec": 0.0, "seconds": 0.0,
                    "batches": 0, "final_metric": 0.0,
                    "backend": jax.default_backend(),
                    "note": "replay file produced zero kept rows",
                }
            n_chunks = -(-rows // batch_size)

            def featurize(sub):
                # ragged wire from blocks (r3): the block already holds
                # concatenated units + offsets, so no pad copy at all —
                # measured +28% paired over the padded block wire through
                # the tunnel (121 interleaved rounds, tools/bench_ragged.py
                # --ingest block)
                return feat.featurize_parsed_block(
                    sub, row_bucket=batch_size, ragged=True, pack=True
                )

            # warm the compile caches for both the full and the tail chunk
            for sub in iter_row_chunks([block], batch_size):
                model.step(featurize(sub)).mse.block_until_ready()

            def pipeline_source():
                # copy=False: blocks are views, featurized promptly; 4MB
                # blocks amortize per-call overhead (measured best on this
                # host with the view path). wire=True: the r9 zero-copy
                # emitter — the shipped config-#1 path (--blockWire auto
                # resolves on for the ragged wire; paired 1.6× on the parse
                # stage, BENCHMARKS.md "Zero-copy block parse")
                return BlockReplayFileSource(
                    path, copy=False, block_bytes=4 << 20, wire=True
                ).produce()

            def one_pass():
                """File bytes → trained weights, stages overlapped: the
                worker owns parse→chunk→featurize (its GIL-held numpy work
                hides under the GIL-free C parse and the main thread's
                device waits); main owns every device interaction. Worker
                failures propagate — a truncated pass must never be scored
                as a fast successful one."""
                model.reset()
                q: "queue.Queue" = queue.Queue(maxsize=8)

                def producer():
                    try:
                        for sub in iter_row_chunks(pipeline_source(), batch_size):
                            q.put(featurize(sub))
                        q.put(None)
                    except BaseException as exc:  # noqa: BLE001
                        q.put(exc)

                t0 = time.perf_counter()
                threading.Thread(target=producer, daemon=True).start()
                last = None
                while True:
                    item = q.get()
                    if item is None:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    last = model.step(item)
                # real host fetch: block_until_ready is a no-op through the
                # tunnel, and the weights chain through every step — one
                # scalar fetch closes the timed window over actual work
                float(last.mse)
                return time.perf_counter() - t0, last

            # the shared stall-riding measurement core (benchloop): best-of
            # with a time budget + settle check, never trusting one pass
            best_dt, final, _passes = measure_passes(
                one_pass, repeats=3, time_budget_s=30.0, settled_after=2
            )

            # stage rates for the notes column, measured with the SAME
            # source settings the pipeline uses: parse alone, train alone
            def parse_pass():
                t0 = time.perf_counter()
                for _ in pipeline_source():
                    pass
                return time.perf_counter() - t0, None

            parse_s, _, _ = measure_passes(parse_pass, repeats=3)
            subs = list(iter_row_chunks([block], batch_size))

            def train_pass():
                model.reset()
                t0 = time.perf_counter()
                last = None
                for sub in subs:
                    last = model.step(featurize(sub))
                float(last.mse)  # one real fetch closes the pass
                return time.perf_counter() - t0, None

            train_s, _, _ = measure_passes(train_pass, repeats=3)

            out.update(
                {
                    "tweets_per_sec": round(rows / best_dt, 1),
                    "seconds": round(best_dt, 3),
                    "batches": n_chunks,
                    "final_metric": round(float(final.mse), 3),
                    "parse_tweets_per_sec": round(rows / parse_s, 1),
                    "train_tweets_per_sec": round(rows / train_s, 1),
                }
            )
        finally:
            os.unlink(path)
    elif name == "logistic_sentiment":
        from twtml_tpu.features.sentiment import (
            sentiment_label,
            sentiment_labels,
        )
        from twtml_tpu.models import StreamingLogisticRegressionWithSGD

        feat = Featurizer(now_ms=1785320000000)
        feat.label_fn = sentiment_label
        feat.batch_label_fn = sentiment_labels
        model = StreamingLogisticRegressionWithSGD()
        # ragged wire: +9.7% paired over 193 interleaved rounds
        # (tools/bench_ragged.py --config logistic)
        out.update(_pipeline_rate(model, feat, statuses, batch_size,
                                  ragged=True))
    elif name == "hashing_2e18_l2":
        from twtml_tpu.models import StreamingLinearRegressionWithSGD

        feat = Featurizer(num_text_features=2**18, now_ms=1785320000000)
        model = StreamingLinearRegressionWithSGD(
            num_text_features=2**18, l2_reg=0.1
        )
        # batch: 2048 at the suite's pass shape (see the per-config
        # defaults comment above; tools/bench_2e18.py re-checks the
        # batch curve — b3072 wins long passes, b2048 wins here).
        # r3's --superBatch NEGATIVE finding stands.
        out.update(_pipeline_rate(model, feat, statuses, batch_size,
                                  ragged=True))
    elif name == "multi_tenant_m8":
        # the multi-tenant model plane (ISSUE 7): 8 models, one jit
        # program, one stacked fetch per tick — the per-config rate here;
        # the PAIRED verdict vs 8 sequential single-tenant pipelines is
        # tools/bench_tenants.py (interleaved arms, per-round ratios)
        from twtml_tpu.parallel import TenantStackModel

        feat = Featurizer(now_ms=1785320000000)
        model = TenantStackModel(8)
        out.update(_pipeline_rate(model, feat, statuses, batch_size,
                                  ragged=True, pack=False))
        out["tenants"] = 8
    elif name == "serving_qps":
        # the serving plane (ISSUE 9): coalesced + depth-8 pipelined
        # inference vs naive per-request, paired on tools/pairedbench.py
        # with the 70 ms modeled-RTT control (the acceptance regime —
        # tools/bench_serving.py is the full harness; this is its compact
        # per-config form for the suite's one-line-per-config record)
        from tools.bench_serving import measure as serving_measure

        rec = serving_measure(
            requests=64, rows_per_request=16, batch_rows=256, depth=8,
            budget=30.0, model_rtt_ms=70.0,
        )
        out.update({
            "qps_pipelined": rec["pipelined_rtt"]["qps_median"],
            "qps_naive": rec["naive_rtt"]["qps_median"],
            "p99_ms": rec["pipelined_rtt"]["p99_ms"],
            "paired_speedup_rtt70": (
                rec["pipelined_rtt"]["paired_speedup_vs_naive"]
            ),
            "paired_speedup_cpu_control": (
                rec["pipelined"]["paired_speedup_vs_naive"]
            ),
        })
    elif name == "wire_codec":
        # the compressed ragged units wire (ISSUE 12): digram codec off vs
        # on, paired on tools/pairedbench.py, in the object-ingest regime
        # with the modeled upload-bound transport control —
        # tools/bench_wirecodec.py is the full harness (both ingest
        # regimes, group-wire arms); this is its compact per-config form
        from tools.bench_wirecodec import measure as codec_measure

        small = n_tweets < 16384  # plumbing-test sizes stay fast
        rec = codec_measure(
            regime="object", n_tweets=min(n_tweets, 32768),
            batch=batch_size if explicit_batch else 4096,
            k=2 if small else 4, budget_s=3.0 if small else 25.0,
        )
        modeled = rec["modeled_upload"]
        out.update({
            "wire_ratio": modeled["wire_ratio_single"],
            "units_ratio": modeled["units_ratio"],
            "paired_codec_cpu_control": (
                rec["control"]["paired_single_codec_vs_raw"]
            ),
            "paired_codec_upload55": (
                modeled["paired_upload_bound"]["55"]["single_codec_vs_raw"]
            ),
            "paired_group_codec_upload55": (
                modeled["paired_upload_bound"]["55"]["group_codec_vs_raw"]
            ),
            "final_metric": rec["control"]["final_mse"],
        })
    elif name == "featurize":
        # one-pass host featurize (ISSUE 15): the featurize stage split
        # into sub-stages and paired r17/truth/fused on the object path
        # plus the block host chain — tools/bench_featurize.py is the
        # full harness; this is its compact per-config form
        from tools.bench_featurize import measure as featurize_measure

        small = n_tweets < 16384  # plumbing-test sizes stay fast
        obj = featurize_measure(
            regime="object", n_tweets=min(n_tweets, 65536),
            batch=batch_size if explicit_batch else 8192,
            budget_s=3.0 if small else 25.0,
        )["object"]
        blk = featurize_measure(
            regime="block", n_tweets=min(n_tweets, 65536),
            batch=batch_size if explicit_batch else 8192,
            budget_s=3.0 if small else 25.0,
        )["block"]
        out.update({
            "paired_fused_vs_r17": obj["paired_fused_vs_r17"],
            "paired_truth_vs_r17": obj["paired_truth_vs_r17"],
            "tweets_per_sec_fused": obj["tweets_per_sec_fused"],
            "paired_block_chain": blk["paired_chain_fused_vs_truth"],
            "block_chain_tweets_per_sec": blk[
                "chain_tweets_per_sec_fused"
            ],
        })
    elif name in ("sharded_dp4", "sharded_dp4_logistic", "sharded_2e18_2d"):
        from twtml_tpu.parallel import ParallelSGDModel, make_mesh
        from twtml_tpu.parallel.sharding import shard_batch

        if len(jax.devices()) < 4:
            return {**out, "skipped": "backend initialized with <4 devices"}
        # per-config mesh shape / feature width; data-axis size sets the
        # row_multiple every padded batch must divide by
        num_data, num_model = (2, 2) if name == "sharded_2e18_2d" else (4, 1)
        mesh = make_mesh(
            num_data=num_data, num_model=num_model, devices=jax.devices()[:4]
        )
        if name == "sharded_2e18_2d":
            feat = Featurizer(num_text_features=2**18, now_ms=1785320000000)
            model = ParallelSGDModel(mesh, num_text_features=2**18, l2_reg=0.1)
        elif name == "sharded_dp4_logistic":
            from twtml_tpu.features.sentiment import (
                sentiment_label,
                sentiment_labels,
            )
            from twtml_tpu.models import StreamingLogisticRegressionWithSGD as LR

            feat = Featurizer(now_ms=1785320000000)
            feat.label_fn = sentiment_label
            feat.batch_label_fn = sentiment_labels
            model = ParallelSGDModel(
                mesh, step_size=0.1,
                residual_fn=LR.residual_fn, prediction_fn=LR.prediction_fn,
                round_predictions=LR.round_predictions,
            )
        else:
            feat = Featurizer(now_ms=1785320000000)
            model = ParallelSGDModel(mesh)
        out.update(
            _pipeline_rate(
                model, feat, statuses, batch_size,
                row_multiple=num_data, shard=lambda b: shard_batch(b, mesh),
            )
        )
    else:
        raise SystemExit(f"unknown config {name!r}")

    out["backend"] = jax.default_backend()
    return out


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    # 65536 default tweets: the per-config default batches (up to 16384)
    # need several chunks per pass to measure a pipeline, not one batch
    n_tweets, batch_size, out_path, child = 65536, 0, "", ""  # batch 0 = default
    selected = list(CONFIGS)
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch_size = int(args[i + 1]); i += 2
        elif args[i] == "--json":
            out_path = args[i + 1]; i += 2
        elif args[i] == "--config":
            child = args[i + 1]; i += 2
        elif args[i] == "--configs":
            selected = [c for c in args[i + 1].split(",") if c]
            unknown = set(selected) - set(CONFIGS)
            if unknown:
                raise SystemExit(f"unknown configs: {sorted(unknown)}")
            i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    force_cpu = bool(os.environ.get("TWTML_BENCH_CPU"))

    if child:
        real = os.environ.get("TWTML_REAL_DEVICES")
        if child.startswith("sharded_") and (
            force_cpu or (real is not None and int(real) < 4)
        ):
            # parent saw <4 real chips (or CPU was requested): run the mesh
            # on 4 virtual CPU devices — must happen before this process
            # initializes any backend. Invoked directly (no parent, env
            # unset), real devices win and run_config skips below 4.
            from twtml_tpu.utils import force_virtual_cpu_devices

            force_virtual_cpu_devices(4)
        elif force_cpu:
            from twtml_tpu.utils import force_virtual_cpu_devices

            force_virtual_cpu_devices(1)
        print(json.dumps(run_config(child, n_tweets, batch_size)))
        return

    if force_cpu:
        # TWTML_BENCH_CPU=1: measure everything host-side (no accelerator
        # init at all — also the escape hatch when the TPU tunnel is down)
        n_real = 0
    else:
        # count real devices in a throwaway subprocess: accelerators are
        # exclusive per process, so the parent must never initialize one
        # while children need it
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
                capture_output=True, text=True, timeout=300,
            )
            n_real = int(probe.stdout.strip().splitlines()[-1])
        except Exception:
            n_real = 0
    env = dict(os.environ, TWTML_REAL_DEVICES=str(n_real))

    # run provenance (ISSUE 20): ONE monotonic run id for the whole suite
    # invocation (each config line carries its own fingerprint) so suite
    # rows join the telemetry historian's segments run-over-run
    from twtml_tpu.utils.runid import config_fingerprint, next_run_id

    suite_run_id = next_run_id()
    lines = []
    for name in selected:
        proc = None
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--config", name,
                 "--tweets", str(n_tweets), "--batch", str(batch_size)],
                env=env, capture_output=True, text=True, timeout=1800,
            )
            rec = json.loads(proc.stdout.strip().splitlines()[-1])
        except subprocess.TimeoutExpired:
            rec = {"config": name, "error": "timeout (1800s)"}
        except Exception as exc:
            detail = (
                (proc.stderr or proc.stdout).strip()[-400:]
                if proc is not None
                else ""
            )
            rec = {"config": name, "error": detail or repr(exc)}
        rec["run_id"] = suite_run_id
        rec["config_fingerprint"] = config_fingerprint(
            {"config": name, "tweets": n_tweets, "batch": batch_size})
        lines.append(rec)
        print(json.dumps(rec), flush=True)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(json.dumps(r) for r in lines) + "\n")


if __name__ == "__main__":
    main()
