"""Compressed-wire verdict (ISSUE 12): ``--wireCodec dict`` off vs on,
paired, in the upload-bound ingest regimes.

The question: the digram codec (features/wirecodec.py) shrinks the
dominant wire tensor ~1.3-2x on ASCII tweet text, paying a one-core host
encode (~60 µs/64 KiB in C) and an in-jit gather-expand decode. Does the
byte saving beat the encode cost where upload binds?

Method: the house harness only (tools/pairedbench.py) — interleaved
single passes, paired per-round ratios, parity asserted per round (the
codec may never change the math). Per regime (object / block ingest),
FOUR arms round-robin in one window: the k=1 packed wire and the K-group
coalesced wire, each raw and codec ("codec off/on × stacked/group" —
"stacked" here is the per-batch one-buffer pack; the codec rides packed
forms only, config.effective_wire_pack rejects the contradictory combo).

Each regime answers twice:

- CPU control — the full pipeline (pack → step → completion fetch) on the
  CPU backend. Wire-insensitive by design: this isolates the codec's HOST
  cost (the one-core encode) as a paired ratio ~1x-minus-encode.
- modeled upload-bound transport — paired pack-only passes (the codec's
  only timed host delta) plus EXACT upload arithmetic wire_bytes/BW over
  the tunnel's measured 45-70 MB/s envelope (BENCHMARKS.md r2: upload is
  the top of the ladder and dispatch/compute overlap underneath it, so
  serialized upload + pack IS the bound in that regime). Deterministic
  bytes x measured pack times — no sleep-granularity noise, no CPU
  device-step compute that a real accelerator would not pay. The live
  tunnel re-run of this tool is the standing item-5 chore.

Usage: python tools/bench_wirecodec.py [--regime object|block|both]
       [--tweets N] [--batch B] [--k K] [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _make_batches(regime: str, n_tweets: int, batch: int):
    """Pre-featurized ragged batches (the wire inputs). Featurize cost is
    identical across arms; what differs — and what each arm's pass times —
    is pack (the codec encode rides it), upload, dispatch, fetch."""
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import SyntheticSource

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    if regime == "object":
        return [
            feat.featurize_batch_ragged(
                statuses[i : i + batch], row_bucket=batch, pre_filtered=True
            )
            for i in range(0, n_tweets, batch)
        ]
    # block ingest: JSONL → native wire parser → columnar blocks →
    # the same ragged batches, zero per-tweet Python objects
    from tools.bench_suite import _status_json
    from twtml_tpu.features import native
    from twtml_tpu.features.blocks import ParsedBlock, iter_row_chunks

    data = (
        "\n".join(json.dumps(_status_json(s)) for s in statuses) + "\n"
    ).encode("utf-8")
    parsed = native.parse_tweet_block_wire(data, 0, 10**9)
    if parsed is None:
        raise SystemExit("block regime needs the native wire parser")
    block = ParsedBlock(*parsed[:4])
    return [
        feat.featurize_parsed_block(b, row_bucket=batch, ragged=True)
        for b in iter_row_chunks([block], batch)
    ]


# the tunnel's measured upload-bandwidth envelope (BENCHMARKS.md r2):
# the modeled verdict is reported across it, never at one cherry-picked
# operating point
UPLOAD_MBS_SWEEP = (45.0, 55.0, 70.0)


def _uniform_groups(batches, k: int):
    """K-groups of signature-matching batches (the SuperBatcher rule: one
    compiled scan program per (signature, K)). Batches sharing the MODAL
    signature are grouped (the bench's corpus is small enough that the
    data-dependent units bucket can differ batch to batch; production
    grouping is by-signature too, just streamwise)."""
    from collections import Counter

    sig = lambda b: (b.units.shape, b.units.dtype, b.row_len)  # noqa: E731
    modal, _n = Counter(sig(b) for b in batches).most_common(1)[0]
    same = [b for b in batches if sig(b) == modal]
    groups = [
        same[i : i + k] for i in range(0, len(same) - k + 1, k)
    ]
    if not groups:
        raise SystemExit("no signature-uniform group; raise --tweets")
    return groups


def _control_window(batches, k: int, budget_s: float) -> dict:
    """The CPU-control window: the FULL pipeline (pack → step → one
    completion fetch), 4 arms (single/group × raw/codec) round-robin.
    Every arm trains its OWN model over the same batch sequence each pass
    (arms stay step-for-step comparable because run_rounds completes
    every round); parity is asserted on final mse per window. A light
    step (5 inner iterations) stands in for the device — the real
    accelerator step is MICROSECONDS (the r2 ladder), so the CPU default
    of 50 iterations would drown the wire contrast in compute the tunnel
    regime does not pay. Identical across arms either way."""
    import jax
    import numpy as np

    from tools.pairedbench import paired_ratio_median, run_rounds
    from twtml_tpu.features.batch import pack_batch, pack_ragged_group
    from twtml_tpu.models import StreamingLinearRegressionWithSGD

    groups = _uniform_groups(batches, k)
    finals: dict[str, float] = {}

    def single_arm(name, codec):
        model = StreamingLinearRegressionWithSGD(num_iterations=5)

        def run():
            t0 = time.perf_counter()
            out = None
            for b in batches:
                out = model.step(pack_batch(b, codec=codec))
            finals[name] = float(np.asarray(jax.device_get(out.mse)))
            return time.perf_counter() - t0

        return run

    def group_arm(name, codec):
        model = StreamingLinearRegressionWithSGD(num_iterations=5)

        def run():
            t0 = time.perf_counter()
            out = None
            for g in groups:
                out = model.step_many(pack_ragged_group(g, codec=codec))
            finals[name] = float(np.asarray(jax.device_get(out.mse))[-1])
            return time.perf_counter() - t0

        return run

    arms = {
        "single_raw": single_arm("single_raw", None),
        "single_codec": single_arm("single_codec", "dict"),
        "group_raw": group_arm("group_raw", None),
        "group_codec": group_arm("group_codec", "dict"),
    }
    for run in arms.values():  # warmup: compile + completion fetch
        run()
    times = run_rounds(arms, budget_s)
    # parity per window: identical batch sequence → identical final mse
    assert finals["single_raw"] == finals["single_codec"], finals
    assert finals["group_raw"] == finals["group_codec"], finals
    return {
        "rounds": len(times["single_raw"]),
        "paired_single_codec_vs_raw": paired_ratio_median(
            times["single_raw"], times["single_codec"]
        ),
        "paired_group_codec_vs_raw": paired_ratio_median(
            times["group_raw"], times["group_codec"]
        ),
        "final_mse": finals["single_raw"],
    }


def _modeled_window(batches, k: int, budget_s: float) -> dict:
    """The modeled upload-bound window: paired PACK-ONLY passes (the
    codec's entire timed host delta — featurize is arm-identical and
    dispatch/compute overlap under upload in the target regime), then
    exact serialized-upload arithmetic wire_bytes/BW across the measured
    45-70 MB/s envelope. Parity of the packed wires themselves is the
    test suite's job (tests/test_wirecodec.py byte-parity)."""
    from tools.pairedbench import paired_ratios, run_rounds
    from twtml_tpu.features.batch import (
        pack_batch, pack_ragged_group, wire_composition, wire_nbytes,
    )
    import statistics

    groups = _uniform_groups(batches, k)
    wire: dict[str, int] = {}

    def single_pack(name, codec):
        def run():
            t0 = time.perf_counter()
            for b in batches:
                w = pack_batch(b, codec=codec)
            wire[name] = wire_nbytes(w)
            return time.perf_counter() - t0

        return run

    def group_pack(name, codec):
        def run():
            t0 = time.perf_counter()
            for g in groups:
                w = pack_ragged_group(g, codec=codec)
            wire[name] = wire_nbytes(w)
            return time.perf_counter() - t0

        return run

    arms = {
        "single_raw": single_pack("single_raw", None),
        "single_codec": single_pack("single_codec", "dict"),
        "group_raw": group_pack("group_raw", None),
        "group_codec": group_pack("group_codec", "dict"),
    }
    for run in arms.values():
        run()  # warmup: page in buffers, build the LUT once
    times = run_rounds(arms, budget_s)

    def modeled(base, arm, n_transfers, mbs):
        # per-round modeled pass time = measured pack pass + exact upload
        up_b = wire[base] * n_transfers / (mbs * 1e6)
        up_a = wire[arm] * n_transfers / (mbs * 1e6)
        return round(statistics.median(paired_ratios(
            [t + up_b for t in times[base]],
            [t + up_a for t in times[arm]],
        )), 3)

    comp = wire_composition(pack_batch(batches[0], codec="dict"))
    rec = {
        "rounds": len(times["single_raw"]),
        "wire_bytes": dict(wire),
        "wire_ratio_single": round(
            wire["single_raw"] / wire["single_codec"], 3
        ),
        "wire_ratio_group": round(
            wire["group_raw"] / wire["group_codec"], 3
        ),
        "units_ratio": (
            round(comp["units"] / comp["units_compressed"], 3)
            if comp.get("units_compressed")
            else 1.0
        ),
        "pack_ms_per_batch": {
            n: round(
                statistics.median(ts) * 1e3 / len(batches), 3
            )
            for n, ts in times.items()
        },
        "paired_upload_bound": {},
    }
    for mbs in UPLOAD_MBS_SWEEP:
        rec["paired_upload_bound"][str(int(mbs))] = {
            "single_codec_vs_raw": modeled(
                "single_raw", "single_codec", len(batches), mbs
            ),
            "group_codec_vs_raw": modeled(
                "group_raw", "group_codec", len(groups), mbs
            ),
        }
    return rec


def measure(
    regime: str = "object", n_tweets: int = 65536, batch: int = 8192,
    k: int = 4, budget_s: float = 60.0,
) -> dict:
    import jax

    batches = _make_batches(regime, n_tweets, batch)
    return {
        "regime": regime, "tweets": n_tweets, "batch": batch, "k": k,
        "backend": jax.devices()[0].platform,
        # the CPU control is wire-insensitive by design: it isolates the
        # codec's host cost (encode + the extra in-program decode)
        "control": _control_window(batches, k, budget_s),
        # the modeled upload-bound verdict across the measured bandwidth
        # envelope: the acceptance regime until a live tunnel window
        # re-runs this tool
        "modeled_upload": _modeled_window(batches, k, budget_s),
    }


def main() -> None:
    args = sys.argv[1:]

    def opt(name, default, cast):
        if name in args:
            return cast(args[args.index(name) + 1])
        return default

    regime = opt("--regime", "both", str)
    n_tweets = opt("--tweets", 65536, int)
    batch = opt("--batch", 8192, int)
    k = opt("--k", 4, int)
    budget = opt("--budget", 60.0, float)
    regimes = ["object", "block"] if regime == "both" else [regime]
    out = [measure(r, n_tweets, batch, k, budget) for r in regimes]
    print(json.dumps(out if len(out) > 1 else out[0]))


if __name__ == "__main__":
    main()
