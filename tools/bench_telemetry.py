"""Per-batch-telemetry regime: the fetch strategies, interleaved.

The production apps read the full StepOutput every batch for the stats
plane; through this build's tunnel each host fetch is a ~70-100 ms round
trip, capping the back-to-back telemetry-on rate far below the free-
dispatch rate. Arms (single passes round-robin in one window; paired
per-round ratios are the phase-robust comparison):

- sync     : device_get right after each dispatch (the r2 baseline);
- lag      : one-batch-lag fetch (VERDICT r2 #2's proposal) — measured
             NEUTRAL here, kept for the record;
- pool8    : concurrent in-order fetches on a thread pool — the measured
             6.2x winner, the mechanism FetchPipeline ships;
- fetchpipe: the SHIPPED path end-to-end — apps/common.FetchPipeline over
             the ragged+packed wire, per-batch handler included. This is
             the arm behind the r4 batch-retune claim (2.2x paired at
             --batch 16384 vs 2048: the per-batch fetch amortizes over 8x
             more tweets — BENCHMARKS.md).

Usage: python tools/bench_telemetry.py [--tweets N] [--batch B] [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget = 65536, 2048, 180.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]
    batches = [
        feat.featurize_batch_units(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]

    def consume(out, b, t, at_boundary=True):
        # what the app handlers do: read every StepOutput field on host
        float(out.count); float(out.mse)
        float(out.real_stdev); float(out.pred_stdev)
        _ = out.predictions[0]

    model = StreamingLinearRegressionWithSGD()
    for _ in range(2):
        float(model.step(batches[0]).mse)  # warm the program

    def sync_pass():
        model.reset()
        t0 = time.perf_counter()
        for b in batches:
            consume(jax.device_get(model.step(b)), b, 0.0)
        return time.perf_counter() - t0

    def lag_pass():
        """One-batch-lag fetch (dispatch k, then fetch k-1; async copy at
        dispatch) — kept as an arm for the record: measured NEUTRAL on this
        transport (device_get is an RTT-bound request), which is why the
        shipped pipeline is the concurrent pool below instead."""
        model.reset()
        pending = None
        t0 = time.perf_counter()
        for b in batches:
            out = model.step(b)
            for leaf in jax.tree_util.tree_leaves(out):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            if pending is not None:
                consume(jax.device_get(pending[0]), pending[1], 0.0)
            pending = (out, b)
        if pending is not None:
            consume(jax.device_get(pending[0]), pending[1], 0.0)
        return time.perf_counter() - t0

    from concurrent.futures import ThreadPoolExecutor

    def pool_pass(workers=8):
        """Fetch each batch's StepOutput on a thread pool while the main
        thread keeps dispatching; consume in order. If the transport
        accepts concurrent host-fetch requests, N in-flight requests
        pipeline the RTT (throughput → N/RTT); if it serializes them,
        this matches sync. (device_put off-main collapses throughput —
        measured r2 — but these are GETs.)"""
        model.reset()
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=workers) as pool:
            futs = [
                pool.submit(jax.device_get, model.step(b)) for b in batches
            ]
            for f, b in zip(futs, batches):
                consume(f.result(), b, 0.0)
        return time.perf_counter() - t0

    from twtml_tpu.apps.common import FetchPipeline

    from twtml_tpu.features.batch import pack_batch

    r_batches = [
        feat.featurize_batch_ragged(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]
    # warm the PACKED program the timed arm actually dispatches
    # (pack=True → model.step(pack_batch(b)): a different jit pytree than
    # the raw ragged batch), once per distinct wire layout — the ragged
    # units bucket is data-dependent, so chunks can land in several
    seen_layouts = set()
    for rb in r_batches:
        key = (rb.units.shape, str(rb.units.dtype), rb.row_len)
        if key not in seen_layouts:
            seen_layouts.add(key)
            float(model.step(pack_batch(rb)).mse)
    model.reset()

    def fetchpipe_pass():
        """The shipped back-to-back path verbatim: FetchPipeline (depth 8,
        packed ragged wire) delivering every batch's StepOutput to the
        same handler work as every other arm."""
        model.reset()
        t0 = time.perf_counter()
        pipe = FetchPipeline(model, consume, depth=8, pack=True)
        for b in r_batches:
            pipe.on_batch(b, 0.0)
        pipe.flush()
        return time.perf_counter() - t0

    from twtml_tpu.features.batch import stack_batches
    from twtml_tpu.models.base import StepOutput

    groups = [
        stack_batches(batches[i : i + 8])
        for i in range(0, len(batches) - len(batches) % 8, 8)
    ]
    tail = batches[len(batches) - len(batches) % 8 :]
    if groups:
        float(model.step_many(groups[0]).mse[-1])  # warm the scan program

    def super_pool_pass(workers=4):
        """--superBatch 8 + pooled group fetches: one scan dispatch and one
        pooled fetch per 8 batches — the two levers stacked. The per-batch
        consume() runs here too, so every arm measures the same handler
        work."""
        model.reset()
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=workers) as pool:
            futs = [
                (pool.submit(jax.device_get, model.step_many(g)), True)
                for g in groups
            ] + [
                (pool.submit(jax.device_get, model.step(b)), False)
                for b in tail
            ]
            for f, stacked in futs:
                host = f.result()
                if stacked:
                    for k in range(host.count.shape[0]):
                        consume(
                            StepOutput(*(x[k] for x in host)), None, 0.0
                        )
                else:
                    consume(host, None, 0.0)
        return time.perf_counter() - t0

    from twtml_tpu.apps.common import SuperBatcher

    def super_ragged_pass():
        """r5: --superBatch 8 on the RAGGED wire through the shipped
        SuperBatcher (stacked [K, N] buffers scan with row_len static;
        grouping by shape signature) — the composition VERDICT r4 #1c
        asked to wire and measure. Same per-batch handler work."""
        model.reset()
        t0 = time.perf_counter()
        sb = SuperBatcher(model, 8, consume, fetch_depth=4)
        for rb in r_batches:
            sb.on_batch(rb, 0.0)
        sb.flush()
        return time.perf_counter() - t0

    if groups:
        super_ragged_pass()  # warm the ragged scan programs (per layout)

    # the house interleaved/paired scheduling (tools/pairedbench.py)
    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )

    arms = {
        "sync": sync_pass, "lag": lag_pass, "pool8": pool_pass,
        "fetchpipe": fetchpipe_pass,
    }
    if groups:
        arms["super8_pool4"] = super_pool_pass
        arms["super8_ragged"] = super_ragged_pass
    times = run_rounds(arms, budget)

    out = {"regime": "per-batch-telemetry", "batch": batch,
           "tweets": n_tweets, "backend": jax.default_backend(),
           "rounds": len(times["sync"])}
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
        }
    for name in [
        k
        for k in (
            "lag", "pool8", "fetchpipe", "super8_pool4", "super8_ragged",
        )
        if k in times
    ]:
        out[name]["paired_speedup_vs_sync"] = paired_ratio_median(
            times["sync"], times[name]
        )
    if "super8_ragged" in times:
        # the composition question directly: does the superbatch stack on
        # the shipped ragged fetch pipeline?
        out["super8_ragged"]["paired_vs_fetchpipe"] = paired_ratio_median(
            times["fetchpipe"], times["super8_ragged"]
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
