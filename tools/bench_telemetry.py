"""Per-batch-telemetry regime: synchronous stats fetch vs the one-batch-lag
pipeline (apps/common.LagPipeline — VERDICT r2 #2).

The production apps read the full StepOutput every batch for the stats
plane; through this build's tunnel each host fetch is a ~70-100 ms round
trip, capping the back-to-back telemetry-on rate far below the free-
dispatch rate. The lag pipeline dispatches batch k, then fetches k-1
(whose device→host copy started at its dispatch), so the round trip
overlaps the next batch's work. Arms interleave within one window; paired
per-round ratios are the phase-robust comparison.

Usage: python tools/bench_telemetry.py [--tweets N] [--batch B] [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget = 65536, 2048, 180.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.apps.common import LagPipeline
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]
    batches = [
        feat.featurize_batch_units(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]

    def consume(out, b, t, at_boundary=True):
        # what the app handlers do: read every StepOutput field on host
        float(out.count); float(out.mse)
        float(out.real_stdev); float(out.pred_stdev)
        _ = out.predictions[0]

    model = StreamingLinearRegressionWithSGD()
    for _ in range(2):
        float(model.step(batches[0]).mse)  # warm the program

    def sync_pass():
        model.reset()
        t0 = time.perf_counter()
        for b in batches:
            consume(jax.device_get(model.step(b)), b, 0.0)
        return time.perf_counter() - t0

    def lag_pass():
        model.reset()
        pipe = LagPipeline(model, consume)
        t0 = time.perf_counter()
        for b in batches:
            pipe.on_batch(b, 0.0)
        pipe.flush()
        return time.perf_counter() - t0

    times = {"sync": [], "lag": []}
    t_end = time.perf_counter() + budget
    while time.perf_counter() < t_end:
        times["sync"].append(sync_pass())
        times["lag"].append(lag_pass())

    out = {"regime": "per-batch-telemetry", "batch": batch,
           "tweets": n_tweets, "backend": jax.default_backend(),
           "rounds": len(times["sync"])}
    for name, ts in times.items():
        out[name] = {
            "tweets_per_sec_best": round(n_tweets / min(ts), 1),
            "tweets_per_sec_median": round(n_tweets / statistics.median(ts), 1),
        }
    out["paired_speedup_median"] = round(
        statistics.median([s / l for s, l in zip(times["sync"], times["lag"])]),
        3,
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
