"""One-pass featurize verdict (ISSUE 15): the featurize stage split into
its sub-stages and paired off/on, on BOTH ingest paths.

The question BENCHMARKS r17 left open: the host chain is
featurize-dominated (61-70 ms per 65k-tweet pass vs ~1.4 ms of pack), so
which HALF of featurize gates the host — the Python traversals, the
UTF-16 encode, the numeric scaling, or the wire build? This tool
measures the split BEFORE the attack (the r9/r17 honest-miss discipline:
the floor must be a number, not a guess), then renders the paired
verdicts:

- **object regime** — three interleaved arms over the identical Status
  chunks: ``r17`` (the pre-r18 call sequence recreated from the same
  building blocks: filtrate comprehension, originals comprehension,
  per-text ascii/lower loop, encode, numpy wire build, fromiter
  numeric/label/mask — byte parity asserted against the live path),
  ``truth`` (``--featurizeNative off``: the r18 one-traversal gather +
  numpy array passes), ``fused`` (``on``: gather + the one-pass C fill
  into an arena lease). ``paired_fused_vs_r17`` is the acceptance
  number (target >= 2x); fused-vs-truth isolates the C fill,
  truth-vs-r17 isolates the traversal collapse.
- **block regime** — the full host chain (raw JSONL bytes -> native wire
  parse -> featurize -> packed wire, the production block path) off vs
  on paired (target >= 1.4x), plus a featurize-stage-only window. The
  block ``off`` path IS the r17 path (unchanged numpy passes), so two
  arms suffice.
- **sub-stages** — per-arm median ms of the featurize sub-stage clock
  (featurizer.last_substages: encode / numeric / wire_build; the fused
  arm reports its C fill under wire_build), so the ladder names the
  dominator.

Method: the house harness only (tools/pairedbench.py) — interleaved
single passes, paired per-round ratios; batch parity asserted per window
(featurize may never change the batch).

Usage: python tools/bench_featurize.py [--regime object|block|both]
       [--tweets N] [--batch B] [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOW_MS = 1785320000000


def _statuses(n_tweets: int):
    from twtml_tpu.streaming.sources import SyntheticSource

    return list(SyntheticSource(total=n_tweets, seed=3).produce())


def _block_data(statuses) -> bytes:
    from tools.bench_suite import _status_json

    return (
        "\n".join(json.dumps(_status_json(s)) for s in statuses) + "\n"
    ).encode("utf-8")


def _r17_featurize(feat, statuses, row_bucket: int, stages: dict):
    """The pre-r18 object featurize, recreated from the SAME building
    blocks the live path still uses (encode_texts, ragged_wire_arrays,
    the fromiter numeric/label/mask) — the paired baseline arm, with its
    own sub-stage clock. Byte parity vs the live path is asserted once
    per window, so this recreation cannot drift silently."""
    import itertools

    import numpy as np

    from twtml_tpu.features import native
    from twtml_tpu.features.batch import (
        NUM_NUMBER_FEATURES,
        RaggedUnitBatch,
        ragged_wire_arrays,
    )
    from twtml_tpu.features.featurizer import _NUMERIC_COLS, AGE_SCALE, COUNT_SCALE

    t0 = time.perf_counter()
    keep = [s for s in statuses if feat.filtrate(s)]
    t1 = time.perf_counter()
    stages["filter"] += t1 - t0
    originals = [s.retweeted_status for s in keep]
    all_ascii = True
    texts = []
    for o in originals:
        t = o.text
        if not t.isascii():
            t = t.lower()
            all_ascii = False
        texts.append(t)
    units, offsets = native.encode_texts(texts)
    lengths = np.diff(offsets).astype(np.int32)
    t2 = time.perf_counter()
    stages["encode"] += t2 - t1
    n = len(keep)
    b, lu = feat._unit_batch_shape(n, lengths, row_bucket, 0, 1)
    flat, offs = ragged_wire_arrays(units, offsets, n, b, narrow=all_ascii)
    t3 = time.perf_counter()
    stages["wire_build"] += t3 - t2
    numeric = np.zeros((b, NUM_NUMBER_FEATURES), dtype=np.float32)
    label = np.zeros((b,), dtype=np.float32)
    mask = np.zeros((b,), dtype=np.float32)
    if n:
        cols = np.fromiter(
            itertools.chain.from_iterable(map(_NUMERIC_COLS, originals)),
            np.float64, n * 5,
        ).reshape(n, 5)
        numeric[:n, :3] = cols[:, :3] * COUNT_SCALE
        numeric[:n, 3] = (NOW_MS - cols[:, 3]) * AGE_SCALE
        label[:n] = cols[:, 4]
        mask[:n] = 1.0
    stages["numeric"] += time.perf_counter() - t3
    return RaggedUnitBatch(flat, offs, numeric, label, mask, row_len=lu)


def _retire(batch) -> None:
    lease = getattr(batch, "_lease", None)
    if lease is not None:
        lease.retire()  # featurize-only window: nothing is in flight


def _assert_same_batch(a, b, tag: str) -> None:
    import numpy as np

    for f in ("units", "offsets", "numeric", "label", "mask"):
        x, y = getattr(a, f), getattr(b, f)
        assert x.dtype == y.dtype and np.array_equal(x, y), (
            f"featurize path diverged: {tag}.{f}"
        )
    assert a.row_len == b.row_len, (tag, a.row_len, b.row_len)


def _substage_ms(samples: "dict[str, list[float]]") -> dict:
    return {
        name: round(statistics.median(ts) * 1e3, 3)
        for name, ts in samples.items()
        if ts
    }


def _object_window(statuses, batch: int, budget_s: float) -> dict:
    from tools.pairedbench import paired_ratio_median, run_rounds
    from twtml_tpu.features import featurize_native as ffz
    from twtml_tpu.features.featurizer import Featurizer

    feat = Featurizer(now_ms=NOW_MS)
    chunks = [
        statuses[i : i + batch] for i in range(0, len(statuses), batch)
    ]
    r17_stages = {"filter": 0.0, "encode": 0.0, "numeric": 0.0,
                  "wire_build": 0.0}
    subs: "dict[str, dict[str, list[float]]]" = {
        "truth": {}, "fused": {}, "r17": {}
    }

    def record_subs(arm: str) -> None:
        agg: "dict[str, float]" = {}
        for name, _t0, dur in feat.last_substages:
            agg[name] = agg.get(name, 0.0) + dur
        for name, dur in agg.items():
            subs[arm].setdefault(name, []).append(dur)

    def arm_r17():
        for k in r17_stages:
            r17_stages[k] = 0.0
        t0 = time.perf_counter()
        for c in chunks:
            _r17_featurize(feat, c, batch, r17_stages)
        dt = time.perf_counter() - t0
        for k, v in r17_stages.items():
            subs["r17"].setdefault(k, []).append(v)
        return dt

    def arm(mode, name):
        def run():
            with ffz.forced(mode):
                t0 = time.perf_counter()
                per_sub: "dict[str, float]" = {}
                for c in chunks:
                    b = feat.featurize_batch_ragged(c, row_bucket=batch)
                    for sname, _st, dur in feat.last_substages:
                        per_sub[sname] = per_sub.get(sname, 0.0) + dur
                    _retire(b)
                dt = time.perf_counter() - t0
            for sname, dur in per_sub.items():
                subs[name].setdefault(sname, []).append(dur)
            return dt

        return run

    # parity: the r17 recreation and both live modes emit identical batches
    ref = _r17_featurize(feat, chunks[0], batch, dict(r17_stages))
    with ffz.forced("off"):
        _assert_same_batch(
            ref, feat.featurize_batch_ragged(chunks[0], row_bucket=batch),
            "truth",
        )
    with ffz.forced("on"):
        got = feat.featurize_batch_ragged(chunks[0], row_bucket=batch)
        _assert_same_batch(ref, got, "fused")
        _retire(got)

    arms = {"r17": arm_r17, "truth": arm("off", "truth"),
            "fused": arm("on", "fused")}
    for run in arms.values():
        run()  # warmup: page in, fill the arena pool
    for v in subs.values():
        v.clear()
    times = run_rounds(arms, budget_s)
    n_valid = sum(
        1 for c in chunks for s in c if feat.filtrate(s)
    )
    med = statistics.median(times["fused"])
    return {
        "rounds": len(times["r17"]),
        "tweets_per_pass": len(statuses),
        "paired_fused_vs_r17": paired_ratio_median(
            times["r17"], times["fused"]
        ),
        "paired_fused_vs_truth": paired_ratio_median(
            times["truth"], times["fused"]
        ),
        "paired_truth_vs_r17": paired_ratio_median(
            times["r17"], times["truth"]
        ),
        "featurize_ms_median": {
            n: round(statistics.median(ts) * 1e3, 2)
            for n, ts in times.items()
        },
        "tweets_per_sec_fused": round(n_valid / med, 1) if med else None,
        "substage_ms": {k: _substage_ms(v) for k, v in subs.items()},
    }


def _block_window(data: bytes, batch: int, budget_s: float) -> dict:
    """Block regime: featurize-stage window + the full host chain (bytes
    -> native wire parse -> featurize -> packed wire), off vs on."""
    from tools.pairedbench import paired_ratio_median, run_rounds
    from twtml_tpu.features import featurize_native as ffz
    from twtml_tpu.features import native
    from twtml_tpu.features.batch import pack_batch
    from twtml_tpu.features.blocks import ParsedBlock, iter_row_chunks
    from twtml_tpu.features.featurizer import Featurizer

    feat = Featurizer(now_ms=NOW_MS)
    parsed = native.parse_tweet_block_wire(data, 0, 10**9)
    if parsed is None:
        raise SystemExit("block regime needs the native wire parser")
    block = ParsedBlock(*parsed[:4])
    blocks = list(iter_row_chunks([block], batch))
    subs: "dict[str, dict[str, list[float]]]" = {"truth": {}, "fused": {}}

    def featurize_only(mode, name):
        def run():
            with ffz.forced(mode):
                t0 = time.perf_counter()
                per_sub: "dict[str, float]" = {}
                for blk in blocks:
                    b = feat.featurize_parsed_block(
                        blk, row_bucket=batch, ragged=True
                    )
                    for sname, _st, dur in feat.last_substages:
                        per_sub[sname] = per_sub.get(sname, 0.0) + dur
                    _retire(b)
                dt = time.perf_counter() - t0
            for sname, dur in per_sub.items():
                subs[name].setdefault(sname, []).append(dur)
            return dt

        return run

    def chain(mode):
        def run():
            with ffz.forced(mode):
                t0 = time.perf_counter()
                p = native.parse_tweet_block_wire(data, 0, 10**9)
                blk_all = ParsedBlock(*p[:4])
                for blk in iter_row_chunks([blk_all], batch):
                    fb = feat.featurize_parsed_block(
                        blk, row_bucket=batch, ragged=True
                    )
                    pb = pack_batch(fb)
                    lease = getattr(pb, "_lease", None)
                    if lease is not None:
                        lease.retire()
                    _retire(fb)
                return time.perf_counter() - t0

        return run

    # parity per window
    import numpy as np  # noqa: F401

    with ffz.forced("off"):
        ref = feat.featurize_parsed_block(
            blocks[0], row_bucket=batch, ragged=True
        )
    with ffz.forced("on"):
        got = feat.featurize_parsed_block(
            blocks[0], row_bucket=batch, ragged=True
        )
        _assert_same_batch(ref, got, "block")
        _retire(got)

    f_arms = {"truth": featurize_only("off", "truth"),
              "fused": featurize_only("on", "fused")}
    c_arms = {"truth": chain("off"), "fused": chain("on")}
    for run in (*f_arms.values(), *c_arms.values()):
        run()
    for v in subs.values():
        v.clear()
    f_times = run_rounds(f_arms, budget_s / 2)
    c_times = run_rounds(c_arms, budget_s / 2)
    rows = sum(b.rows for b in blocks)
    med = statistics.median(c_times["fused"])
    return {
        "rounds": len(f_times["truth"]),
        "rows_per_pass": rows,
        "paired_featurize_fused_vs_truth": paired_ratio_median(
            f_times["truth"], f_times["fused"]
        ),
        "paired_chain_fused_vs_truth": paired_ratio_median(
            c_times["truth"], c_times["fused"]
        ),
        "featurize_ms_median": {
            n: round(statistics.median(ts) * 1e3, 2)
            for n, ts in f_times.items()
        },
        "chain_ms_median": {
            n: round(statistics.median(ts) * 1e3, 2)
            for n, ts in c_times.items()
        },
        "chain_tweets_per_sec_fused": round(rows / med, 1) if med else None,
        "substage_ms": {k: _substage_ms(v) for k, v in subs.items()},
    }


def measure(
    regime: str, n_tweets: int, batch: int, budget_s: float
) -> dict:
    from twtml_tpu.features import featurize_native as ffz

    statuses = _statuses(n_tweets)
    rec: dict = {
        "regime": regime, "tweets": n_tweets, "batch": batch,
        "featurize_native_available": ffz.available(),
    }
    if regime == "object":
        rec["object"] = _object_window(statuses, batch, budget_s)
    else:
        rec["block"] = _block_window(_block_data(statuses), batch, budget_s)
    return rec


def main() -> None:
    args = sys.argv[1:]

    def opt(name, default, cast):
        if name in args:
            return cast(args[args.index(name) + 1])
        return default

    regime = opt("--regime", "both", str)
    n_tweets = opt("--tweets", 65536, int)
    batch = opt("--batch", 8192, int)
    budget = opt("--budget", 60.0, float)
    regimes = ["object", "block"] if regime == "both" else [regime]
    out = [measure(r, n_tweets, batch, budget) for r in regimes]
    print(json.dumps(out if len(out) > 1 else out[0]))


if __name__ == "__main__":
    main()
