"""Wire-padding measurement: padded UnitBatch vs ragged RaggedUnitBatch.

VERDICT r2 #3: the padded [B, L_bucket] units buffer is the dominant wire
tensor and nothing measured what fraction of it is padding. This tool
reports, for a corpus at a given batch size:

  - the padding fraction of the padded units buffer (1 - Σlen / B·L);
  - wire bytes per batch for both formats (all five arrays);
  - the pipelined end-to-end rate for both formats on the current
    backend — single passes INTERLEAVED A/B/A/B (utils/benchloop._run_once
    per pass: dispatch freely, one completion fetch), with paired
    per-round ratios so tunnel phase swings hit both arms equally.

Usage: python tools/bench_ragged.py [--tweets N] [--batch B] [--budget S]
       [--config dense|2e18|logistic] [--ingest object|block]
Prints one JSON line. ``--ingest block`` compares the formats fed from the
native columnar parser's blocks (featurize_parsed_block) instead of Status
objects — the ragged form there skips the pad copy entirely.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def wire_bytes(batch) -> int:
    import jax

    return sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(batch)
    )


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch_size, budget, config = 65536, 2048, 45.0, "dense"
    ingest = "object"
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch_size = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        elif args[i] == "--config":
            config = args[i + 1]; i += 2
        elif args[i] == "--ingest":
            ingest = args[i + 1]; i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax
    import numpy as np

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import (
        StreamingLinearRegressionWithSGD,
        StreamingLogisticRegressionWithSGD,
    )
    from twtml_tpu.streaming.sources import SyntheticSource

    f_text = 2**18 if config == "2e18" else 1000
    feat = Featurizer(num_text_features=f_text, now_ms=1785320000000)
    if config == "logistic":
        # the suite's config #3: lexicon sentiment labels via the C batch
        # scorer, logistic residual
        from twtml_tpu.features.sentiment import (
            sentiment_label,
            sentiment_labels,
            sentiment_labels_from_units,
        )

        feat.label_fn = sentiment_label
        feat.batch_label_fn = sentiment_labels
        feat.unit_label_fn = sentiment_labels_from_units  # block ingest
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())

    if ingest == "block":
        # columnar-block chunks (the config #1 path): materialize the
        # stream to .jsonl once, parse with the native loader, slice into
        # fixed row chunks; featurize_parsed_block builds either wire
        import tempfile

        from tools.bench_suite import _status_json
        from twtml_tpu.features.blocks import iter_row_chunks, merge_blocks
        from twtml_tpu.streaming.sources import BlockReplayFileSource

        with tempfile.NamedTemporaryFile(
            "w", suffix=".jsonl", delete=False
        ) as fh:
            for s in statuses:
                fh.write(json.dumps(_status_json(s)) + "\n")
            path = fh.name
        block = merge_blocks(list(BlockReplayFileSource(path).produce()))
        os.unlink(path)
        chunks = list(iter_row_chunks([block], batch_size))

        def fz_padded(sub):
            return feat.featurize_parsed_block(sub, row_bucket=batch_size)

        def fz_ragged(sub):
            return feat.featurize_parsed_block(
                sub, row_bucket=batch_size, ragged=True
            )
    else:
        chunks = [
            statuses[i : i + batch_size]
            for i in range(0, len(statuses), batch_size)
        ]

        def fz_padded(c):
            return feat.featurize_batch_units(
                c, row_bucket=batch_size, pre_filtered=True
            )

        def fz_ragged(c):
            return feat.featurize_batch_ragged(
                c, row_bucket=batch_size, pre_filtered=True
            )

    # ---- wire accounting on the first full chunk -------------------------
    pb = fz_padded(chunks[0])
    rb = fz_ragged(chunks[0])
    real_units = int(np.asarray(rb.offsets)[-1])
    padded_units = int(pb.units.shape[0] * pb.units.shape[1])
    out = {
        "config": config,
        "ingest": ingest,
        "batch": batch_size,
        "units_padding_fraction": round(1 - real_units / padded_units, 4),
        "padded_wire_bytes": wire_bytes(pb),
        "ragged_wire_bytes": wire_bytes(rb),
        "unit_dtype": str(pb.units.dtype),
        "backend": jax.default_backend(),
    }

    # ---- pipelined end-to-end rates, INTERLEAVED -------------------------
    # The house method (tools/pairedbench.py): single passes round-robin
    # A/B/A/B inside one window, paired per-round ratios — tunnel phase
    # swings hit both arms equally.
    from tools.pairedbench import (
        best_median_rate,
        paired_ratio_median,
        paired_ratios,
        run_rounds,
    )
    from twtml_tpu.utils.benchloop import _run_once

    finals: dict[str, float] = {}

    def make(name, featurize):
        if config == "logistic":
            model = StreamingLogisticRegressionWithSGD()
        else:
            model = StreamingLinearRegressionWithSGD(
                num_text_features=f_text,
                l2_reg=0.1 if config == "2e18" else 0.0,
            )
        warm = featurize(chunks[0])
        for _ in range(2):
            float(model.step(warm).mse)  # completion-fetch warmup

        def one_pass():
            model.reset()
            dt, last = _run_once(model, featurize, chunks, prefetch=True)
            finals[name] = round(float(last.mse), 3)
            return dt

        return one_pass

    arms = {
        "padded": make("padded", fz_padded),
        "ragged": make("ragged", fz_ragged),
    }
    n = sum(
        c.rows if hasattr(c, "rows") else len(c) for c in chunks
    )  # block chunks count rows, Status chunks count items
    times = run_rounds(arms, budget)
    for name, ts in times.items():
        best, median = best_median_rate(ts, n)
        out[name] = {
            "tweets_per_sec": best,
            "median_tweets_per_sec": median,
            "passes": len(ts),
            "final_mse": finals[name],
        }
    # paired per-round ratios: phase-robust (each pair shares a window)
    out["paired_speedup_median"] = paired_ratio_median(
        times["padded"], times["ragged"]
    )
    out["paired_speedup_all"] = [
        round(x, 3) for x in paired_ratios(times["padded"], times["ragged"])
    ]
    assert out["padded"]["final_mse"] == out["ragged"]["final_mse"], (
        "wire formats diverged — parity violation"
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
