"""RMSE-vs-wall-clock measurement — the BASELINE.md metric the reference
only ever displayed live on a dashboard ("streaming RMSE vs wall-clock",
BASELINE.md:11; the reference computes per-batch MSE at
LinearRegression.scala:65 but never records a curve).

Runs the flagship streaming pipeline on a replayed or synthetic stream and
emits one JSON line per batch: elapsed wall-clock seconds, cumulative tweet
count, per-batch RMSE (progressive validation — each batch scored with
pre-update weights). Curves from different backends/configs are directly
comparable ("identical RMSE curves" is the north-star acceptance criterion,
BASELINE.json).

Usage:
  python tools/rmse_curve.py --source synthetic --tweets 100000 \
      [--batch 2048] [--backend cpu] [--out curve.jsonl] [usual twtml flags]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from twtml_tpu.config import ConfArguments  # noqa: E402


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    tweets, batch_size, out_path = 50_000, 2048, ""
    rest: list[str] = []
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch_size = int(args[i + 1]); i += 2
        elif args[i] == "--out":
            out_path = args[i + 1]; i += 2
        else:
            rest.append(args[i]); i += 1

    conf = ConfArguments().setAppName("rmse-curve").parse(rest)

    from twtml_tpu.apps.linear_regression import build_model, select_backend
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import ReplayFileSource, SyntheticSource

    select_backend(conf)
    featurizer = Featurizer.from_conf(conf)
    model, row_multiple = build_model(conf)

    if conf.source == "replay":
        if not conf.replayFile:
            raise SystemExit("--source replay requires --replayFile")
        statuses = [
            s for s in ReplayFileSource(conf.replayFile).produce()
            if featurizer.filtrate(s)
        ]
        pre_filtered = True
    else:
        statuses = list(SyntheticSource(total=tweets, seed=7).produce())
        pre_filtered = True

    sink = open(out_path, "w", encoding="utf-8") if out_path else sys.stdout
    count = 0
    t0 = time.perf_counter()
    featurize = (
        featurizer.featurize_batch_units
        if conf.hashOn == "device"
        else featurizer.featurize_batch
    )
    for k in range(0, len(statuses), batch_size):
        chunk = statuses[k : k + batch_size]
        batch = featurize(
            chunk, row_bucket=batch_size, pre_filtered=pre_filtered,
            row_multiple=row_multiple,
        )
        if batch.num_valid == 0:
            continue
        out = model.step(batch)
        count += int(out.count)
        record = {
            "wall_s": round(time.perf_counter() - t0, 4),
            "count": count,
            "batch": int(out.count),
            "rmse": round(float(out.mse) ** 0.5, 3),
        }
        print(json.dumps(record), file=sink, flush=sink is sys.stdout)
    if sink is not sys.stdout:
        sink.close()


if __name__ == "__main__":
    main()
