"""Healthy-path neutrality of the r7 ingest/state guards (ISSUE 4
acceptance): the divergence sentinel (three host isfinite checks per
delivered batch) and the bounded intake queue (one int compare per put)
must cost nothing measurable when nothing is wrong.

Arms (the house interleaved/paired method, tools/pairedbench.py — each
pass is ONE full flagship-app replay run, end to end: source thread,
bounded queue, featurize, FetchPipeline, sentinel gate, checkpoint-free
handler):

- guards_off : --sentinel off --maxQueueRows -1 (the pre-r7 pipeline);
- guards_on  : the shipped defaults (sentinel on, auto queue bound).

The verdict is the median paired off/on ratio; >= 0.98 means the guard
layer ships free. CPU control only unless a TPU is attached — the guards
are pure host work, so the CPU control is the binding measurement.

Usage: python tools/bench_ingest_guard.py [--tweets N] [--batch B]
          [--budget S]
Prints one JSON line (BENCHMARKS.md "Ingest guards" records the result).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget = 32768, 2048, 120.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import tempfile

    import jax

    from tools.bench_suite import _status_json
    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.streaming.sources import SyntheticSource

    tmp = tempfile.mkdtemp(prefix="bench-guard-")
    replay = os.path.join(tmp, "tweets.jsonl")
    with open(replay, "w") as fh:
        for s in SyntheticSource(
            total=n_tweets, seed=5, base_ms=1785320000000
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"  # closed port: telemetry Try paths
    base = [
        "--source", "replay", "--replayFile", replay,
        "--seconds", "0", "--batchBucket", str(batch),
        "--tokenBucket", "512",
        "--lightning", closed, "--twtweb", closed, "--webTimeout", "0.5",
    ]

    def run_app(extra):
        t0 = time.perf_counter()
        totals = app.run(ConfArguments().parse(base + extra))
        dt = time.perf_counter() - t0
        assert totals["count"] == n_tweets, totals
        return dt

    # one warm pass per arm (program compiles; both arms share programs)
    run_app(["--sentinel", "off", "--maxQueueRows", "-1"])
    run_app([])

    times = run_rounds({
        "guards_off": lambda: run_app(
            ["--sentinel", "off", "--maxQueueRows", "-1"]
        ),
        "guards_on": lambda: run_app([]),
    }, budget)

    out = {
        "regime": "ingest-guard-neutrality",
        "tweets": n_tweets, "batch": batch,
        "backend": jax.default_backend(),
        "rounds": len(times["guards_on"]),
    }
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
        }
    out["guards_on"]["paired_vs_off"] = paired_ratio_median(
        times["guards_off"], times["guards_on"]
    )
    out["neutral"] = out["guards_on"]["paired_vs_off"] >= 0.98
    print(json.dumps(out))


if __name__ == "__main__":
    main()


