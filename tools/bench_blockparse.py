"""Zero-copy block parse bench: wire emitter vs legacy ParsedBlock chain.

ISSUE 6 / ROADMAP item 4: the post-wire bottleneck ladder names host parse
(~1.2M tweets/s recorded, r5) as the binding stage of block ingest. This
tool measures the zero-copy wire emitter (``native.parse_tweet_block_wire``
through ``BlockReplayFileSource(wire=True)``) against the legacy parser on
the SAME corpus, with the house method (tools/pairedbench.py): single
passes round-robin all arms inside one budget window, paired per-round
ratios — phase-robust, the only way wire/dispatch verdicts are quoted here.

Two stage pairs, four arms interleaved per round:

  parse:legacy / parse:wire — file bytes → blocks (``produce()`` drained,
      exactly the suite's parse-stage measurement, copy=False views);
  chain:legacy / chain:wire — file bytes → PACKED ragged wire batches
      (produce → iter_row_chunks → featurize_parsed_block(ragged, pack)):
      the full host side of block ingest, no device.

Parity is asserted before timing: blocks unit-for-unit, packed buffers
byte-for-byte. Host-only — no jax, runs on any box.

Usage: python tools/bench_blockparse.py [--tweets N] [--batch B]
       [--budget S] [--blockBytes N] [--corpus ascii|unicode]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch_size, budget = 65536, 1024, 30.0
    block_bytes, corpus = 4 << 20, "ascii"
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch_size = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        elif args[i] == "--blockBytes":
            block_bytes = int(args[i + 1]); i += 2
        elif args[i] == "--corpus":
            corpus = args[i + 1]; i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")
    if corpus not in ("ascii", "unicode"):
        raise SystemExit("--corpus must be ascii or unicode")

    import numpy as np

    from tools.bench_suite import _status_json
    from tools.pairedbench import (
        best_median_rate,
        paired_ratio_median,
        paired_ratios,
        run_rounds,
    )
    from twtml_tpu.features import native
    from twtml_tpu.features.blocks import iter_row_chunks, merge_blocks
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import BlockReplayFileSource

    if not native.wire_available():
        print(json.dumps({"skipped": "native wire emitter unavailable"}))
        return

    # ---- corpus: the suite's synthetic stream, materialized once ---------
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    if corpus == "unicode":
        # ~6% non-ASCII rows: exercises the widen path honestly (the wire
        # parser must carry uint16 end to end once any row widens)
        marks = ("é", "火", "\U0001f600")
        for k, s in enumerate(statuses):
            if k % 16 == 7:
                s.retweeted_status.text += " " + marks[k % 3]
    with tempfile.NamedTemporaryFile(
        "w", suffix=".jsonl", delete=False
    ) as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s), ensure_ascii=False) + "\n")
        path = fh.name

    feat = Featurizer(now_ms=1785320000000)

    def source(wire: bool) -> BlockReplayFileSource:
        return BlockReplayFileSource(
            path, copy=False, block_bytes=block_bytes, wire=wire
        )

    def featurize(sub):
        return feat.featurize_parsed_block(
            sub, row_bucket=batch_size, ragged=True, pack=True
        )

    try:
        # ---- parity gate (never time an unverified fast path) ------------
        legacy = merge_blocks(list(source(False).produce()))
        wire = merge_blocks(list(source(True).produce()))
        rows = legacy.rows
        np.testing.assert_array_equal(legacy.numeric, wire.numeric)
        np.testing.assert_array_equal(legacy.offsets, wire.offsets)
        np.testing.assert_array_equal(legacy.ascii, wire.ascii)
        np.testing.assert_array_equal(
            legacy.units.astype(np.uint16), wire.units.astype(np.uint16)
        )
        for a, b in zip(
            iter_row_chunks([legacy], batch_size),
            iter_row_chunks([wire], batch_size),
        ):
            pa, pb = featurize(a), featurize(b)
            assert pa.layout == pb.layout
            np.testing.assert_array_equal(pa.buffer, pb.buffer)

        # ---- arms (each returns one pass's wall seconds) -----------------
        def parse_pass(wire_on):
            def run():
                t0 = time.perf_counter()
                n = 0
                for b in source(wire_on).produce():
                    n += b.rows
                dt = time.perf_counter() - t0
                assert n == rows
                return dt
            return run

        def chain_pass(wire_on):
            def run():
                t0 = time.perf_counter()
                n = 0
                for sub in iter_row_chunks(
                    source(wire_on).produce(), batch_size
                ):
                    featurize(sub)
                    n += sub.rows
                dt = time.perf_counter() - t0
                assert n == rows
                return dt
            return run

        arms = {
            "parse_legacy": parse_pass(False),
            "parse_wire": parse_pass(True),
            "chain_legacy": chain_pass(False),
            "chain_wire": chain_pass(True),
        }
        for run in arms.values():  # warmup (page cache, allocator, numpy)
            run()
        times = run_rounds(arms, budget, min_rounds=3)

        out = {
            "corpus": corpus,
            "tweets": rows,
            "batch": batch_size,
            "block_bytes": block_bytes,
            "wire_units_dtype": str(wire.units.dtype),
        }
        for name, ts in times.items():
            best, median = best_median_rate(ts, rows)
            out[name] = {
                "tweets_per_sec": best,
                "median_tweets_per_sec": median,
                "passes": len(ts),
            }
        for stage in ("parse", "chain"):
            base, arm = times[f"{stage}_legacy"], times[f"{stage}_wire"]
            out[f"{stage}_paired_speedup_median"] = paired_ratio_median(
                base, arm
            )
            out[f"{stage}_paired_speedup_all"] = [
                round(x, 3) for x in paired_ratios(base, arm)
            ]
        print(json.dumps(out))
    finally:
        os.unlink(path)


if __name__ == "__main__":
    main()
