"""Model-observability overhead check (ISSUE 8): the full --modelWatch
plane — the in-step quality vector riding the StepOutput fetch PLUS the
host-side drift/trend watcher fed per batch — measured against a
quality-off control in the per-batch-telemetry regime (the regime where
per-batch overheads bind; BENCHMARKS.md).

Arms (interleaved single passes + paired per-round ratios, the house
method — tools/pairedbench.py):

- off   : the ``--modelWatch off`` program (no quality leaf — the HEAD
          step program) with no watcher;
- watch : the quality-leaf program + one modelwatch.record_tick per batch
          (drift windows, EWMAs, registry gauges — the full delivered-tick
          cost).

Passes the acceptance gate when the paired ratio (off/watch) is >= 0.97x
(the ISSUE's <= 3% budget).

Usage: python tools/bench_modelwatch.py [--tweets N] [--batch B]
          [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, batch, budget = 65536, 2048, 120.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--batch":
            batch = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import numpy as np

    import jax

    from twtml_tpu.apps.common import FetchPipeline
    from twtml_tpu.features.batch import pack_batch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.telemetry import modelwatch as _modelwatch

    feat = Featurizer(now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())
    chunks = [statuses[i : i + batch] for i in range(0, len(statuses), batch)]
    r_batches = [
        feat.featurize_batch_ragged(c, row_bucket=batch, pre_filtered=True)
        for c in chunks
    ]

    def consume_off(out, b, t, at_boundary=True):
        float(out.count); float(out.mse)
        float(out.real_stdev); float(out.pred_stdev)
        _ = out.predictions[0]

    def consume_watch(out, b, t, at_boundary=True):
        consume_off(out, b, t, at_boundary)
        _modelwatch.record_tick(
            np.asarray(out.quality, np.float64),
            np.asarray(out.count, np.float64),
            np.asarray(out.mse, np.float64),
        )

    model_off = StreamingLinearRegressionWithSGD()
    model_on = StreamingLinearRegressionWithSGD(quality=True)
    seen = set()
    for rb in r_batches:  # warm every packed layout BOTH arms dispatch
        key = (rb.units.shape, str(rb.units.dtype), rb.row_len)
        if key not in seen:
            seen.add(key)
            float(model_off.step(pack_batch(rb)).mse)
            float(model_on.step(pack_batch(rb)).mse)

    def run_pass(model, consume):
        model.reset()
        t0 = time.perf_counter()
        pipe = FetchPipeline(model, consume, depth=8, pack=True)
        for b in r_batches:
            pipe.on_batch(b, 0.0)
        pipe.flush()
        return time.perf_counter() - t0

    def off_pass():
        return run_pass(model_off, consume_off)

    def watch_pass():
        _modelwatch.reset_for_tests()  # fresh windows per pass
        return run_pass(model_on, consume_watch)

    off_pass(); watch_pass()  # warm both arms' code paths

    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )

    times = run_rounds({"off": off_pass, "watch": watch_pass}, budget)
    out = {
        "regime": "modelwatch-overhead", "batch": batch,
        "tweets": n_tweets, "backend": jax.default_backend(),
        "rounds": len(times["off"]),
    }
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {
            "tweets_per_sec_best": best,
            "tweets_per_sec_median": median,
        }
    out["watch"]["paired_vs_off"] = paired_ratio_median(
        times["off"], times["watch"]
    )
    out["neutral"] = out["watch"]["paired_vs_off"] >= 0.97
    print(json.dumps(out))


if __name__ == "__main__":
    main()
