"""Microbenchmark: XLA-compiled SGD inner loop vs the pallas VMEM-resident
kernel (ops/pallas_sgd.py) at the flagship operating point.

Measurement methodology — this build's TPU attaches through a tunnel whose
``block_until_ready`` does NOT wait for device execution (a no-op sync: a
4096³ matmul "measures" 50+ PFLOP/s that way), and whose per-dispatch
overhead is milliseconds. The only honest per-step timing is CHAINED
dispatches with one host fetch at the end: run K data-dependent steps, fetch
a scalar, divide. Even then the resolution floor is the dispatch pipeline,
~100 µs/step — far above the actual device time of either implementation at
2048×1024×50 iterations — so expect both rows to read the same. That
equality IS the result: the kernel is validated and VMEM-fits on hardware,
and no measurable win exists at this model size (BENCHMARKS.md).

Usage: python tools/bench_pallas.py [--rows 2048] [--features 1024]
       [--iters 50] [--chain 32]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    rows, features, iters, chain = 2048, 1024, 50, 32
    i = 0
    while i < len(args):
        if args[i] == "--rows":
            rows = int(args[i + 1]); i += 2
        elif args[i] == "--features":
            features = int(args[i + 1]); i += 2
        elif args[i] == "--iters":
            iters = int(args[i + 1]); i += 2
        elif args[i] == "--chain":
            chain = int(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from twtml_tpu.ops import pallas_sgd

    rng = np.random.default_rng(0)
    x = np.zeros((rows, features), np.float32)
    idx = rng.integers(0, features - 4, size=(rows, 40))
    for r in range(rows):
        np.add.at(x[r], idx[r], 1.0)
    x[:, -4:] = rng.normal(size=(rows, 4)).astype(np.float32) * 0.1
    X = jnp.asarray(x)
    y = jnp.asarray(rng.uniform(100, 1000, size=(rows,)).astype(np.float32))
    m = jnp.ones((rows,), jnp.float32)
    w0 = jnp.zeros((features,), jnp.float32)

    def xla_loop(X, y, m, w):
        # drive the CANONICAL inner loop (models/sgd.py is the one place
        # the parity-critical semantics live) so the comparison can never
        # drift from the shipped path
        from twtml_tpu.models.sgd import sampling_key, sgd_inner_loop

        def grad_and_count(wv, sel):
            residual = (X @ wv - y) * sel
            return X.T @ residual, jnp.sum(sel)

        return sgd_inner_loop(
            w,
            num_iterations=iters,
            step_size=0.005,
            mini_batch_fraction=1.0,
            l2_reg=0.0,
            convergence_tol=0.001,
            mask=m,
            sample_key=sampling_key(None, 1.0),
            grad_and_count=grad_and_count,
        )

    xla_fn = jax.jit(xla_loop)
    pal_fn = jax.jit(
        lambda X, y, m, w: pallas_sgd.fused_dense_sgd(
            X, y, m, w, num_iterations=iters, step_size=0.005
        )[0]
    )

    def chained(fn) -> float:
        """Seconds per step over `chain` data-dependent dispatches, best of 3
        (the fetch at the end forces real completion)."""
        w = fn(X, y, m, w0)
        float(w[0])  # warm compile + transport
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            w = w0
            for _ in range(chain):
                w = fn(X, y, m, w)  # w chains: no overlap, honest total
            float(w[0])
            best = min(best, (time.perf_counter() - t0) / chain)
        return best

    t_xla = chained(xla_fn)
    t_pal = chained(pal_fn)
    diff = float(jnp.max(jnp.abs(xla_fn(X, y, m, w0) - pal_fn(X, y, m, w0))))
    for name, t in (("xla_fori_loop", t_xla), ("pallas_vmem_resident", t_pal)):
        print(json.dumps({
            "impl": name,
            "ms_per_step_upper_bound": round(t * 1000, 3),
            "rows": rows, "features": features, "iters": iters,
            "chain": chain,
            "note": "dispatch-pipeline floor dominates; see module docstring",
        }))
    print(json.dumps({"max_abs_weight_diff": diff}))


if __name__ == "__main__":
    main()
