"""Config #4 (hashing_2e18_l2) operating-point sweep — VERDICT r2 #4.

The 2^18 Gram-domain step is device-bound at batch 2048 (~21 ms: the
G = Z·Zᵀ matmul is ~2.2 TFLOP, ~53% of bf16 peak — BENCHMARKS.md). But the
G build costs B²·F FLOPs, i.e. PER-TWEET device cost scales linearly with
batch size, so a smaller batch trades per-batch overheads for less G work
per tweet. This tool interleaves arms (batch size × wire × superbatch)
within one window — single passes round-robin, so tunnel phase swings hit
every arm equally — and reports each arm's best/median plus per-round
rates, to pick the config #4 operating point from data.

Usage: python tools/bench_2e18.py [--tweets N] [--budget S]
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

F_TEXT = 2**18


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    n_tweets, budget = 65536, 240.0
    i = 0
    while i < len(args):
        if args[i] == "--tweets":
            n_tweets = int(args[i + 1]); i += 2
        elif args[i] == "--budget":
            budget = float(args[i + 1]); i += 2
        else:
            raise SystemExit(f"unknown flag {args[i]!r}")

    import jax

    from twtml_tpu.features.batch import stack_batches
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource
    from twtml_tpu.utils.benchloop import _run_once

    feat = Featurizer(num_text_features=F_TEXT, now_ms=1785320000000)
    statuses = list(SyntheticSource(total=n_tweets, seed=3).produce())

    def chunked(b):
        return [statuses[i : i + b] for i in range(0, len(statuses), b)]

    def model(int8=None):
        # gram_int8 is threaded as a trace-time PARAMETER (not a module
        # global): the ragged wire retraces per flat-buffer bucket, and a
        # global flag would leave every post-warmup trace on the default
        # plane — the A/B arms would silently converge
        return StreamingLinearRegressionWithSGD(
            num_text_features=F_TEXT, l2_reg=0.1, gram_int8=int8
        )

    arms: dict = {}

    def pipeline_arm(name, batch, wire, int8=None):
        chunks = chunked(batch)
        fz = (
            (lambda c: feat.featurize_batch_ragged(
                c, row_bucket=batch, pre_filtered=True))
            if wire == "ragged"
            else (lambda c: feat.featurize_batch_units(
                c, row_bucket=batch, pre_filtered=True))
        )
        m = model(int8)
        for _ in range(2):
            float(m.step(fz(chunks[0])).mse)  # completion-fetch warmup

        def one_pass(m=m, fz=fz, chunks=chunks):
            m.reset()
            return _run_once(m, fz, chunks, prefetch=True)

        arms[name] = one_pass

    def superbatch_arm(name, batch, k):
        # K batches stacked into one step_many dispatch (padded wire —
        # ragged doesn't stack); featurize+stack on a prefetch thread
        from concurrent.futures import ThreadPoolExecutor

        chunks = chunked(batch)
        groups = [chunks[i : i + k] for i in range(0, len(chunks), k)]

        def fz(group):
            return stack_batches([
                feat.featurize_batch_units(
                    c, row_bucket=batch, pre_filtered=True
                )
                for c in group
            ])

        m = model()
        warm = fz(groups[0])
        for _ in range(2):
            float(m.step_many(warm).mse[-1])

        def one_pass():
            m.reset()
            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=1) as pool:
                pending = pool.submit(fz, groups[0])
                for nxt in groups[1:]:
                    stacked = pending.result()
                    pending = pool.submit(fz, nxt)
                    m.step_many(stacked)
                last = m.step_many(pending.result())
            float(last.mse[-1])  # completion fetch closes the window
            return time.perf_counter() - t0, last

        arms[name] = one_pass

    pipeline_arm("padded_b2048", 2048, "padded")  # the r2 operating point
    pipeline_arm("ragged_b2048", 2048, "ragged", int8=True)
    pipeline_arm("ragged_b3072", 3072, "ragged", int8=True)  # r4 point
    pipeline_arm("ragged_b4096", 4096, "ragged", int8=True)  # past-the-optimum
    pipeline_arm("ragged_b1024", 1024, "ragged", int8=True)  # r3 point
    pipeline_arm("ragged_b1024_bf16", 1024, "ragged", int8=False)  # r3 plane A/B
    pipeline_arm("ragged_b2048_bf16", 2048, "ragged", int8=False)
    pipeline_arm("ragged_b512", 512, "ragged")
    pipeline_arm("padded_b1024", 1024, "padded")
    superbatch_arm("padded_b2048_k8", 2048, 8)

    # the house interleaved/paired scheduling (tools/pairedbench.py)
    from tools.pairedbench import (
        best_median_rate, paired_ratio_median, run_rounds,
    )

    times = run_rounds(arms, budget)

    out = {"config": "hashing_2e18_l2_sweep", "tweets": n_tweets,
           "backend": jax.default_backend(), "rounds": len(times["padded_b2048"])}
    for name, ts in times.items():
        best, median = best_median_rate(ts, n_tweets)
        out[name] = {"best": best, "median": median}
    base = times["padded_b2048"]
    for name, ts in times.items():
        if name != "padded_b2048":
            out[name]["paired_speedup_median"] = paired_ratio_median(base, ts)
    # the int8-plane question, answered directly: same wire, same batch,
    # per-round ratios of the bf16-plane arm over the int8-plane arm
    for b in (1024, 2048):
        i8, bf = times.get(f"ragged_b{b}"), times.get(f"ragged_b{b}_bf16")
        if i8 and bf:
            out[f"int8_vs_bf16_b{b}"] = paired_ratio_median(bf, i8)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
