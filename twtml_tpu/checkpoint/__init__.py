from .checkpointer import Checkpointer

__all__ = ["Checkpointer"]
