"""Model checkpoint/resume — an upgrade the reference lacks.

The reference never checkpoints model weights: a restarted job begins from
zeros (LinearRegression.scala:32; SURVEY.md §5.4 flags this as the gap —
only the web server's Config JSON survives restarts). Here the full learner
state (weight pytree + cumulative counters + batch index) is saved every N
batches and restored on start, so a crashed/restarted streaming job resumes
its RMSE curve instead of relearning from scratch.

Format: one .npz per checkpoint (atomic rename), flat key namespace for the
weight pytree, JSON sidecar metadata inside the archive. keep_last bounds
disk use. Works for single-device and mesh-sharded states (arrays are pulled
to host; on restore the model re-shards via its own set_initial_weights).
"""

from __future__ import annotations

import io
import json
import os
import tempfile

import numpy as np

from ..utils import get_logger

log = get_logger("checkpoint")


class Checkpointer:
    def __init__(self, directory: str, keep_last: int = 3):
        self.directory = directory
        self.keep_last = keep_last
        os.makedirs(directory, exist_ok=True)
        self._sweep_stale_tmp()

    def _sweep_stale_tmp(self) -> None:
        """A hard kill between mkstemp and the atomic rename leaks the
        ``*.tmp`` forever — ``_prune`` only matches finished
        ``ckpt-*.npz`` names, so sweep them at startup. Safe: once this
        process runs, it is the directory's only writer (multi-host
        writes are lead-only, apps/common.AppCheckpoint)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.directory, name))
                    log.info("swept stale checkpoint temp file %s", name)
                except OSError:
                    pass

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step:012d}.npz")

    def save(self, step: int, weights, metadata: dict | None = None) -> str:
        """Atomically write weights (array or flat dict of arrays) + metadata
        at the given step; prunes old checkpoints beyond keep_last."""
        arrays: dict[str, np.ndarray] = {}
        if isinstance(weights, dict):
            for key, value in weights.items():
                arrays[f"w__{key}"] = np.asarray(value)
        else:
            arrays["w"] = np.asarray(weights)
        meta = dict(metadata or {})
        meta["step"] = int(step)
        buf = io.BytesIO()
        np.savez(buf, __meta__=np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8), **arrays)
        final = self._path(step)
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(buf.getvalue())
            os.replace(tmp, final)  # atomic on POSIX
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        self._prune()
        log.info("checkpoint saved: %s", final)
        return final

    def _checkpoints(self) -> list[str]:
        try:
            names = [
                n for n in os.listdir(self.directory)
                if n.startswith("ckpt-") and n.endswith(".npz")
            ]
        except FileNotFoundError:
            return []
        return sorted(names)

    def _prune(self) -> None:
        names = self._checkpoints()
        for name in names[: -self.keep_last]:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass

    def latest_step(self) -> int | None:
        names = self._checkpoints()
        if not names:
            return None
        return int(names[-1][len("ckpt-") : -len(".npz")])

    def restore(self, step: int | None = None):
        """(weights, metadata) of the given/latest checkpoint, or None.
        Corrupt newest checkpoints fall back to older ones (crash-during-
        write tolerance beyond the atomic rename)."""
        names = self._checkpoints()
        if step is not None:
            names = [n for n in names if n == os.path.basename(self._path(step))]
        for name in reversed(names):
            path = os.path.join(self.directory, name)
            try:
                with np.load(path) as data:
                    meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
                    keys = [k for k in data.files if k != "__meta__"]
                    if keys == ["w"]:
                        weights = data["w"]
                    else:
                        weights = {
                            k[len("w__"):]: data[k] for k in keys
                        }
                return weights, meta
            except Exception:
                log.warning("unreadable checkpoint %s; trying older", path)
        return None
