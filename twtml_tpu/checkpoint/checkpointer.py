"""Model checkpoint/resume — an upgrade the reference lacks.

The reference never checkpoints model weights: a restarted job begins from
zeros (LinearRegression.scala:32; SURVEY.md §5.4 flags this as the gap —
only the web server's Config JSON survives restarts). Here the full learner
state (weight pytree + cumulative counters + batch index) is saved every N
batches and restored on start, so a crashed/restarted streaming job resumes
its RMSE curve instead of relearning from scratch.

Format: one .npz per checkpoint (atomic rename), flat key namespace for the
weight pytree, JSON sidecar metadata inside the archive. keep_last bounds
disk use. Works for single-device and mesh-sharded states (arrays are pulled
to host; on restore the model re-shards via its own set_initial_weights).

Integrity (r7): the checkpoint is the divergence sentinel's rollback target
(apps/common.DivergenceSentinel), so it must be trustworthy on two axes the
atomic rename alone cannot give:

- **Corruption**: each array's CRC32 (+ dtype/shape) is recorded in the
  meta; ``restore`` re-hashes and falls back past any archive whose bytes
  no longer match — a torn or bit-flipped file that still ``np.load``s
  would otherwise restore garbage weights silently.
- **Finiteness**: the meta records whether every float array was finite at
  save time. ``save`` refuses to let non-finite weights overwrite good
  history (within ``keep_last`` saves a diverged model would poison every
  checkpoint): they are quarantined to a ``quarantine-*`` name instead,
  preserved for postmortems but invisible to ``restore``. ``restore``
  additionally skips any (legacy) archive holding non-finite weights, so a
  rollback always lands on a verified-finite state.
"""

from __future__ import annotations

import io
import json
import os
import re
import tempfile
import zlib

import numpy as np

from ..utils import get_logger

log = get_logger("checkpoint")

# finished checkpoints only: a stray name sharing the prefix (editor
# backup, partial copy) must never crash the int(...) step parse
_CKPT_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def _array_crc(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes())


def _is_finite(a: np.ndarray) -> bool:
    """Whether an array holds only finite values; non-float dtypes are
    trivially finite (isfinite rejects them)."""
    if not np.issubdtype(a.dtype, np.floating) and not np.issubdtype(
        a.dtype, np.complexfloating
    ):
        return True
    return bool(np.isfinite(a).all())


class Checkpointer:
    def __init__(self, directory: str, keep_last: int = 3):
        self.directory = directory
        self.keep_last = keep_last
        os.makedirs(directory, exist_ok=True)
        self._sweep_stale_tmp()

    def _sweep_stale_tmp(self) -> None:
        """A hard kill between mkstemp and the atomic rename leaks the
        ``*.tmp`` forever — ``_prune`` only matches finished
        ``ckpt-*.npz`` names, so sweep them at startup. Safe: once this
        process runs, it is the directory's only writer (multi-host
        writes are lead-only, apps/common.AppCheckpoint)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.directory, name))
                    log.info("swept stale checkpoint temp file %s", name)
                except OSError:
                    pass

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step:012d}.npz")

    @staticmethod
    def _as_arrays(weights) -> "dict[str, np.ndarray]":
        arrays: dict[str, np.ndarray] = {}
        if isinstance(weights, dict):
            for key, value in weights.items():
                arrays[f"w__{key}"] = np.asarray(value)
        else:
            arrays["w"] = np.asarray(weights)
        return arrays

    def save(self, step: int, weights, metadata: dict | None = None) -> str:
        """Atomically write weights (array or flat dict of arrays) + metadata
        at the given step; prunes old checkpoints beyond keep_last.

        The meta records per-array CRC32/dtype/shape and a ``finite`` flag.
        NON-FINITE weights never overwrite good history: they are written
        under a ``quarantine-`` name ``restore`` ignores (a diverged model
        checkpointing on cadence would otherwise rotate every good archive
        out of ``keep_last`` within N saves)."""
        arrays = self._as_arrays(weights)
        meta = dict(metadata or {})
        meta["step"] = int(step)
        finite = all(_is_finite(a) for a in arrays.values())
        meta["finite"] = finite
        meta["arrays"] = {
            key: {
                "crc": _array_crc(a),
                "dtype": str(a.dtype),
                "shape": list(a.shape),
            }
            for key, a in arrays.items()
        }
        buf = io.BytesIO()
        np.savez(buf, __meta__=np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8), **arrays)
        if not finite:
            final = os.path.join(
                self.directory, f"quarantine-ckpt-{int(step):012d}.npz"
            )
        else:
            final = self._path(step)
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(buf.getvalue())
            os.replace(tmp, final)  # atomic on POSIX
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        if not finite:
            from ..telemetry import metrics as _metrics

            _metrics.get_registry().counter("checkpoint.quarantined").inc()
            log.error(
                "weights at step %d are NON-FINITE: quarantined to %s "
                "instead of overwriting good history (restore ignores it)",
                step, final,
            )
            return final
        self._prune()
        log.info("checkpoint saved: %s", final)
        return final

    def _checkpoints(self) -> list[str]:
        try:
            names = [
                n for n in os.listdir(self.directory) if _CKPT_RE.match(n)
            ]
        except FileNotFoundError:
            return []
        return sorted(names)

    def _prune(self) -> None:
        names = self._checkpoints()
        for name in names[: -self.keep_last]:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass

    def latest_step(self) -> int | None:
        names = self._checkpoints()
        if not names:
            return None
        return int(_CKPT_RE.match(names[-1]).group(1))

    def _read_meta(self, name: str) -> dict | None:
        """Meta sidecar of one archive, arrays untouched (np.load is lazy
        per entry, so this reads a few KB, not the weights)."""
        try:
            with np.load(os.path.join(self.directory, name)) as data:
                return json.loads(bytes(data["__meta__"]).decode("utf-8"))
        except Exception:
            log.warning("unreadable checkpoint meta in %s; skipped", name)
            return None

    def latest_meta(self) -> dict | None:
        """Newest readable archive's meta (no state load, no verification)
        — the intake journal reads its local replay cursor from here when
        a broadcast rollback names only (count, batches)."""
        for name in reversed(self._checkpoints()):
            meta = self._read_meta(name)
            if meta is not None:
                return meta
        return None

    def oldest_meta(self) -> dict | None:
        """Oldest RETAINED archive's meta — journal segments retire only
        once covered by every checkpoint a fallback restore could land on,
        so retirement keys on the oldest cursor still on disk."""
        for name in self._checkpoints():
            meta = self._read_meta(name)
            if meta is not None:
                return meta
        return None

    @staticmethod
    def _verify(path: str, meta: dict, arrays: "dict[str, np.ndarray]") -> bool:
        """Integrity + finiteness gate for one loaded archive; False means
        the caller must fall back to an older checkpoint. Distinct warnings
        per failure class so an operator can tell bit-rot from divergence.
        Archives written before the integrity meta existed verify by
        recomputed finiteness alone."""
        from ..telemetry import metrics as _metrics

        declared = meta.get("arrays")
        if declared is not None:
            if sorted(declared) != sorted(arrays):
                log.warning(
                    "corrupt checkpoint %s: archive keys %s do not match "
                    "the declared meta %s; trying older",
                    path, sorted(arrays), sorted(declared),
                )
                _metrics.get_registry().counter(
                    "checkpoint.restore_corrupt").inc()
                return False
            for key, spec in declared.items():
                a = arrays[key]
                if (
                    str(a.dtype) != spec["dtype"]
                    or list(a.shape) != list(spec["shape"])
                    or _array_crc(a) != spec["crc"]
                ):
                    log.warning(
                        "corrupt checkpoint %s: array %r failed "
                        "CRC/shape/dtype verification; trying older",
                        path, key,
                    )
                    _metrics.get_registry().counter(
                        "checkpoint.restore_corrupt").inc()
                    return False
        finite = meta.get("finite")
        if finite is None:  # legacy archive: compute what save() now records
            finite = all(_is_finite(a) for a in arrays.values())
        if not finite:
            log.warning(
                "checkpoint %s holds NON-FINITE weights (a diverged save); "
                "trying older", path,
            )
            _metrics.get_registry().counter(
                "checkpoint.restore_nonfinite").inc()
            return False
        return True

    def restore(self, step: int | None = None):
        """(weights, metadata) of the given/latest VERIFIED checkpoint, or
        None. Falls back past unreadable archives (crash-during-write
        tolerance beyond the atomic rename), past corrupt ones (per-array
        CRC/shape/dtype), and past non-finite ones (divergence) — each with
        its own warning."""
        names = self._checkpoints()
        if step is not None:
            names = [n for n in names if n == os.path.basename(self._path(step))]
        for name in reversed(names):
            path = os.path.join(self.directory, name)
            try:
                with np.load(path) as data:
                    meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
                    keys = [k for k in data.files if k != "__meta__"]
                    arrays = {k: data[k] for k in keys}
            except Exception:
                log.warning("unreadable checkpoint %s; trying older", path)
                continue
            if not self._verify(path, meta, arrays):
                continue
            if sorted(arrays) == ["w"]:
                return arrays["w"], meta
            return {k[len("w__"):]: a for k, a in arrays.items()}, meta
        return None
