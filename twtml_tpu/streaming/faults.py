"""Fault injection for stream sources AND the transport below them
(SURVEY.md §5.3: the reference has no fault injection anywhere; receiver
recovery was whatever Spark defaulted to).

``FaultInjectingSource`` wraps any Source and raises a simulated receiver
crash every ``crash_every`` tweets (deterministic) or with probability
``crash_prob`` per tweet (seeded) — exercising the supervision/restart/backoff
harness end-to-end in tests and chaos runs. Emitted tweets are passed through
unchanged; a crash loses the in-flight iterator exactly like a dropped
socket, so delivery gaps behave like the real failure mode.

``ChaosInjector`` (``--chaos SPEC``) extends the same idea BELOW the source
layer, to the external dependencies the tunnel facts make the real failure
domain (BENCHMARKS.md "Measurement integrity": stalls burst for minutes,
RTT 50–90 ms): seeded latency spikes / multi-second stalls / exceptions at
three injection points —

- ``fetch``  — the pooled ``device_get``s (FetchPipeline / SuperBatcher),
- ``step``   — the device dispatch (``model.step``/``step_many``),
- ``web``    — every dashboard HTTP request (``WebClient._request``),

so the runtime guards those points carry (fetch deadline/retry/abort, the
publish circuit breaker, the lockstep watchdogs) are testable end-to-end.

r7 adds SOURCE/PARSE chaos — the untrusted-data failure domain the ingest
guards exist for (bounded backpressure, the divergence sentinel, verified
checkpoints):

- ``source.garbage`` — corrupt (truncate + garble) a block source's raw
  byte buffer before the parser sees it: the parser must skip, count, and
  never crash (one corrupted chunk can also bleed into the next via the
  carry, exactly like real wire damage),
- ``source.burst``  — re-emit the current item N extra times (a rate
  spike), exercising the bounded intake queue's block/shed policies,
- ``source.nan``    — poison every valid label of the current featurized
  batch with NaN: the model diverges in one step, exercising the
  divergence sentinel's rollback-to-verified-checkpoint path.

Spec grammar (comma-separated clauses):

    TARGET[:ACTION][@TRIGGER]   or   seed=N

    ACTION   delay=SECONDS (sleep before the call — a spike or a stall,
             depending on magnitude; ``stall=`` is an alias) | error
             (raise InjectedFault instead of the call) — fetch/step/web
             targets only. ``source.*`` targets take no action (the
             injection IS the action), except ``source.burst:rows=N``
             (extra re-emits per firing; default 4).
    TRIGGER  N       every Nth call of that target (deterministic)
             pP      probability P per call (seeded RNG)
             fromN   every call from the Nth on (a permanent outage)
             default: every call

Example: ``--chaos "fetch:delay=2@3,source.nan@5,source.burst:rows=8@p0.1,seed=7"``
"""

from __future__ import annotations

import random
import threading
import time
from typing import Iterator

from ..utils import get_logger
from .sources import Source

log = get_logger("streaming.faults")

TRANSPORT_TARGETS = ("fetch", "step", "web")
SOURCE_TARGETS = ("source.garbage", "source.burst", "source.nan")
# membership churn (r16, ISSUE 13): peer death/stall injectable from the
# CLI like every other fault — previously only reachable via
# tests/distributed_worker.py's peer_kill mode
PEER_TARGETS = ("peer.kill", "peer.pause")
CHAOS_TARGETS = TRANSPORT_TARGETS + SOURCE_TARGETS + PEER_TARGETS

# extra re-emits per source.burst firing when the rule gives no rows=N
BURST_DEFAULT_EXTRA = 4
# default peer.pause stall length (lockstep ticks' worth of wall time)
PAUSE_DEFAULT_TICKS = 4
# exit code of a peer.kill hard death (test-assertable, distinct from the
# jax coordination-service SIGABRT and from clean failures)
PEER_KILL_EXIT_CODE = 77


class InjectedFault(ConnectionError):
    pass


class _ChaosRule:
    """One parsed ``TARGET:ACTION[@TRIGGER]`` clause."""

    __slots__ = ("target", "kind", "value", "mode", "param", "uid")

    def __init__(self, target: str, kind: str, value: float, mode: str,
                 param: float, uid: int = -1):
        self.target = target
        self.kind = kind  # "delay" | "error"
        self.value = value  # sleep seconds (delay only)
        self.mode = mode  # "every" | "prob" | "from"
        self.param = param
        # peer.* host selector: fire only on the host whose ORIGINAL
        # process uid matches (-1 = every host). This is what makes
        # kill-the-lead expressible from one fleet-wide --chaos spec:
        # peer.kill:uid=0:tick=4 kills exactly the launch lead.
        self.uid = int(uid)

    def fires(self, call_index: int, rng: random.Random) -> bool:
        if self.mode == "every":
            return call_index % int(self.param) == 0
        if self.mode == "from":
            return call_index >= int(self.param)
        return rng.random() < self.param

    def on_host(self, uid: int) -> bool:
        return self.uid < 0 or self.uid == int(uid)

    def __repr__(self) -> str:  # shows up in the install log line
        sel = f" uid={self.uid}" if self.uid >= 0 else ""
        if self.kind == "kill":
            return f"{self.target}{sel} (at lockstep tick {int(self.value)})"
        act = (
            "error" if self.kind == "error"
            else "inject" if self.kind == "inject"
            else f"pause={int(self.value)} ticks" if self.kind == "pause"
            else f"delay={self.value:g}s"
        )
        trig = {"every": "every %d", "from": "from call %d on",
                "prob": "p=%g"}[self.mode] % self.param
        return f"{self.target}{sel}:{act} ({trig})"


def _parse_trigger(text: str) -> "tuple[str, float]":
    if text.startswith("p"):
        p = float(text[1:])
        if not 0.0 < p <= 1.0:
            raise ValueError(f"probability trigger out of (0, 1]: {text!r}")
        return "prob", p
    if text.startswith("from"):
        n = int(text[len("from"):])
        if n < 1:
            raise ValueError(f"'from' trigger must be >= 1: {text!r}")
        return "from", n
    n = int(text)
    if n < 1:
        raise ValueError(f"every-Nth trigger must be >= 1: {text!r}")
    return "every", n


class ChaosInjector:
    """Seeded transport-fault injector. ``perturb(target)`` is called at
    each injection point: it may sleep (latency spike / stall) and/or raise
    ``InjectedFault`` according to the parsed rules. Thread-safe — the
    pooled fetch calls it from worker threads; sleeps happen outside the
    lock so concurrent fetches stall independently, like real tunnel
    stalls. Deterministic for a given seed and per-target call sequence."""

    def __init__(self, spec: str):
        self.spec = spec
        seed = 0
        rules: list[_ChaosRule] = []
        for raw in spec.split(","):
            clause = raw.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                seed = int(clause[len("seed="):])
                continue
            body, _, trigger = clause.partition("@")
            target, sep, action = body.partition(":")
            if target not in CHAOS_TARGETS:
                raise ValueError(
                    f"bad chaos clause {clause!r}: want TARGET[:ACTION] with "
                    f"TARGET in {CHAOS_TARGETS}"
                )
            mode, param = _parse_trigger(trigger) if trigger else ("every", 1)
            if target in PEER_TARGETS:
                # membership churn: peer.kill[:uid=U][:tick=N] hard-exits
                # host U (every host when no uid) at lockstep tick N
                # (default 1); peer.pause[:uid=U][:ticks=K] stalls it for
                # ~K ticks' wall time at the trigger's ticks. Parts are
                # colon-separated and order-free.
                count_key = "tick" if target == "peer.kill" else "ticks"
                uid, value = -1, None
                for part in filter(None, action.split(":")):
                    key, eq, num = part.partition("=")
                    if not eq or key not in ("uid", count_key):
                        raise ValueError(
                            f"bad chaos action {part!r} in {clause!r}: "
                            f"{target} takes {count_key}=N and uid=U"
                        )
                    if key == "uid":
                        uid = int(num)
                        if uid < 0:
                            raise ValueError(
                                f"negative uid in {clause!r}"
                            )
                    else:
                        value = int(num)
                        if value < 1:
                            raise ValueError(
                                f"non-positive {count_key} in {clause!r}"
                            )
                if target == "peer.kill":
                    value = 1 if value is None else value
                    rules.append(
                        _ChaosRule(target, "kill", value, "every", value,
                                   uid=uid)
                    )
                else:
                    value = PAUSE_DEFAULT_TICKS if value is None else value
                    rules.append(
                        _ChaosRule(target, "pause", value, mode, param,
                                   uid=uid)
                    )
                continue
            if target in SOURCE_TARGETS:
                # the injection IS the action; only source.burst takes a
                # magnitude (rows=N extra re-emits per firing)
                if action.startswith("rows="):
                    if target != "source.burst":
                        raise ValueError(
                            f"rows= only applies to source.burst, not {clause!r}"
                        )
                    value = int(action.partition("=")[2])
                    if value < 1:
                        raise ValueError(f"non-positive rows in {clause!r}")
                elif action:
                    raise ValueError(
                        f"bad chaos action {action!r} in {clause!r}: "
                        "source targets take no action (source.burst "
                        "accepts rows=N)"
                    )
                else:
                    value = BURST_DEFAULT_EXTRA
                rules.append(_ChaosRule(target, "inject", value, mode, param))
            elif action == "error":
                rules.append(_ChaosRule(target, "error", 0.0, mode, param))
            elif action.startswith(("delay=", "stall=")):
                value = float(action.partition("=")[2])
                if value <= 0:
                    raise ValueError(f"non-positive delay in {clause!r}")
                rules.append(_ChaosRule(target, "delay", value, mode, param))
            else:
                raise ValueError(
                    f"bad chaos action {action!r} in {clause!r}: want "
                    "delay=SECONDS, stall=SECONDS, or error"
                )
        if not rules:
            raise ValueError(f"chaos spec {spec!r} names no injection rules")
        self._rules: dict[str, list[_ChaosRule]] = {}
        for r in rules:
            self._rules.setdefault(r.target, []).append(r)
        self._rng = random.Random(seed)
        self._calls = {t: 0 for t in CHAOS_TARGETS}
        self._lock = threading.Lock()

    def perturb(self, target: str) -> None:
        """Apply this call's injections for ``target``: sleep for every
        firing delay rule, then raise if any error rule fired."""
        rules = self._rules.get(target)
        if not rules:
            return
        with self._lock:
            self._calls[target] += 1
            n = self._calls[target]
            fired = [r for r in rules if r.fires(n, self._rng)]
        if not fired:
            return
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import metrics as _metrics

        reg = _metrics.get_registry()
        raise_after = False
        for r in fired:
            reg.counter("chaos.injected").inc()
            # flight-recorder ring: a post-mortem over a chaos run must
            # show which rules fired on the way down (no-op when no
            # recorder is installed)
            _blackbox.record(
                "chaos", target=target, action=r.kind, call=n,
            )
            if r.kind == "delay":
                reg.counter(f"chaos.{target}.delays").inc()
                log.warning(
                    "chaos: injecting %.2fs %s into %s call #%d",
                    r.value, "stall" if r.value >= 1 else "delay", target, n,
                )
                time.sleep(r.value)
            else:
                reg.counter(f"chaos.{target}.errors").inc()
                raise_after = True
        if raise_after:
            raise InjectedFault(f"injected {target} fault (call #{n})")

    def should(self, target: str) -> "float | None":
        """Source-injection query: count one call of ``target`` and return
        the firing rule's magnitude (``source.burst`` rows; 1 otherwise), or
        None when nothing fires. Never sleeps or raises — the CALLER owns
        the injection (corrupting bytes, duplicating emits, poisoning
        labels), this just decides whether and how much."""
        rules = self._rules.get(target)
        if not rules:
            return None
        with self._lock:
            self._calls[target] += 1
            n = self._calls[target]
            fired = [r for r in rules if r.fires(n, self._rng)]
        if not fired:
            return None
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import metrics as _metrics

        reg = _metrics.get_registry()
        value = 0.0
        for r in fired:
            reg.counter("chaos.injected").inc()
            reg.counter(f"chaos.{target}.injected").inc()
            _blackbox.record("chaos", target=target, action="inject", call=n)
            value = max(value, r.value)
        return value

    def calls(self, target: str) -> int:
        return self._calls.get(target, 0)

    def peer_chaos(self, tick: int, interval: float, uid: int = -1) -> None:
        """``peer.kill``/``peer.pause`` injection, driven by the lockstep
        scheduler once per tick (the TICK NUMBER is the call index —
        deterministic on every host, so a rule fires at the same point of
        each host's own loop). ``uid`` is this host's original process id;
        rules with a uid selector fire only on the matching host. A kill
        is a HARD exit (``os._exit`` with ``PEER_KILL_EXIT_CODE``): no
        abort broadcast, no goodbye — exactly the failure the peer
        watchdog + elastic rescue path exist for. A pause sleeps ~K ticks'
        worth of wall time (``K x max(interval, 0.5s)``), long enough to
        trip the peer watchdog when K x interval exceeds
        ``TWTML_LOCKSTEP_TIMEOUT_S``."""
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import metrics as _metrics

        for r in self._rules.get("peer.kill", ()):
            if tick == int(r.value) and r.on_host(uid):
                log.critical(
                    "chaos: peer.kill firing at lockstep tick %d — hard "
                    "exit %d (no abort broadcast)", tick,
                    PEER_KILL_EXIT_CODE,
                )
                _metrics.get_registry().counter("chaos.injected").inc()
                _blackbox.record(
                    "chaos", target="peer.kill", tick=tick, uid=uid,
                )
                import os as _os
                import sys as _sys

                _sys.stdout.flush()
                _sys.stderr.flush()
                _os._exit(PEER_KILL_EXIT_CODE)
        rules = self._rules.get("peer.pause", ())
        if not rules:
            return
        with self._lock:
            # every host draws the SAME rng sequence (rules evaluate before
            # the uid filter) so uid-selected rules never desynchronize the
            # prob-mode draws of unselected rules across the fleet
            fired = [r for r in rules if r.fires(tick, self._rng)]
        fired = [r for r in fired if r.on_host(uid)]
        for r in fired:
            dur = int(r.value) * max(float(interval), 0.5)
            _metrics.get_registry().counter("chaos.injected").inc()
            _metrics.get_registry().counter("chaos.peer.pauses").inc()
            _blackbox.record(
                "chaos", target="peer.pause", tick=tick, secs=round(dur, 2),
            )
            log.warning(
                "chaos: peer.pause stalling this host %.1fs (~%d ticks) "
                "at lockstep tick %d", dur, int(r.value), tick,
            )
            time.sleep(dur)


# process-wide injector: injection points are scattered across layers
# (apps/common fetch+dispatch, telemetry/web_client) and all belong to the
# one run-level chaos configuration the --chaos flag names
_CHAOS: "ChaosInjector | None" = None


def install_chaos(spec: str) -> ChaosInjector:
    """Parse + activate a chaos spec process-wide (``--chaos`` wiring;
    raises ValueError on a malformed spec)."""
    global _CHAOS
    _CHAOS = ChaosInjector(spec)
    log.warning(
        "transport chaos ACTIVE: %s",
        "; ".join(repr(r) for rs in _CHAOS._rules.values() for r in rs),
    )
    return _CHAOS


def uninstall_chaos() -> None:
    global _CHAOS
    _CHAOS = None


def get_chaos() -> "ChaosInjector | None":
    return _CHAOS


def perturb(target: str) -> None:
    """Module-level injection point: no-op unless a chaos spec is
    installed (one global read on the hot path)."""
    if _CHAOS is not None:
        _CHAOS.perturb(target)


def lockstep_chaos(tick: int, interval: float, uid: int = -1) -> None:
    """``peer.*`` injection point, called by the lockstep scheduler at the
    top of every tick (streaming/context._lockstep_loop) with this host's
    original process uid. No-op unless a chaos spec with peer rules is
    installed."""
    if _CHAOS is not None:
        _CHAOS.peer_chaos(tick, interval, uid=uid)


# -- source/parse injection points (r7 — the ingest-guard failure domain) ----


def maybe_corrupt_block(data: bytes) -> bytes:
    """``source.garbage`` injection point (block sources' bytes → parser
    stage): truncate the buffer mid-line and garble a window, simulating a
    torn/damaged wire chunk. The parser contract (skip malformed lines,
    never crash, count the skips) absorbs it; the truncated tail rides the
    carry into the next chunk like real damage would.

    Buffers under 256 bytes pass untouched (and don't count a call): the
    parser's capacity/tail loops re-parse their own shrinking carry, and
    re-corrupting every remnant would chase it to zero forever instead of
    modeling one damaged chunk."""
    if _CHAOS is None or len(data) < 256:
        return data
    if _CHAOS.should("source.garbage") is None:
        return data
    cut = max(1, len(data) * 2 // 3)
    corrupted = bytearray(data[:cut])
    lo = max(0, cut // 2 - 16)
    for i in range(lo, min(len(corrupted), lo + 32)):
        corrupted[i] ^= 0xFF
    log.warning(
        "chaos: corrupted a %d-byte block buffer (truncated to %d, "
        "garbled 32 bytes)", len(data), cut,
    )
    return bytes(corrupted)


def burst_extra() -> int:
    """``source.burst`` injection point (source emit loop): number of EXTRA
    re-emits of the current item this call (0 = no burst). A burst of
    duplicated items is a rate spike the bounded intake queue must absorb
    (block) or shed (shed-oldest) — rows, not wall-clock, is what the
    backpressure bound meters."""
    if _CHAOS is None:
        return 0
    v = _CHAOS.should("source.burst")
    return int(v) if v else 0


def maybe_poison_labels(batch):
    """``source.nan`` injection point (featurize stage): return ``batch``
    with every VALID row's label poisoned to NaN (padding rows keep their
    zeros — the learner multiplies by mask, and poisoned padding would
    taint even batches the rule never fired on). One poisoned batch drives
    the fused predict-then-train step's weights non-finite in a single
    update — the exact event the divergence sentinel exists to catch."""
    if _CHAOS is None:
        return batch
    if _CHAOS.should("source.nan") is None:
        return batch
    import numpy as np

    label = np.array(batch.label, copy=True)
    valid = np.asarray(batch.mask) > 0
    if not valid.any():
        return batch
    label[valid] = np.nan
    log.warning(
        "chaos: poisoned %d label(s) with NaN in a %d-row batch",
        int(valid.sum()), label.shape[0],
    )
    if hasattr(batch, "_replace"):  # FeatureBatch / UnitBatch NamedTuples
        return batch._replace(label=label)
    from ..features.batch import RaggedUnitBatch

    if isinstance(batch, RaggedUnitBatch):
        return RaggedUnitBatch(
            batch.units, batch.offsets, batch.numeric, label, batch.mask,
            row_len=batch.row_len, num_shards=batch.num_shards,
        )
    raise TypeError(f"source.nan cannot poison a {type(batch).__name__}")


class FaultInjectingSource(Source):
    name = "fault-injecting"

    def __init__(
        self,
        inner: Source,
        crash_every: int = 0,
        crash_prob: float = 0.0,
        max_crashes: int = 3,
        seed: int = 0,
        **kw,
    ):
        kw.setdefault("max_restarts", 1_000_000)  # chaos runs should survive
        kw.setdefault("restart_backoff", 0.01)
        super().__init__(**kw)
        self.inner = inner
        self.crash_every = crash_every
        self.crash_prob = crash_prob
        # crashes are capped so finite sources (replay files) still complete:
        # each restart re-runs inner.produce() from scratch, so unbounded
        # deterministic crashing would livelock any file shorter than
        # crash_every × restarts. max_crashes<=0 means unbounded (only
        # sensible for unbounded sources).
        self.max_crashes = max_crashes
        self._rng = random.Random(seed)
        self._emitted = 0  # TWEETS emitted (a columnar block counts its rows)
        self._next_crash = crash_every
        self.crashes = 0

    def _may_crash(self) -> bool:
        return self.max_crashes <= 0 or self.crashes < self.max_crashes

    def produce(self) -> Iterator:
        from ..features.blocks import ParsedBlock

        for item in self.inner.produce():
            # crash_every counts TWEETS on every source kind: block sources
            # emit ParsedBlocks of ~thousands of rows each, so item-counting
            # would make --faultEvery thousands of times rarer than asked
            size = item.rows if isinstance(item, ParsedBlock) else 1
            if self.crash_prob and self._may_crash():
                # per-tweet probability, scaled to the item's row count
                p = 1.0 - (1.0 - self.crash_prob) ** size
                if self._rng.random() < p:
                    self.crashes += 1
                    raise InjectedFault(
                        f"injected probabilistic crash #{self.crashes}"
                    )
            # count first, then crash BEFORE the yield: the item that
            # crosses the threshold is lost in flight (like a dropped
            # socket), and a threshold crossed inside a stream's final
            # block still fires
            self._emitted += size
            if (
                self.crash_every
                and self._emitted >= self._next_crash
                and self._may_crash()
            ):
                self.crashes += 1
                self._next_crash = self._emitted + self.crash_every
                raise InjectedFault(
                    f"injected receiver crash #{self.crashes} "
                    f"after {self._emitted} tweets"
                )
            yield item

    def stop(self) -> None:
        # unblock the inner source first: our producer thread may be parked
        # in the inner's paced _stop.wait(), which only inner.stop() releases
        self.inner.stop()
        super().stop()
