"""Fault injection for stream sources (SURVEY.md §5.3: the reference has no
fault injection anywhere; receiver recovery was whatever Spark defaulted to).

``FaultInjectingSource`` wraps any Source and raises a simulated receiver
crash every ``crash_every`` tweets (deterministic) or with probability
``crash_prob`` per tweet (seeded) — exercising the supervision/restart/backoff
harness end-to-end in tests and chaos runs. Emitted tweets are passed through
unchanged; a crash loses the in-flight iterator exactly like a dropped
socket, so delivery gaps behave like the real failure mode.
"""

from __future__ import annotations

import random
from typing import Iterator

from ..features.featurizer import Status
from ..utils import get_logger
from .sources import Source

log = get_logger("streaming.faults")


class InjectedFault(ConnectionError):
    pass


class FaultInjectingSource(Source):
    name = "fault-injecting"

    def __init__(
        self,
        inner: Source,
        crash_every: int = 0,
        crash_prob: float = 0.0,
        max_crashes: int = 3,
        seed: int = 0,
        **kw,
    ):
        kw.setdefault("max_restarts", 1_000_000)  # chaos runs should survive
        kw.setdefault("restart_backoff", 0.01)
        super().__init__(**kw)
        self.inner = inner
        self.crash_every = crash_every
        self.crash_prob = crash_prob
        # crashes are capped so finite sources (replay files) still complete:
        # each restart re-runs inner.produce() from scratch, so unbounded
        # deterministic crashing would livelock any file shorter than
        # crash_every × restarts. max_crashes<=0 means unbounded (only
        # sensible for unbounded sources).
        self.max_crashes = max_crashes
        self._rng = random.Random(seed)
        self._emitted = 0
        self.crashes = 0

    def _may_crash(self) -> bool:
        return self.max_crashes <= 0 or self.crashes < self.max_crashes

    def produce(self) -> Iterator[Status]:
        for status in self.inner.produce():
            if (
                self.crash_every
                and self._emitted
                and self._emitted % self.crash_every == 0
                and self._may_crash()
            ):
                self._emitted += 1
                self.crashes += 1
                raise InjectedFault(
                    f"injected receiver crash #{self.crashes} "
                    f"after {self._emitted - 1} tweets"
                )
            if (
                self.crash_prob
                and self._may_crash()
                and self._rng.random() < self.crash_prob
            ):
                self.crashes += 1
                raise InjectedFault(f"injected probabilistic crash #{self.crashes}")
            self._emitted += 1
            yield status

    def stop(self) -> None:
        # unblock the inner source first: our producer thread may be parked
        # in the inner's paced _stop.wait(), which only inner.stop() releases
        self.inner.stop()
        super().stop()
