"""Fault injection for stream sources (SURVEY.md §5.3: the reference has no
fault injection anywhere; receiver recovery was whatever Spark defaulted to).

``FaultInjectingSource`` wraps any Source and raises a simulated receiver
crash every ``crash_every`` tweets (deterministic) or with probability
``crash_prob`` per tweet (seeded) — exercising the supervision/restart/backoff
harness end-to-end in tests and chaos runs. Emitted tweets are passed through
unchanged; a crash loses the in-flight iterator exactly like a dropped
socket, so delivery gaps behave like the real failure mode.
"""

from __future__ import annotations

import random
from typing import Iterator

from ..utils import get_logger
from .sources import Source

log = get_logger("streaming.faults")


class InjectedFault(ConnectionError):
    pass


class FaultInjectingSource(Source):
    name = "fault-injecting"

    def __init__(
        self,
        inner: Source,
        crash_every: int = 0,
        crash_prob: float = 0.0,
        max_crashes: int = 3,
        seed: int = 0,
        **kw,
    ):
        kw.setdefault("max_restarts", 1_000_000)  # chaos runs should survive
        kw.setdefault("restart_backoff", 0.01)
        super().__init__(**kw)
        self.inner = inner
        self.crash_every = crash_every
        self.crash_prob = crash_prob
        # crashes are capped so finite sources (replay files) still complete:
        # each restart re-runs inner.produce() from scratch, so unbounded
        # deterministic crashing would livelock any file shorter than
        # crash_every × restarts. max_crashes<=0 means unbounded (only
        # sensible for unbounded sources).
        self.max_crashes = max_crashes
        self._rng = random.Random(seed)
        self._emitted = 0  # TWEETS emitted (a columnar block counts its rows)
        self._next_crash = crash_every
        self.crashes = 0

    def _may_crash(self) -> bool:
        return self.max_crashes <= 0 or self.crashes < self.max_crashes

    def produce(self) -> Iterator:
        from ..features.blocks import ParsedBlock

        for item in self.inner.produce():
            # crash_every counts TWEETS on every source kind: block sources
            # emit ParsedBlocks of ~thousands of rows each, so item-counting
            # would make --faultEvery thousands of times rarer than asked
            size = item.rows if isinstance(item, ParsedBlock) else 1
            if self.crash_prob and self._may_crash():
                # per-tweet probability, scaled to the item's row count
                p = 1.0 - (1.0 - self.crash_prob) ** size
                if self._rng.random() < p:
                    self.crashes += 1
                    raise InjectedFault(
                        f"injected probabilistic crash #{self.crashes}"
                    )
            # count first, then crash BEFORE the yield: the item that
            # crosses the threshold is lost in flight (like a dropped
            # socket), and a threshold crossed inside a stream's final
            # block still fires
            self._emitted += size
            if (
                self.crash_every
                and self._emitted >= self._next_crash
                and self._may_crash()
            ):
                self.crashes += 1
                self._next_crash = self._emitted + self.crash_every
                raise InjectedFault(
                    f"injected receiver crash #{self.crashes} "
                    f"after {self._emitted} tweets"
                )
            yield item

    def stop(self) -> None:
        # unblock the inner source first: our producer thread may be parked
        # in the inner's paced _stop.wait(), which only inner.stop() releases
        self.inner.stop()
        super().stop()
