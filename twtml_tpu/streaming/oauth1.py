"""OAuth 1.0a request signing (HMAC-SHA1), stdlib only.

The reference delegates OAuth to Twitter4j: `ConfArguments` routes the four
credentials into ``twitter4j.oauth.*`` system properties
(ConfArguments.scala:58-76) and ``TwitterUtils.createStream``
(LinearRegression.scala:44) signs every streaming request with them. This
module is the native equivalent: RFC 5849 parameter normalization, signature
base string, HMAC-SHA1 signature, and ``Authorization: OAuth ...`` header —
pinned by the published RFC 5849 §1.2 and Twitter developer-docs test
vectors (tests/test_twitter_live.py).

Nonce/timestamp are injectable so signatures are deterministic under test.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import secrets
import time
from urllib.parse import parse_qsl, quote, urlsplit

__all__ = ["percent_encode", "signature_base_string", "sign", "authorization_header"]


def percent_encode(value: str) -> str:
    """RFC 5849 §3.6 encoding: unreserved chars (RFC 3986 §2.3) stay, all
    else becomes uppercase %XX over the UTF-8 bytes. ``quote`` with
    ``safe=""`` implements exactly this (it never encodes ``-._~``)."""
    return quote(value.encode("utf-8"), safe="")


def _normalized_params(params: list[tuple[str, str]]) -> str:
    """RFC 5849 §3.4.1.3.2: encode each key and value, sort by encoded key
    then encoded value, join with ``&``/``=``."""
    encoded = sorted(
        (percent_encode(k), percent_encode(v)) for k, v in params
    )
    return "&".join(f"{k}={v}" for k, v in encoded)


def _base_uri(url: str) -> str:
    """RFC 5849 §3.4.1.2: lowercase scheme/host, strip default ports, drop
    query and fragment."""
    parts = urlsplit(url)
    scheme = parts.scheme.lower()
    host = (parts.hostname or "").lower()
    port = parts.port
    if port and not (
        (scheme == "http" and port == 80) or (scheme == "https" and port == 443)
    ):
        host = f"{host}:{port}"
    return f"{scheme}://{host}{parts.path or '/'}"


def signature_base_string(
    method: str, url: str, params: list[tuple[str, str]]
) -> str:
    """RFC 5849 §3.4.1.1. ``params`` must already contain the oauth_*
    protocol params and every query/form param (NOT oauth_signature)."""
    return "&".join((
        method.upper(),
        percent_encode(_base_uri(url)),
        percent_encode(_normalized_params(params)),
    ))


def sign(
    method: str,
    url: str,
    params: list[tuple[str, str]],
    consumer_secret: str,
    token_secret: str = "",
) -> str:
    """HMAC-SHA1 signature (RFC 5849 §3.4.2), base64 text."""
    key = f"{percent_encode(consumer_secret)}&{percent_encode(token_secret)}"
    digest = hmac.new(
        key.encode("ascii"),
        signature_base_string(method, url, params).encode("ascii"),
        hashlib.sha1,
    ).digest()
    return base64.b64encode(digest).decode("ascii")


def authorization_header(
    method: str,
    url: str,
    consumer_key: str,
    consumer_secret: str,
    token: str,
    token_secret: str,
    extra_params: list[tuple[str, str]] | None = None,
    nonce: str | None = None,
    timestamp: int | None = None,
) -> str:
    """Build the ``OAuth ...`` Authorization header value for a request.

    ``extra_params`` = query-string and form-body params that participate in
    the signature (RFC 5849 §3.4.1.3.1) but are NOT emitted in the header.
    The query component of ``url`` is folded in automatically.
    """
    oauth_params = [
        ("oauth_consumer_key", consumer_key),
        ("oauth_nonce", nonce if nonce is not None else secrets.token_hex(16)),
        ("oauth_signature_method", "HMAC-SHA1"),
        ("oauth_timestamp", str(timestamp if timestamp is not None else int(time.time()))),
        ("oauth_token", token),
        ("oauth_version", "1.0"),
    ]
    signed: list[tuple[str, str]] = list(oauth_params)
    query = urlsplit(url).query
    if query:
        # query strings arrive form-urlencoded; decode to raw values (incl.
        # '+' as space, RFC 5849 §3.4.1.3.1 mandates W3C form decoding) so
        # the signature re-encodes them exactly once
        signed.extend(parse_qsl(query, keep_blank_values=True))
    if extra_params:
        signed.extend(extra_params)
    signature = sign(method, url, signed, consumer_secret, token_secret)
    header_params = oauth_params + [("oauth_signature", signature)]
    return "OAuth " + ", ".join(
        f'{percent_encode(k)}="{percent_encode(v)}"' for k, v in header_params
    )
