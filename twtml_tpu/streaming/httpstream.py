"""Minimal streaming HTTP/1.1 client for long-lived delimited-JSON streams.

Twitter's v1.1 streaming endpoints speak plain HTTP/1.1 with
``Transfer-Encoding: chunked`` and one JSON document per ``\\r\\n``-delimited
line, with blank keep-alive lines every ~30 s. The reference gets this whole
layer from Twitter4j (an external dependency); this is the native, stdlib
implementation: raw socket (+TLS for https), request writing, status/header
parse, chunked-body decoding, and line reassembly across chunk boundaries.

``urllib`` is unsuitable here: it buffers, follows redirects, and cannot
surface the per-chunk flow a streaming consumer needs mid-response; the
protocol loop below is ~100 lines and fully testable against a local server
(tests/test_twitter_live.py).
"""

from __future__ import annotations

import socket
import ssl
from typing import Iterator
from urllib.parse import urlsplit

__all__ = ["StreamHTTPError", "RateLimitedError", "open_stream"]


class StreamHTTPError(ConnectionError):
    """Non-200 response on a streaming endpoint."""

    def __init__(self, status: int, reason: str = ""):
        super().__init__(f"HTTP {status} {reason}".strip())
        self.status = status
        self.reason = reason


class RateLimitedError(StreamHTTPError):
    """HTTP 420 (Twitter's 'Enhance Your Calm') / 429: the caller must back
    off exponentially starting at a full minute (Twitter streaming rules)."""


def _read_line(sock: socket.socket, buf: bytearray) -> bytes:
    """Read one CRLF-terminated line from the socket (for status/headers and
    chunk-size lines). ``buf`` carries overflow bytes between calls."""
    while True:
        nl = buf.find(b"\n")
        if nl >= 0:
            line = bytes(buf[:nl])
            del buf[: nl + 1]
            return line.rstrip(b"\r")
        data = sock.recv(65536)
        if not data:
            raise ConnectionError("connection closed during HTTP header read")
        buf.extend(data)


def _read_exact(sock: socket.socket, buf: bytearray, n: int) -> bytes:
    while len(buf) < n:
        data = sock.recv(65536)
        if not data:
            raise ConnectionError("connection closed mid-chunk")
        buf.extend(data)
    out = bytes(buf[:n])
    del buf[:n]
    return out


def _body_chunks(
    sock: socket.socket, buf: bytearray, headers: dict[str, str]
) -> Iterator[bytes]:
    """Yield raw body byte chunks per the response framing."""
    encoding = headers.get("transfer-encoding", "").lower()
    if "chunked" in encoding:
        while True:
            size_line = _read_line(sock, buf)
            if not size_line:
                continue  # tolerate stray blank between chunks
            size = int(size_line.split(b";")[0], 16)  # ignore chunk extensions
            if size == 0:
                # trailer section until blank line, then done
                while _read_line(sock, buf):
                    pass
                return
            yield _read_exact(sock, buf, size)
            _read_line(sock, buf)  # CRLF after chunk data
    elif "content-length" in headers:
        remaining = int(headers["content-length"])
        if buf:
            take = min(len(buf), remaining)
            yield _read_exact(sock, buf, take)
            remaining -= take
        while remaining > 0:
            data = sock.recv(min(65536, remaining))
            if not data:
                return
            remaining -= len(data)
            yield data
    else:
        # read-until-close framing
        if buf:
            yield bytes(buf)
            buf.clear()
        while True:
            data = sock.recv(65536)
            if not data:
                return
            yield data


def open_stream(
    url: str,
    headers: dict[str, str] | None = None,
    method: str = "GET",
    body: bytes | None = None,
    timeout: float = 90.0,
    ssl_context: ssl.SSLContext | None = None,
) -> Iterator[str]:
    """Open ``url`` and yield decoded text lines (without terminators) as
    they arrive. Blank keep-alive lines ARE yielded — the consumer decides.

    Raises ``RateLimitedError`` on 420/429, ``StreamHTTPError`` on any other
    non-200, plain ``ConnectionError``/``OSError``/``TimeoutError`` on
    transport failures — the distinction drives the reconnect/backoff policy
    (twitter.py).
    """
    parts = urlsplit(url)
    host = parts.hostname or "localhost"
    port = parts.port or (443 if parts.scheme == "https" else 80)
    target = parts.path or "/"
    if parts.query:
        target += "?" + parts.query

    sock = socket.create_connection((host, port), timeout=timeout)
    try:
        if parts.scheme == "https":
            ctx = ssl_context or ssl.create_default_context()
            sock = ctx.wrap_socket(sock, server_hostname=host)

        req_headers = {
            "Host": parts.netloc,
            "User-Agent": "twtml-tpu/0.2",
            "Accept": "*/*",
            "Connection": "close",
        }
        if body is not None:
            req_headers["Content-Length"] = str(len(body))
            req_headers.setdefault(
                "Content-Type", "application/x-www-form-urlencoded"
            )
        if headers:
            req_headers.update(headers)
        request = f"{method} {target} HTTP/1.1\r\n" + "".join(
            f"{k}: {v}\r\n" for k, v in req_headers.items()
        ) + "\r\n"
        sock.sendall(request.encode("ascii") + (body or b""))

        buf = bytearray()
        status_line = _read_line(sock, buf)
        try:
            status = int(status_line.split()[1])
        except (IndexError, ValueError):
            raise ConnectionError(f"malformed status line: {status_line!r}")
        reason = b" ".join(status_line.split()[2:]).decode("latin-1")
        resp_headers: dict[str, str] = {}
        while True:
            line = _read_line(sock, buf)
            if not line:
                break
            key, _, value = line.decode("latin-1").partition(":")
            resp_headers[key.strip().lower()] = value.strip()

        if status in (420, 429):
            raise RateLimitedError(status, reason)
        if status != 200:
            raise StreamHTTPError(status, reason)

        # reassemble text lines across chunk boundaries
        pending = b""
        for chunk in _body_chunks(sock, buf, resp_headers):
            pending += chunk
            while True:
                nl = pending.find(b"\n")
                if nl < 0:
                    break
                line_bytes = pending[:nl].rstrip(b"\r")
                pending = pending[nl + 1 :]
                yield line_bytes.decode("utf-8", errors="replace")
        if pending.strip():
            yield pending.decode("utf-8", errors="replace")
    finally:
        try:
            sock.close()
        except OSError:
            pass
