from .context import StreamingContext, FeatureStream
from .sources import ReplayFileSource, SyntheticSource, QueueSource, Source

__all__ = [
    "StreamingContext",
    "FeatureStream",
    "ReplayFileSource",
    "SyntheticSource",
    "QueueSource",
    "Source",
]
