"""Micro-batch streaming runtime — the DStream/StreamingContext equivalent.

The reference slices a live stream into RDDs every ``seconds`` and runs two
registered outputs per batch: the stats ``foreachRDD`` and ``model.trainOn``
(LinearRegression.scala:40-47,53,86). Here a ``StreamingContext`` owns one
source feeding a thread-safe queue; a scheduler thread wakes every
``batch_interval`` seconds, drains the queue, filters + featurizes + pads the
tweets into one fixed-shape ``FeatureBatch``, and invokes every registered
output in registration order (so stats-before-train ordering is preserved
when callers register them separately; the fused model step keeps it
internally regardless).

Differences by design:
- featurization happens once per batch on the host (numpy), not as per-element
  closures shipped to executors — the device program consumes one padded batch;
- ``run_to_completion`` offers a deterministic clock-free mode (replay/bench):
  process fixed-size batches back-to-back until the source is exhausted,
  which wall-clock DStreams cannot do;
- batch row/token counts are padded to power-of-two buckets (features/batch.py)
  so XLA compiles a handful of programs, not one per batch shape.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from ..features.batch import FeatureBatch, UnitBatch
from ..features.featurizer import Featurizer, Status
from ..telemetry import metrics as _metrics
from ..telemetry import trace as _trace
from ..utils import get_logger
from .sources import Source

log = get_logger("streaming.context")

BatchFn = Callable[[FeatureBatch, float], None]

# lockstep peer watchdog: how long the per-tick cadence allgather may make
# no progress before this host concludes a peer is gone (hard kill /
# network partition) and aborts loudly instead of hanging in the
# collective forever. Generous default: ticks legitimately skew by a slow
# host's featurize/parse + a ~30s first-batch compile. 0 disables.
LOCKSTEP_TIMEOUT_ENV = "TWTML_LOCKSTEP_TIMEOUT_S"
LOCKSTEP_TIMEOUT_DEFAULT_S = 120.0


def _watched_allgather(arr, timeout_s: float):
    """Run one cadence allgather under a progress watchdog: returns the
    gathered array, or None when the watchdog fired. The collective runs
    on a daemon thread (never a ThreadPoolExecutor — concurrent.futures
    joins its workers at interpreter exit, so a wedged collective would
    hang shutdown; a daemon thread dies with the process). The scheduler
    blocks on the result before dispatching, so per-host collective issue
    order stays total — only the executing thread changes. Thread spawn is
    ~50µs against a per-batch tick; exceptions from the collective (a dead
    peer often surfaces as a transport error rather than a hang) propagate
    to the caller."""
    from jax.experimental import multihost_utils

    if timeout_s <= 0:
        return multihost_utils.process_allgather(arr)
    box: dict = {}
    done = threading.Event()

    def run() -> None:
        try:
            box["out"] = multihost_utils.process_allgather(arr)
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            box["exc"] = exc
        done.set()

    threading.Thread(
        target=run, daemon=True, name="twtml-lockstep-allgather"
    ).start()
    if not done.wait(timeout_s):
        return None
    if "exc" in box:
        raise box["exc"]
    return box["out"]


class _RowCountQueue(queue.Queue):
    """queue.Queue that also tracks the queued ROW count (a ParsedBlock item
    counts its rows, a Status counts 1) — maintained inside ``_put``/``_get``,
    which run under the queue's own mutex, so the per-tweet intake path pays
    no extra lock. The back-to-back fill gate compares ``rows_queued`` (not
    item count) to the row bucket; reading the int without the mutex is fine
    for a gate that only ever errs toward one more 2 ms wait."""

    def _init(self, maxsize: int) -> None:
        super()._init(maxsize)
        self.rows_queued = 0

    def _put(self, item) -> None:
        super()._put(item)
        self.rows_queued += getattr(item, "rows", 1)

    def _get(self):
        item = super()._get()
        self.rows_queued -= getattr(item, "rows", 1)
        return item

    def putback(self, item) -> None:
        """Return an item to the FRONT of the queue (the drain splitter's
        remainder — it must come out first so row order is preserved)."""
        with self.mutex:
            self.queue.appendleft(item)
            self.rows_queued += getattr(item, "rows", 1)
            self.not_empty.notify()


class RawStream:
    """A stream of raw Status lists — for apps with their own featurization
    (the k-means entry featurizes to a dense pair, KMeans.scala:19-33).
    Outputs fire per micro-batch in registration order (reference: foreachRDD
    at LinearRegression.scala:53, trainOn at :86).

    ``row_bucket`` (optional) caps the scheduler's back-to-back drains —
    required by multi-host lockstep, where the app's per-batch handler owns
    fixed-shape padding and every host must dispatch the same program."""

    def __init__(self, row_bucket: int = 0):
        self._outputs: list[Callable] = []
        self.row_bucket = row_bucket

    def foreach_batch(self, fn) -> "RawStream":
        self._outputs.append(fn)
        return self

    def _process(self, statuses: list[Status], batch_time: float):
        for fn in self._outputs:
            fn(statuses, batch_time)


class FeatureStream(RawStream):
    """A RawStream whose outputs receive padded FeatureBatches instead of
    Status lists (DStream.map(featurize) analog)."""

    def __init__(
        self,
        featurizer: Featurizer,
        row_bucket: int = 0,
        token_bucket: int = 0,
        row_multiple: int = 1,
        device_hash: bool = False,
        ragged: bool = False,
    ):
        super().__init__()
        self.featurizer = featurizer
        self.row_bucket = row_bucket
        self.token_bucket = token_bucket
        self.row_multiple = row_multiple
        self.device_hash = device_hash
        self.ragged = ragged
        if ragged and not device_hash:
            raise ValueError(
                "the ragged wire IS a device-hash wire format: "
                "--wire ragged requires --hashOn device"
            )
        self._bucket_overflow_warned = False
        # the pinned row shape includes the mesh-divisibility round-up,
        # matching every batch the featurizer emits; fixed at construction
        from ..features.batch import pad_row_count

        self._pinned_rows = (
            pad_row_count(0, row_bucket, row_multiple) if row_bucket > 0 else 0
        )

    @staticmethod
    def batch_shape(batch) -> "tuple[int, int]":
        """(rows, tokens-or-units) of a featurized batch — the two axes the
        pinned buckets govern."""
        from ..features.batch import RaggedUnitBatch

        if isinstance(batch, RaggedUnitBatch):
            # the ragged wire's row length is static aux (the device-side
            # re-pad width) — the same axis token_bucket pins
            return batch.mask.shape[0], batch.row_len
        tokens = (
            batch.units.shape[1]
            if isinstance(batch, UnitBatch)
            else batch.token_idx.shape[1]
        )
        return batch.mask.shape[0], tokens

    def bucket_overflow(self, batch) -> bool:
        """Whether a featurized batch outgrew the pinned buckets (the
        featurizer grows rather than truncates)."""
        rows, tokens = self.batch_shape(batch)
        return (0 < self._pinned_rows < rows) or (
            0 < self.token_bucket < tokens
        )

    def _check_buckets(self, batch) -> None:
        """Warn (once) when a batch overflowed the pinned buckets: the
        featurizer grows the bucket rather than truncate, so the step
        recompiles for the bigger shape — silently defeating a pre-stream
        compile warmup and multiplying program count."""
        if self._bucket_overflow_warned or not self.bucket_overflow(batch):
            return
        self._bucket_overflow_warned = True
        rows, tokens = self.batch_shape(batch)
        log.warning(
            "batch shape (%d, %d) overflowed the pinned buckets "
            "(%d, %d): the step recompiles for the larger shape — "
            "raise --batchBucket/--tokenBucket to keep one program",
            rows, tokens, self.row_bucket, self.token_bucket,
        )

    def _featurize(self, statuses: list) -> "FeatureBatch | UnitBatch":
        """The ONE featurize dispatch for this stream's configuration —
        shared by the per-batch path and ``featurize_empty`` so a compile
        warmup always warms exactly the program the stream will run.
        Instrumented as the ``featurize`` stage (host featurize incl. wire
        build); the span and the ``pipeline.*``/``wire.bytes`` metrics are
        side-channel only — the batch itself is untouched."""
        tr = _trace.get()
        if not tr.enabled:
            return self._featurize_impl(statuses)
        with tr.span("featurize", items=len(statuses)) as sp:
            batch = self._featurize_impl(statuses)
            from ..features.batch import wire_nbytes

            sp.add(
                rows=int(batch.mask.shape[0]),
                valid=batch.num_valid,
                wire_bytes=wire_nbytes(batch),
            )
        return batch

    @staticmethod
    def _record_metrics(batch) -> None:
        from ..features.batch import wire_composition, wire_nbytes

        reg = _metrics.get_registry()
        reg.counter("pipeline.batches").inc()
        reg.counter("pipeline.tweets").inc(batch.num_valid)
        reg.counter("wire.bytes").inc(wire_nbytes(batch))
        # per-batch wire composition (Lean wire v2): the units/offsets/
        # sideband split makes the offset-narrowing visible in /api/metrics
        # and trace reports without a bench run
        comp = wire_composition(batch)
        reg.gauge("wire.units_bytes").set(comp["units"])
        reg.gauge("wire.offsets_bytes").set(comp["offsets"])
        reg.gauge("wire.sideband_bytes").set(comp["sideband"])

    def _featurize_impl(self, statuses: list) -> "FeatureBatch | UnitBatch":
        from ..features.blocks import ParsedBlock, merge_blocks

        if statuses and isinstance(statuses[0], ParsedBlock):
            # native block ingest: items are pre-filtered columnar blocks
            # (sources.BlockReplayFileSource); featurize without per-tweet
            # Python objects
            return self.featurizer.featurize_parsed_block(
                merge_blocks(statuses), row_bucket=self.row_bucket,
                unit_bucket=self.token_bucket, row_multiple=self.row_multiple,
                ragged=self.ragged,
            )
        if self.device_hash:
            if self.ragged:
                # concatenated units + offsets: no per-row pad bytes on the
                # upload-bound wire (features/batch.RaggedUnitBatch —
                # measured +14% paired vs the padded wire, BENCHMARKS.md)
                return self.featurizer.featurize_batch_ragged(
                    statuses, row_bucket=self.row_bucket,
                    unit_bucket=self.token_bucket,
                    row_multiple=self.row_multiple,
                )
            # ship raw code units; the learner hashes bigrams on device
            # (ops/text_hash.py) — bit-identical features, ~2x host headroom
            return self.featurizer.featurize_batch_units(
                statuses, row_bucket=self.row_bucket,
                unit_bucket=self.token_bucket, row_multiple=self.row_multiple,
            )
        return self.featurizer.featurize_batch(
            statuses, row_bucket=self.row_bucket,
            token_bucket=self.token_bucket,
            row_multiple=self.row_multiple,
        )

    def featurize_empty(self) -> "FeatureBatch | UnitBatch":
        """An all-padding batch of this stream's exact configured shape
        (meaningful when both buckets are pinned) — for pre-stream compile
        warmup."""
        return self._featurize([])

    def _process(
        self, statuses: list[Status], batch_time: float
    ) -> "FeatureBatch | UnitBatch":
        batch = self._featurize(statuses)
        self._check_buckets(batch)
        self._record_metrics(batch)
        for fn in self._outputs:
            fn(batch, batch_time)
        return batch


class StreamingContext:
    def __init__(self, batch_interval: float = 5.0):
        self.batch_interval = batch_interval
        self._queue: _RowCountQueue = _RowCountQueue()
        self._source: Source | None = None
        self._stream: RawStream | None = None
        self._scheduler: threading.Thread | None = None
        self._stop = threading.Event()
        self._terminated = threading.Event()
        self.batches_processed = 0
        # set when a lockstep run aborted (this host or a peer): the app
        # must surface a failure instead of reporting success
        self.failed = False

    def source_stream(
        self,
        source: Source,
        featurizer: Featurizer,
        row_bucket: int = 0,
        token_bucket: int = 0,
        row_multiple: int = 1,
        device_hash: bool = False,
        ragged: bool = False,
    ) -> FeatureStream:
        """Attach the (single) source and build its feature stream —
        equivalent of TwitterUtils.createStream().filter().map().cache()
        (LinearRegression.scala:44-47)."""
        if self._source is not None:
            raise ValueError("StreamingContext supports one source stream")
        self._source = source
        self._stream = FeatureStream(
            featurizer, row_bucket, token_bucket, row_multiple, device_hash,
            ragged,
        )
        return self._stream

    def raw_stream(self, source: Source, row_bucket: int = 0) -> RawStream:
        """Attach the source with no featurization — outputs receive the raw
        Status list per micro-batch. ``row_bucket`` caps back-to-back
        drains (required in multi-host lockstep)."""
        if self._source is not None:
            raise ValueError("StreamingContext supports one source stream")
        self._source = source
        self._stream = RawStream(row_bucket)
        return self._stream

    def _drain(self, limit: int = 0) -> list[Status]:
        """Drain queued items; ``limit`` caps the drained ROW count (a
        ParsedBlock item counts its rows, a Status counts 1). A ParsedBlock
        that would overshoot the cap is SPLIT at the cap (r5) and its
        remainder put back at the queue front — capped drains are therefore
        exactly ``limit`` rows while data lasts, which multi-host lockstep
        requires (an overshooting block would grow this host's program
        shape away from its peers') and which makes single-host
        back-to-back block batches deterministic bucket-sized too.

        Instrumented as the ``source_read`` stage when tracing is on."""
        tr = _trace.get()
        if not tr.enabled:
            return self._drain_impl(limit)
        with tr.span("source_read") as sp:
            out = self._drain_impl(limit)
            sp.add(items=len(out))
        return out

    def _drain_impl(self, limit: int = 0) -> list[Status]:
        out: list[Status] = []
        rows = 0
        while not limit or rows < limit:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            take = getattr(item, "rows", None)
            if take is not None and limit and rows + take > limit:
                from ..features.blocks import slice_block

                cut = limit - rows
                out.append(slice_block(item, 0, cut))
                self._queue.putback(slice_block(item, cut, take))
                rows = limit
                break
            out.append(item)
            rows += take if take is not None else 1
        return out

    def _run_batch(self, statuses: list[Status], batch_time: float) -> None:
        try:
            self._stream._process(statuses, batch_time)
            self.batches_processed += 1
        except Exception:
            log.exception("batch at t=%.3f failed", batch_time)

    def _scheduler_loop(self) -> None:
        # back-to-back mode (--seconds 0) with a pinned row bucket: cap each
        # batch at the bucket so a fast source yields deterministic
        # fixed-size batches (the run_to_completion semantic) instead of one
        # giant drain — bounded memory, one compiled shape, and the unit
        # --superBatch groups. Wall-clock mode drains the full interval.
        limit = (
            getattr(self._stream, "row_bucket", 0)
            if self.batch_interval == 0
            else 0
        )
        next_tick = time.monotonic() + self.batch_interval
        while not self._stop.is_set():
            delay = next_tick - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                break
            next_tick += self.batch_interval
            if limit and self._queue.rows_queued < limit and not self._source.exhausted:
                # fill the bucket before processing: batch boundaries stay
                # deterministic (full buckets + one tail) instead of racing
                # the producer — the run_to_completion contract
                self._stop.wait(0.002)
                continue
            self._run_batch(self._drain(limit), time.time())
            if self._source.exhausted and self._queue.empty():
                break
        self._terminated.set()

    def request_stop(self) -> None:
        """Ask the scheduler to stop after the current batch — the public
        early-exit hook apps use for max-batches caps."""
        self._stop.set()

    def request_abort(self) -> None:
        """Loud-failure hook for the runtime guards (fetch watchdog,
        lockstep peer watchdog): mark the run failed and stop after the
        current batch, so the app's shutdown path still flushes its final
        checkpoint and the process exits non-zero."""
        self.failed = True
        self.request_stop()

    @property
    def stop_requested(self) -> bool:
        """Whether a stop has been requested (read by the concurrent
        fetch pipeline to honor max-batches caps exactly, apps/common.py
        FetchPipeline)."""
        return self._stop.is_set()

    def _run_batch_aligned(self, statuses: list[Status], batch_time: float) -> None:
        """Lockstep-mode batch: host-local failures must never change this
        host's COLLECTIVE program sequence (the other hosts' psums would
        block forever on the missing program). A featurize failure — purely
        host-side, nothing dispatched yet — substitutes the all-padding
        batch (rows lost, loudly). A shape overflow of the pinned buckets
        would dispatch a DIFFERENTLY-SHAPED program than the peers', so it
        is a hard error. Output (dispatch/handler) exceptions propagate to
        the loop: after a possible partial dispatch alignment is unknowable,
        and failing fast beats a distributed hang."""
        stream = self._stream
        if not isinstance(stream, FeatureStream):
            # raw lockstep (the k-means entry): the app's per-batch handler
            # owns fixed-shape padding and global assembly, so there is no
            # featurize stage to guard here; handler failures propagate to
            # the loop's abort path (alignment unknowable after a possible
            # partial dispatch)
            stream._process(statuses, batch_time)
            self.batches_processed += 1
            return
        try:
            batch = stream._featurize(statuses)
        except Exception:
            log.exception(
                "featurize failed in lockstep mode; substituting an "
                "all-padding batch to keep the group's collective sequence "
                "aligned (these rows are lost)"
            )
            batch = stream._featurize([])
        if stream.bucket_overflow(batch):
            # single-host runs grow the bucket and recompile (benign); here
            # a grown shape means THIS host dispatches a differently-shaped
            # collective program than its peers → distributed hang. The
            # overflow is data-dependent (one long tweet), so it must not
            # kill the run either: drop the over-long rows, keep the rest.
            # conservative probe: the featurizer owns the canonical text
            # encoding (host-hash wire carries units-1 bigram tokens, so
            # <= token_bucket under-admits by at most one unit there)
            kept = [
                s for s in statuses
                if stream.featurizer.unit_len(s) <= stream.token_bucket
            ]
            rows, tokens = stream.batch_shape(batch)
            log.error(
                "batch shape (%d, %d) overflowed the pinned buckets "
                "(%d, %d) in a multi-host run; dropping %d over-long row(s) "
                "to keep the group's program shapes aligned — raise "
                "--batchBucket/--tokenBucket", rows, tokens,
                stream.row_bucket, stream.token_bucket,
                len(statuses) - len(kept),
            )
            batch = stream._featurize(kept)
            if stream.bucket_overflow(batch):
                # probe missed (e.g. a case fold changed the length):
                # last resort keeps alignment at the cost of the batch
                log.error("overflow persists; dropping the whole batch")
                batch = stream._featurize([])
        stream._record_metrics(batch)
        for fn in stream._outputs:
            fn(batch, batch_time)
        self.batches_processed += 1

    def _lockstep_loop(self) -> None:
        """Multi-host batch scheduler: every process must run the SAME
        sequence of collective programs, so batch cadence and termination
        are agreed per tick with one tiny all-process allgather of
        (has_rows, more_coming, abort). A host whose intake shard ran dry
        keeps dispatching all-padding batches (zero-sample steps are weight
        no-ops) until EVERY host is exhausted — otherwise the other hosts'
        psums would wait forever on its missing program.

        A batch failure AFTER featurize leaves this host's collective
        alignment unknowable, so it stops dispatching — but it keeps
        ticking the allgather with abort=1 until every peer has seen it
        (peers then stop too instead of stalling in their next collective),
        and the run is marked ``failed`` so the app can exit non-zero
        rather than report success.

        A hard-killed peer can never tick its abort flag, so the allgather
        itself runs under a progress watchdog (``_watched_allgather``,
        ``TWTML_LOCKSTEP_TIMEOUT_S``): when it fires — or the collective
        raises a transport error, the other way a dead peer surfaces —
        this host aborts LOUDLY (``failed=True`` → the app exits non-zero
        after its shutdown path flushes a final checkpoint) instead of
        hanging in the collective forever. Collectives INSIDE a dispatched
        step are covered separately: their results surface through the
        pooled stats fetch, whose own watchdog (apps/common.FetchWatchdog)
        aborts the same way.

        Drains are capped at the row bucket in BOTH modes (wall-clock rows
        beyond the bucket stay queued for the next tick): an uncapped drain
        could exceed --batchBucket and grow this host's program shape away
        from its peers'."""
        import os

        import numpy as np

        watch_s = float(
            os.environ.get(LOCKSTEP_TIMEOUT_ENV, "")
            or LOCKSTEP_TIMEOUT_DEFAULT_S
        )
        limit = getattr(self._stream, "row_bucket", 0)
        next_tick = time.monotonic() + self.batch_interval
        aborting = False
        while not self._stop.is_set():
            if self.batch_interval > 0 and not aborting:
                delay = next_tick - time.monotonic()
                if delay > 0 and self._stop.wait(delay):
                    break
                next_tick += self.batch_interval
            elif limit and not aborting:
                # back-to-back fill gate, as in _scheduler_loop
                while (
                    self._queue.rows_queued < limit
                    and not self._source.exhausted
                    and not self._stop.is_set()
                ):
                    self._stop.wait(0.002)
            local = self._drain(limit)
            rows = sum(getattr(s, "rows", 1) for s in local)
            more = (not self._source.exhausted) or self._queue.rows_queued > 0
            try:
                flags = _watched_allgather(
                    np.array(
                        [rows > 0 and not aborting, more and not aborting,
                         aborting],
                        dtype=np.int32,
                    ),
                    watch_s,
                )
            except Exception:
                log.critical(
                    "lockstep cadence allgather FAILED — a peer likely "
                    "died mid-run; aborting this host loudly (progress up "
                    "to the last checkpoint boundary is saved)",
                    exc_info=True,
                )
                _metrics.get_registry().counter(
                    "lockstep.watchdog_aborts"
                ).inc()
                self.failed = True
                break
            if flags is None:
                log.critical(
                    "lockstep peer watchdog: the cadence allgather made no "
                    "progress in %.0fs — a peer is gone (hard kill or "
                    "network partition). Aborting this host loudly instead "
                    "of hanging in the collective; tune with %s (0 "
                    "disables).",
                    watch_s, LOCKSTEP_TIMEOUT_ENV,
                )
                _metrics.get_registry().counter(
                    "lockstep.watchdog_aborts"
                ).inc()
                _trace.get().instant("lockstep_watchdog", timeout_s=watch_s)
                self.failed = True
                break
            if flags[:, 2].any():
                # this host (or a peer) aborted: everyone has now agreed on
                # it in the same tick, so everyone can stop dispatching
                if not aborting:
                    log.critical("a peer host aborted the lockstep run")
                self.failed = True
                break
            if flags[:, 0].any():
                # somebody has rows: EVERY host dispatches (local may be
                # empty — it pads to the pinned bucket)
                try:
                    self._run_batch_aligned(local, time.time())
                except Exception:
                    log.critical(
                        "lockstep batch failed after featurize; this host's "
                        "collective alignment is unknowable — aborting the "
                        "group (fail fast beats a distributed hang)",
                        exc_info=True,
                    )
                    aborting = True  # next tick broadcasts abort to peers
            if not aborting and not (flags[:, 0].any() or flags[:, 1].any()):
                break
        self._terminated.set()

    # -- lifecycle (ssc.start/awaitTermination, LinearRegression.scala:89-91) --
    def start(self, lockstep: bool = False) -> None:
        """``lockstep=True`` (multi-host runs) replaces the local scheduler
        with the collectively-agreed one (``_lockstep_loop``)."""
        if self._stream is None:
            raise ValueError("no stream registered")
        self._stop.clear()
        self._terminated.clear()
        self.failed = False
        self._source.start(self._queue.put)
        self._scheduler = threading.Thread(
            target=self._lockstep_loop if lockstep else self._scheduler_loop,
            name="twtml-batch-scheduler", daemon=True,
        )
        self._scheduler.start()

    def await_termination(self, timeout: float | None = None) -> bool:
        return self._terminated.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        if self._source is not None:
            self._source.stop()
        if self._scheduler is not None:
            self._scheduler.join(timeout=10)
        self._terminated.set()

    # -- deterministic replay mode (no wall clock) ---------------------------
    def run_to_completion(self, max_batch_size: int = 1024) -> int:
        """Drive the source synchronously: fill batches of up to
        ``max_batch_size`` tweets and process back-to-back. Returns number of
        batches run. Used by benchmarks and parity tests where the 5s cadence
        would only add idle time."""
        if self._stream is None:
            raise ValueError("no stream registered")
        self._source.start(self._queue.put)
        n0 = self.batches_processed
        pending: list[Status] = []
        while not self._stop.is_set():
            try:
                pending.append(self._queue.get(timeout=0.05))
                if len(pending) >= max_batch_size:
                    self._run_batch(pending, time.time())
                    pending = []
            except queue.Empty:
                if self._source.exhausted:
                    # re-drain: the source may have emitted between our
                    # timeout and the exhausted flag being set
                    pending.extend(self._drain())
                    break
        if pending and not self._stop.is_set():
            self._run_batch(pending, time.time())
        self._terminated.set()
        return self.batches_processed - n0
