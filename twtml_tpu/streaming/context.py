"""Micro-batch streaming runtime — the DStream/StreamingContext equivalent.

The reference slices a live stream into RDDs every ``seconds`` and runs two
registered outputs per batch: the stats ``foreachRDD`` and ``model.trainOn``
(LinearRegression.scala:40-47,53,86). Here a ``StreamingContext`` owns one
source feeding a thread-safe queue; a scheduler thread wakes every
``batch_interval`` seconds, drains the queue, filters + featurizes + pads the
tweets into one fixed-shape ``FeatureBatch``, and invokes every registered
output in registration order (so stats-before-train ordering is preserved
when callers register them separately; the fused model step keeps it
internally regardless).

Differences by design:
- featurization happens once per batch on the host (numpy), not as per-element
  closures shipped to executors — the device program consumes one padded batch;
- ``run_to_completion`` offers a deterministic clock-free mode (replay/bench):
  process fixed-size batches back-to-back until the source is exhausted,
  which wall-clock DStreams cannot do;
- batch row/token counts are padded to power-of-two buckets (features/batch.py)
  so XLA compiles a handful of programs, not one per batch shape.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from ..features.batch import FeatureBatch, UnitBatch
from ..features.featurizer import Featurizer, Status
from ..utils import get_logger
from .sources import Source

log = get_logger("streaming.context")

BatchFn = Callable[[FeatureBatch, float], None]


class RawStream:
    """A stream of raw Status lists — for apps with their own featurization
    (the k-means entry featurizes to a dense pair, KMeans.scala:19-33).
    Outputs fire per micro-batch in registration order (reference: foreachRDD
    at LinearRegression.scala:53, trainOn at :86)."""

    def __init__(self):
        self._outputs: list[Callable] = []

    def foreach_batch(self, fn) -> "RawStream":
        self._outputs.append(fn)
        return self

    def _process(self, statuses: list[Status], batch_time: float):
        for fn in self._outputs:
            fn(statuses, batch_time)


class FeatureStream(RawStream):
    """A RawStream whose outputs receive padded FeatureBatches instead of
    Status lists (DStream.map(featurize) analog)."""

    def __init__(
        self,
        featurizer: Featurizer,
        row_bucket: int = 0,
        token_bucket: int = 0,
        row_multiple: int = 1,
        device_hash: bool = False,
    ):
        super().__init__()
        self.featurizer = featurizer
        self.row_bucket = row_bucket
        self.token_bucket = token_bucket
        self.row_multiple = row_multiple
        self.device_hash = device_hash
        self._bucket_overflow_warned = False
        # the pinned row shape includes the mesh-divisibility round-up,
        # matching every batch the featurizer emits; fixed at construction
        from ..features.batch import pad_row_count

        self._pinned_rows = (
            pad_row_count(0, row_bucket, row_multiple) if row_bucket > 0 else 0
        )

    def _check_buckets(self, batch) -> None:
        """Warn (once) when a batch overflowed the pinned buckets: the
        featurizer grows the bucket rather than truncate, so the step
        recompiles for the bigger shape — silently defeating a pre-stream
        compile warmup and multiplying program count."""
        if self._bucket_overflow_warned:
            return
        rows = batch.mask.shape[0]
        tokens = (
            batch.units.shape[1]
            if isinstance(batch, UnitBatch)
            else batch.token_idx.shape[1]
        )
        over_rows = 0 < self._pinned_rows < rows
        over_tok = 0 < self.token_bucket < tokens
        if over_rows or over_tok:
            self._bucket_overflow_warned = True
            log.warning(
                "batch shape (%d, %d) overflowed the pinned buckets "
                "(%d, %d): the step recompiles for the larger shape — "
                "raise --batchBucket/--tokenBucket to keep one program",
                rows, tokens, self.row_bucket, self.token_bucket,
            )

    def _featurize(self, statuses: list) -> "FeatureBatch | UnitBatch":
        """The ONE featurize dispatch for this stream's configuration —
        shared by the per-batch path and ``featurize_empty`` so a compile
        warmup always warms exactly the program the stream will run."""
        from ..features.blocks import ParsedBlock, merge_blocks

        if statuses and isinstance(statuses[0], ParsedBlock):
            # native block ingest: items are pre-filtered columnar blocks
            # (sources.BlockReplayFileSource); featurize without per-tweet
            # Python objects
            return self.featurizer.featurize_parsed_block(
                merge_blocks(statuses), row_bucket=self.row_bucket,
                unit_bucket=self.token_bucket, row_multiple=self.row_multiple,
            )
        if self.device_hash:
            # ship raw code units; the learner hashes bigrams on device
            # (ops/text_hash.py) — bit-identical features, ~2x host headroom
            return self.featurizer.featurize_batch_units(
                statuses, row_bucket=self.row_bucket,
                unit_bucket=self.token_bucket, row_multiple=self.row_multiple,
            )
        return self.featurizer.featurize_batch(
            statuses, row_bucket=self.row_bucket,
            token_bucket=self.token_bucket,
            row_multiple=self.row_multiple,
        )

    def featurize_empty(self) -> "FeatureBatch | UnitBatch":
        """An all-padding batch of this stream's exact configured shape
        (meaningful when both buckets are pinned) — for pre-stream compile
        warmup."""
        return self._featurize([])

    def _process(
        self, statuses: list[Status], batch_time: float
    ) -> "FeatureBatch | UnitBatch":
        batch = self._featurize(statuses)
        self._check_buckets(batch)
        for fn in self._outputs:
            fn(batch, batch_time)
        return batch


class StreamingContext:
    def __init__(self, batch_interval: float = 5.0):
        self.batch_interval = batch_interval
        self._queue: "queue.Queue[Status]" = queue.Queue()
        self._source: Source | None = None
        self._stream: RawStream | None = None
        self._scheduler: threading.Thread | None = None
        self._stop = threading.Event()
        self._terminated = threading.Event()
        self.batches_processed = 0

    def source_stream(
        self,
        source: Source,
        featurizer: Featurizer,
        row_bucket: int = 0,
        token_bucket: int = 0,
        row_multiple: int = 1,
        device_hash: bool = False,
    ) -> FeatureStream:
        """Attach the (single) source and build its feature stream —
        equivalent of TwitterUtils.createStream().filter().map().cache()
        (LinearRegression.scala:44-47)."""
        if self._source is not None:
            raise ValueError("StreamingContext supports one source stream")
        self._source = source
        self._stream = FeatureStream(
            featurizer, row_bucket, token_bucket, row_multiple, device_hash
        )
        return self._stream

    def raw_stream(self, source: Source) -> RawStream:
        """Attach the source with no featurization — outputs receive the raw
        Status list per micro-batch."""
        if self._source is not None:
            raise ValueError("StreamingContext supports one source stream")
        self._source = source
        self._stream = RawStream()
        return self._stream

    def _drain(self, limit: int = 0) -> list[Status]:
        """Drain queued items; ``limit`` caps the drained ROW count (a
        ParsedBlock item counts its rows, a Status counts 1 — one block can
        overshoot the cap, exactly like it overshoots a pinned bucket)."""
        out: list[Status] = []
        rows = 0
        while not limit or rows < limit:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            out.append(item)
            rows += getattr(item, "rows", 1)
        return out

    def _run_batch(self, statuses: list[Status], batch_time: float) -> None:
        try:
            self._stream._process(statuses, batch_time)
            self.batches_processed += 1
        except Exception:
            log.exception("batch at t=%.3f failed", batch_time)

    def _scheduler_loop(self) -> None:
        # back-to-back mode (--seconds 0) with a pinned row bucket: cap each
        # batch at the bucket so a fast source yields deterministic
        # fixed-size batches (the run_to_completion semantic) instead of one
        # giant drain — bounded memory, one compiled shape, and the unit
        # --superBatch groups. Wall-clock mode drains the full interval.
        limit = (
            getattr(self._stream, "row_bucket", 0)
            if self.batch_interval == 0
            else 0
        )
        next_tick = time.monotonic() + self.batch_interval
        while not self._stop.is_set():
            delay = next_tick - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                break
            next_tick += self.batch_interval
            if limit and self._queue.qsize() < limit and not self._source.exhausted:
                # fill the bucket before processing: batch boundaries stay
                # deterministic (full buckets + one tail) instead of racing
                # the producer — the run_to_completion contract
                self._stop.wait(0.002)
                continue
            self._run_batch(self._drain(limit), time.time())
            if self._source.exhausted and self._queue.empty():
                break
        self._terminated.set()

    def request_stop(self) -> None:
        """Ask the scheduler to stop after the current batch — the public
        early-exit hook apps use for max-batches caps."""
        self._stop.set()

    # -- lifecycle (ssc.start/awaitTermination, LinearRegression.scala:89-91) --
    def start(self) -> None:
        if self._stream is None:
            raise ValueError("no stream registered")
        self._stop.clear()
        self._terminated.clear()
        self._source.start(self._queue.put)
        self._scheduler = threading.Thread(
            target=self._scheduler_loop, name="twtml-batch-scheduler", daemon=True
        )
        self._scheduler.start()

    def await_termination(self, timeout: float | None = None) -> bool:
        return self._terminated.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        if self._source is not None:
            self._source.stop()
        if self._scheduler is not None:
            self._scheduler.join(timeout=10)
        self._terminated.set()

    # -- deterministic replay mode (no wall clock) ---------------------------
    def run_to_completion(self, max_batch_size: int = 1024) -> int:
        """Drive the source synchronously: fill batches of up to
        ``max_batch_size`` tweets and process back-to-back. Returns number of
        batches run. Used by benchmarks and parity tests where the 5s cadence
        would only add idle time."""
        if self._stream is None:
            raise ValueError("no stream registered")
        self._source.start(self._queue.put)
        n0 = self.batches_processed
        pending: list[Status] = []
        while not self._stop.is_set():
            try:
                pending.append(self._queue.get(timeout=0.05))
                if len(pending) >= max_batch_size:
                    self._run_batch(pending, time.time())
                    pending = []
            except queue.Empty:
                if self._source.exhausted:
                    # re-drain: the source may have emitted between our
                    # timeout and the exhausted flag being set
                    pending.extend(self._drain())
                    break
        if pending and not self._stop.is_set():
            self._run_batch(pending, time.time())
        self._terminated.set()
        return self.batches_processed - n0
