"""Micro-batch streaming runtime — the DStream/StreamingContext equivalent.

The reference slices a live stream into RDDs every ``seconds`` and runs two
registered outputs per batch: the stats ``foreachRDD`` and ``model.trainOn``
(LinearRegression.scala:40-47,53,86). Here a ``StreamingContext`` owns one
source feeding a thread-safe queue; a scheduler thread wakes every
``batch_interval`` seconds, drains the queue, filters + featurizes + pads the
tweets into one fixed-shape ``FeatureBatch``, and invokes every registered
output in registration order (so stats-before-train ordering is preserved
when callers register them separately; the fused model step keeps it
internally regardless).

Differences by design:
- featurization happens once per batch on the host (numpy), not as per-element
  closures shipped to executors — the device program consumes one padded batch;
- ``run_to_completion`` offers a deterministic clock-free mode (replay/bench):
  process fixed-size batches back-to-back until the source is exhausted,
  which wall-clock DStreams cannot do;
- batch row/token counts are padded to power-of-two buckets (features/batch.py)
  so XLA compiles a handful of programs, not one per batch shape.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from ..features.batch import FeatureBatch, UnitBatch
from ..features.featurizer import Featurizer, Status
from ..telemetry import lineage as _lineage
from ..telemetry import metrics as _metrics
from ..telemetry import sideband as _sideband
from ..telemetry import trace as _trace
from ..utils import get_logger
from ..utils.clock import now_s
from . import journal as _journal
from .sources import Source

log = get_logger("streaming.context")

BatchFn = Callable[[FeatureBatch, float], None]

# lockstep peer watchdog: how long the per-tick cadence allgather may make
# no progress before this host concludes a peer is gone (hard kill /
# network partition) and aborts loudly instead of hanging in the
# collective forever. Generous default: ticks legitimately skew by a slow
# host's featurize/parse + a ~30s first-batch compile. 0 disables.
LOCKSTEP_TIMEOUT_ENV = "TWTML_LOCKSTEP_TIMEOUT_S"
LOCKSTEP_TIMEOUT_DEFAULT_S = 120.0


def _watched_allgather(arr, timeout_s: float):
    """Run one cadence allgather under a progress watchdog: returns the
    gathered array, or None when the watchdog fired. The collective runs
    on a daemon thread (never a ThreadPoolExecutor — concurrent.futures
    joins its workers at interpreter exit, so a wedged collective would
    hang shutdown; a daemon thread dies with the process). The scheduler
    blocks on the result before dispatching, so per-host collective issue
    order stays total — only the executing thread changes. Thread spawn is
    ~50µs against a per-batch tick; exceptions from the collective (a dead
    peer often surfaces as a transport error rather than a hang) propagate
    to the caller."""
    from jax.experimental import multihost_utils

    if timeout_s <= 0:
        return multihost_utils.process_allgather(arr)
    box: dict = {}
    done = threading.Event()

    def run() -> None:
        try:
            box["out"] = multihost_utils.process_allgather(arr)
        except BaseException as exc:  # noqa: BLE001 — re-raised below  # lawcheck: disable=TW005 -- not a swallow: captured into the box and re-raised by the waiting caller
            box["exc"] = exc
        done.set()

    threading.Thread(
        target=run, daemon=True, name="twtml-lockstep-allgather"
    ).start()
    if not done.wait(timeout_s):
        return None
    if "exc" in box:
        raise box["exc"]
    return box["out"]


SHED_POLICIES = ("block", "shed-oldest")


class _RowCountQueue(queue.Queue):
    """queue.Queue that also tracks the queued ROW count (a ParsedBlock item
    counts its rows, a Status counts 1) — maintained inside ``_put``/``_get``,
    which run under the queue's own mutex, so the per-tweet intake path pays
    no extra lock. The back-to-back fill gate compares ``rows_queued`` (not
    item count) to the row bucket; reading the int without the mutex is fine
    for a gate that only ever errs toward one more 2 ms wait.

    **Bounded backpressure (r7)**: ``configure_bound`` arms a ROW-count
    ceiling (``--maxQueueRows``) with two overload policies — the intake
    queue was the last unbounded buffer in the pipeline (a source burst or
    a slow tunnel phase grew host RSS without limit, compounding the known
    axon-client retention, BENCHMARKS.md r3 soak):

    - ``block`` (default): the producer thread waits until the consumer
      drains below the bound — correct for replay/backfill sources, where
      the data can't be lost and the file isn't going anywhere;
    - ``shed-oldest``: drop whole items from the queue FRONT until the new
      item fits — correct for live sources, where the freshest rows are
      the valuable ones and blocking would just move the loss upstream
      into the kernel socket buffer. Shedding from the front never
      reorders the survivors (parity: predict-then-train ordering holds
      on whatever rows remain — tests/test_backpressure.py).

    Shed rows are counted (``ingest.rows_shed``); an item bigger than the
    whole bound is admitted alone (blocking it forever would deadlock the
    stream on one oversized block). ``close()`` releases a blocked
    producer at shutdown. Unbounded (``max_rows=0``) puts take the exact
    pre-r7 path."""

    max_rows = 0
    policy = "block"

    def _init(self, maxsize: int) -> None:
        super()._init(maxsize)
        self.rows_queued = 0
        self.rows_shed_total = 0
        self._closed = False

    def configure_bound(self, max_rows: int, policy: str = "block") -> None:
        if policy not in SHED_POLICIES:
            raise ValueError(
                f"shed policy must be one of {SHED_POLICIES}, got {policy!r}"
            )
        self.max_rows = max(0, int(max_rows))
        self.policy = policy

    def close(self) -> None:
        """Release producers blocked on a full bounded queue (shutdown:
        the consumer is gone, so waiting would wedge ``Source.stop``)."""
        with self.mutex:
            self._closed = True
            self.not_full.notify_all()

    def put(self, item, block=True, timeout=None) -> None:
        if self.max_rows <= 0:
            return super().put(item, block, timeout)
        rows = getattr(item, "rows", 1)
        with self.not_full:
            if self.policy == "block":
                # admit when empty regardless of size: one item larger
                # than the whole bound must pass, not deadlock
                while (
                    self.rows_queued > 0
                    and self.rows_queued + rows > self.max_rows
                    and not self._closed
                ):
                    # timed wait belt-and-braces: queue.Queue.get always
                    # notifies not_full, but a missed wakeup must not
                    # wedge the producer forever
                    self.not_full.wait(0.1)
            else:  # shed-oldest
                shed = 0
                while self.queue and self.rows_queued + rows > self.max_rows:
                    old = self.queue.popleft()
                    r = getattr(old, "rows", 1)
                    self.rows_queued -= r
                    shed += r
                if shed:
                    self.rows_shed_total += shed
                    reg = _metrics.get_registry()
                    reg.counter("ingest.rows_shed").inc(shed)
                    reg.gauge("ingest.queue_rows").set(self.rows_queued)
                    log.warning(
                        "intake queue over --maxQueueRows %d: shed %d "
                        "oldest row(s) to admit %d new (total shed %d)",
                        self.max_rows, shed, rows, self.rows_shed_total,
                    )
            self._put(item)
            self.unfinished_tasks += 1
            self.not_empty.notify()

    def putback(self, item) -> None:
        """Return an item to the FRONT of the queue (the drain splitter's
        remainder — it must come out first so row order is preserved).
        Exempt from the bound: these rows were already admitted once."""
        with self.mutex:
            self.queue.appendleft(item)
            self.rows_queued += getattr(item, "rows", 1)
            self.not_empty.notify()

    def drain_rows(self, limit: int = 0, slicer=None):
        """Pop queued items up to ``limit`` ROWS (0 = everything) under ONE
        mutex acquire, splitting an overshooting block via ``slicer(item,
        cut) -> (head, tail)`` with the tail left at the queue front.

        Why not get_nowait in a loop: every ``Queue.get`` notifies
        ``not_full``, so a 2048-row drain woke a bound-blocked producer
        2048 times to re-check and re-sleep against a still-full queue —
        measurable lock churn on the one-core host. One acquire + one
        ``notify_all`` per drain instead, and the producer wakes exactly
        once, into a freshly drained bound."""
        out: list = []
        rows = 0
        with self.mutex:
            while self.queue and (not limit or rows < limit):
                item = self.queue[0]
                take = getattr(item, "rows", None)
                if take is not None and limit and rows + take > limit:
                    cut = limit - rows
                    head, tail = slicer(item, cut)
                    self.queue[0] = tail
                    self.rows_queued -= cut
                    out.append(head)
                    rows = limit
                    break
                self.queue.popleft()
                taken = take if take is not None else 1
                self.rows_queued -= taken
                rows += taken
                out.append(item)
            self.not_full.notify_all()
        return out

    def _put(self, item) -> None:
        super()._put(item)
        self.rows_queued += getattr(item, "rows", 1)

    def _get(self):
        item = super()._get()
        self.rows_queued -= getattr(item, "rows", 1)
        return item


class RawStream:
    """A stream of raw Status lists — for apps with their own featurization
    (the k-means entry featurizes to a dense pair, KMeans.scala:19-33).
    Outputs fire per micro-batch in registration order (reference: foreachRDD
    at LinearRegression.scala:53, trainOn at :86).

    ``row_bucket`` (optional) caps the scheduler's back-to-back drains —
    required by multi-host lockstep, where the app's per-batch handler owns
    fixed-shape padding and every host must dispatch the same program."""

    def __init__(self, row_bucket: int = 0):
        self._outputs: list[Callable] = []
        self.row_bucket = row_bucket

    def foreach_batch(self, fn) -> "RawStream":
        self._outputs.append(fn)
        return self

    def _process(self, statuses: list[Status], batch_time: float):
        for fn in self._outputs:
            fn(statuses, batch_time)


class FeatureStream(RawStream):
    """A RawStream whose outputs receive padded FeatureBatches instead of
    Status lists (DStream.map(featurize) analog)."""

    def __init__(
        self,
        featurizer: Featurizer,
        row_bucket: int = 0,
        token_bucket: int = 0,
        row_multiple: int = 1,
        device_hash: bool = False,
        ragged: bool = False,
    ):
        super().__init__()
        self.featurizer = featurizer
        self.row_bucket = row_bucket
        self.token_bucket = token_bucket
        self.row_multiple = row_multiple
        self.device_hash = device_hash
        self.ragged = ragged
        if ragged and not device_hash:
            raise ValueError(
                "the ragged wire IS a device-hash wire format: "
                "--wire ragged requires --hashOn device"
            )
        self._bucket_overflow_warned = False
        # the pinned row shape includes the mesh-divisibility round-up,
        # matching every batch the featurizer emits; fixed at construction
        from ..features.batch import pad_row_count

        self._pinned_rows = (
            pad_row_count(0, row_bucket, row_multiple) if row_bucket > 0 else 0
        )

    @staticmethod
    def batch_shape(batch) -> "tuple[int, int]":
        """(rows, tokens-or-units) of a featurized batch — the two axes the
        pinned buckets govern."""
        from ..features.batch import RaggedUnitBatch

        if isinstance(batch, RaggedUnitBatch):
            # the ragged wire's row length is static aux (the device-side
            # re-pad width) — the same axis token_bucket pins
            return batch.mask.shape[0], batch.row_len
        tokens = (
            batch.units.shape[1]
            if isinstance(batch, UnitBatch)
            else batch.token_idx.shape[1]
        )
        return batch.mask.shape[0], tokens

    def bucket_overflow(self, batch) -> bool:
        """Whether a featurized batch outgrew the pinned buckets (the
        featurizer grows rather than truncates)."""
        rows, tokens = self.batch_shape(batch)
        return (0 < self._pinned_rows < rows) or (
            0 < self.token_bucket < tokens
        )

    def _check_buckets(self, batch) -> None:
        """Warn (once) when a batch overflowed the pinned buckets: the
        featurizer grows the bucket rather than truncate, so the step
        recompiles for the bigger shape — silently defeating a pre-stream
        compile warmup and multiplying program count."""
        if self._bucket_overflow_warned or not self.bucket_overflow(batch):
            return
        self._bucket_overflow_warned = True
        rows, tokens = self.batch_shape(batch)
        log.warning(
            "batch shape (%d, %d) overflowed the pinned buckets "
            "(%d, %d): the step recompiles for the larger shape — "
            "raise --batchBucket/--tokenBucket to keep one program",
            rows, tokens, self.row_bucket, self.token_bucket,
        )

    def _featurize(self, statuses: list) -> "FeatureBatch | UnitBatch":
        """The ONE featurize dispatch for this stream's configuration —
        shared by the per-batch path and ``featurize_empty`` so a compile
        warmup always warms exactly the program the stream will run.
        Instrumented as the ``featurize`` stage (host featurize incl. wire
        build); the span and the ``pipeline.*``/``wire.bytes`` metrics are
        side-channel only — the batch itself is untouched. Timed
        unconditionally (two clock reads per BATCH) so the per-host
        sideband's featurize attribution works without ``--trace``."""
        tr = _trace.get()
        t0 = time.perf_counter()
        if not tr.enabled:
            batch = self._featurize_impl(statuses)
            _sideband.record_stage("featurize", time.perf_counter() - t0)
            self._record_substages(None)
            return self._poison_gate(statuses, batch)
        with tr.span("featurize", items=len(statuses)) as sp:
            batch = self._featurize_impl(statuses)
            from ..features.batch import wire_nbytes

            sp.add(
                rows=int(batch.mask.shape[0]),
                valid=batch.num_valid,
                wire_bytes=wire_nbytes(batch),
            )
        _sideband.record_stage("featurize", time.perf_counter() - t0)
        self._record_substages(tr)
        return self._poison_gate(statuses, batch)

    def _record_substages(self, tr) -> None:
        """The featurize sub-stage clock (r18): per-batch encode /
        numeric / wire_build durations recorded by the featurizer
        (featurizer.last_substages) become ``featurize.<name>_ms``
        gauges on /api/metrics — so the straggler ladder can name WHICH
        half of featurize gates a host — and, under ``--trace``, nested
        ``featurize.<name>`` complete-events inside the featurize span.
        Telemetry side-channel only: host clock reads, zero added
        fetches (the gauges never touch a device array)."""
        subs = getattr(self.featurizer, "last_substages", None)
        if not subs:
            return
        agg: "dict[str, float]" = {}
        for name, sub_t0, dur in subs:
            agg[name] = agg.get(name, 0.0) + dur
            if tr is not None:
                tr.complete("featurize." + name, sub_t0, dur)
        reg = _metrics.get_registry()
        for name, dur in agg.items():
            reg.gauge(f"featurize.{name}_ms").set(round(dur * 1e3, 4))

    @staticmethod
    def _poison_gate(statuses: list, batch):
        """--chaos ``source.nan`` injection point: only REAL batches count
        toward (and may fire) the rule — warmup/all-padding featurizes pass
        ``statuses=[]`` and must not advance the per-host call counter
        (lockstep hosts featurize in step; a dry host skewing the counter
        would desynchronize deterministic triggers across the group)."""
        from . import faults as _faults_inner

        if not statuses or _faults_inner._CHAOS is None:
            return batch
        return _faults_inner.maybe_poison_labels(batch)

    @staticmethod
    def _record_metrics(batch) -> None:
        from ..features.batch import wire_composition, wire_nbytes

        reg = _metrics.get_registry()
        reg.counter("pipeline.batches").inc()
        reg.counter("pipeline.tweets").inc(batch.num_valid)
        reg.counter("wire.bytes").inc(wire_nbytes(batch))
        # per-batch wire composition (Lean wire v2): the units/offsets/
        # sideband split makes the offset-narrowing visible in /api/metrics
        # and trace reports without a bench run
        comp = wire_composition(batch)
        reg.gauge("wire.units_bytes").set(comp["units"])
        reg.gauge("wire.offsets_bytes").set(comp["offsets"])
        reg.gauge("wire.sideband_bytes").set(comp["sideband"])

    def _featurize_impl(self, statuses: list) -> "FeatureBatch | UnitBatch":
        from ..features.blocks import ParsedBlock, merge_blocks

        if statuses and isinstance(statuses[0], ParsedBlock):
            # native block ingest: items are pre-filtered columnar blocks
            # (sources.BlockReplayFileSource); featurize without per-tweet
            # Python objects
            return self.featurizer.featurize_parsed_block(
                merge_blocks(statuses), row_bucket=self.row_bucket,
                unit_bucket=self.token_bucket, row_multiple=self.row_multiple,
                ragged=self.ragged,
            )
        if self.device_hash:
            if self.ragged:
                # concatenated units + offsets: no per-row pad bytes on the
                # upload-bound wire (features/batch.RaggedUnitBatch —
                # measured +14% paired vs the padded wire, BENCHMARKS.md)
                return self.featurizer.featurize_batch_ragged(
                    statuses, row_bucket=self.row_bucket,
                    unit_bucket=self.token_bucket,
                    row_multiple=self.row_multiple,
                )
            # ship raw code units; the learner hashes bigrams on device
            # (ops/text_hash.py) — bit-identical features, ~2x host headroom
            return self.featurizer.featurize_batch_units(
                statuses, row_bucket=self.row_bucket,
                unit_bucket=self.token_bucket, row_multiple=self.row_multiple,
            )
        return self.featurizer.featurize_batch(
            statuses, row_bucket=self.row_bucket,
            token_bucket=self.token_bucket,
            row_multiple=self.row_multiple,
        )

    def featurize_empty(self) -> "FeatureBatch | UnitBatch":
        """An all-padding batch of this stream's exact configured shape
        (meaningful when both buckets are pinned) — for pre-stream compile
        warmup."""
        return self._featurize([])

    def _process(
        self, statuses: list[Status], batch_time: float
    ) -> "FeatureBatch | UnitBatch":
        # freshness lineage (r16): stamp the batch's record as it enters
        # featurize — the event-time span + a stage-clock snapshot; no-op
        # unless the plane is on
        _lineage.open_batch(statuses)
        # durable intake journal (r21): the ONE blessed append seam with
        # _run_batch_aligned below (lawcheck TW009) — raw rows become a
        # CRC-framed replay record BEFORE featurize, so every recovery
        # path re-ingests bytes the unchanged featurize path re-reads
        _journal.record_intake(statuses)
        batch = self._featurize(statuses)
        self._check_buckets(batch)
        self._record_metrics(batch)
        for fn in self._outputs:
            fn(batch, batch_time)
        return batch


class StreamingContext:
    def __init__(self, batch_interval: float = 5.0,
                 max_queue_rows: int = 0, shed_policy: str = "block"):
        """``max_queue_rows``/``shed_policy`` arm the bounded intake queue
        (``--maxQueueRows``/``--shedPolicy`` — see _RowCountQueue); 0 keeps
        the pre-r7 unbounded queue (tests and embedded uses)."""
        self.batch_interval = batch_interval
        self._queue: _RowCountQueue = _RowCountQueue()
        if max_queue_rows > 0:
            self._queue.configure_bound(max_queue_rows, shed_policy)
        self._source: Source | None = None
        self._stream: RawStream | None = None
        self._scheduler: threading.Thread | None = None
        self._stop = threading.Event()
        self._terminated = threading.Event()
        self.batches_processed = 0
        # set when a lockstep run aborted (this host or a peer): the app
        # must surface a failure instead of reporting success
        self.failed = False
        # divergence-sentinel hook (apps/common.DivergenceSentinel.bind_ssc):
        # returns this host's cumulative rollback count, so the decision
        # rides the per-tick cadence allgather in lockstep runs and every
        # host can verify the group rolled back the same steps
        self.rollback_count_fn: "Callable[[], int] | None" = None
        # elastic membership plane (--elastic on, streaming/membership.py):
        # when set, peer loss re-forms the group instead of aborting it,
        # and the membership columns ride the cadence allgather
        self.membership = None

    def source_stream(
        self,
        source: Source,
        featurizer: Featurizer,
        row_bucket: int = 0,
        token_bucket: int = 0,
        row_multiple: int = 1,
        device_hash: bool = False,
        ragged: bool = False,
    ) -> FeatureStream:
        """Attach the (single) source and build its feature stream —
        equivalent of TwitterUtils.createStream().filter().map().cache()
        (LinearRegression.scala:44-47)."""
        if self._source is not None:
            raise ValueError("StreamingContext supports one source stream")
        self._source = source
        self._stream = FeatureStream(
            featurizer, row_bucket, token_bucket, row_multiple, device_hash,
            ragged,
        )
        return self._stream

    def raw_stream(self, source: Source, row_bucket: int = 0) -> RawStream:
        """Attach the source with no featurization — outputs receive the raw
        Status list per micro-batch. ``row_bucket`` caps back-to-back
        drains (required in multi-host lockstep)."""
        if self._source is not None:
            raise ValueError("StreamingContext supports one source stream")
        self._source = source
        self._stream = RawStream(row_bucket)
        return self._stream

    def _drain(self, limit: int = 0) -> list[Status]:
        """Drain queued items; ``limit`` caps the drained ROW count (a
        ParsedBlock item counts its rows, a Status counts 1). A ParsedBlock
        that would overshoot the cap is SPLIT at the cap (r5) and its
        remainder put back at the queue front — capped drains are therefore
        exactly ``limit`` rows while data lasts, which multi-host lockstep
        requires (an overshooting block would grow this host's program
        shape away from its peers') and which makes single-host
        back-to-back block batches deterministic bucket-sized too.

        Instrumented as the ``source_read`` stage when tracing is on; timed
        unconditionally (per drain, not per item) for the sideband."""
        tr = _trace.get()
        t0 = time.perf_counter()
        if not tr.enabled:
            out = self._drain_impl(limit)
            _sideband.record_stage("source_read", time.perf_counter() - t0)
            return out
        with tr.span("source_read") as sp:
            out = self._drain_impl(limit)
            sp.add(items=len(out))
        _sideband.record_stage("source_read", time.perf_counter() - t0)
        return out

    def _drain_impl(self, limit: int = 0) -> list[Status]:
        from ..features.blocks import slice_block

        out = self._queue.drain_rows(
            limit,
            slicer=lambda item, cut: (
                slice_block(item, 0, cut),
                slice_block(item, cut, item.rows),
            ),
        )
        # queue depth is per-BATCH registry state (one gauge set per drain,
        # never per tweet — the intake hot path pays no metric lock)
        _metrics.get_registry().gauge("ingest.queue_rows").set(
            self._queue.rows_queued
        )
        return out

    def _run_batch(self, statuses: list[Status], batch_time: float) -> None:
        try:
            self._stream._process(statuses, batch_time)
            self.batches_processed += 1
        except Exception:
            log.exception("batch at t=%.3f failed", batch_time)

    def _scheduler_loop(self) -> None:
        # back-to-back mode (--seconds 0) with a pinned row bucket: cap each
        # batch at the bucket so a fast source yields deterministic
        # fixed-size batches (the run_to_completion semantic) instead of one
        # giant drain — bounded memory, one compiled shape, and the unit
        # --superBatch groups. Wall-clock mode drains the full interval.
        limit = (
            getattr(self._stream, "row_bucket", 0)
            if self.batch_interval == 0
            else 0
        )
        next_tick = time.monotonic() + self.batch_interval
        while not self._stop.is_set():
            delay = next_tick - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                break
            next_tick += self.batch_interval
            if limit and self._queue.rows_queued < limit and not self._source.exhausted:
                # fill the bucket before processing: batch boundaries stay
                # deterministic (full buckets + one tail) instead of racing
                # the producer — the run_to_completion contract
                self._stop.wait(0.002)
                continue
            self._run_batch(self._drain(limit), now_s())
            if self._source.exhausted and self._queue.empty():
                break
        self._terminated.set()

    def request_stop(self) -> None:
        """Ask the scheduler to stop after the current batch — the public
        early-exit hook apps use for max-batches caps."""
        self._stop.set()

    def request_abort(self, reason: str = "runtime guard abort") -> None:
        """Loud-failure hook for the runtime guards (fetch watchdog,
        divergence sentinel, lockstep peer watchdog, cadence
        disagreement): mark the run failed and stop after the current
        batch, so the app's shutdown path still flushes its final
        checkpoint and the process exits non-zero.

        Every abort path funnels through here, which makes it the crash
        flight recorder's trigger (telemetry/blackbox.py): the post-mortem
        bundle dumps ONCE, before the stream winds down — no-op when no
        recorder is installed."""
        self.failed = True
        from ..telemetry import blackbox as _blackbox

        _blackbox.abort_dump(reason)
        self.request_stop()

    @property
    def stop_requested(self) -> bool:
        """Whether a stop has been requested (read by the concurrent
        fetch pipeline to honor max-batches caps exactly, apps/common.py
        FetchPipeline)."""
        return self._stop.is_set()

    def _putback(self, items: list) -> None:
        """Return this tick's drained items to the queue FRONT in order —
        an elastic membership transition re-forms the group between ticks,
        and the rows drained for the interrupted tick must train on the
        next one (no silent loss)."""
        for item in reversed(items):
            self._queue.putback(item)

    def _elastic_recover(self, local: list, why: str) -> bool:
        """Peer-loss recovery hook: with an elastic membership plane
        installed, a wedged/failed cadence collective becomes a rescue
        (shrink + re-form + continue) instead of an abort. Returns True
        when the loop should continue on the re-formed group."""
        if self.membership is None:
            return False
        self._putback(local)
        _metrics.get_registry().counter("lockstep.elastic_rescues").inc()
        log.critical(
            "lockstep cadence collective failed (%s); elastic membership "
            "is ON — attempting an out-of-band shrink instead of aborting",
            why,
        )
        try:
            return self.membership.rescue(why)
        except Exception:
            log.critical("elastic rescue failed", exc_info=True)
            return False

    def _run_batch_aligned(self, statuses: list[Status], batch_time: float) -> None:
        """Lockstep-mode batch: host-local failures must never change this
        host's COLLECTIVE program sequence (the other hosts' psums would
        block forever on the missing program). A featurize failure — purely
        host-side, nothing dispatched yet — substitutes the all-padding
        batch (rows lost, loudly). A shape overflow of the pinned buckets
        would dispatch a DIFFERENTLY-SHAPED program than the peers', so it
        is a hard error. Output (dispatch/handler) exceptions propagate to
        the loop: after a possible partial dispatch alignment is unknowable,
        and failing fast beats a distributed hang."""
        stream = self._stream
        if not isinstance(stream, FeatureStream):
            # raw lockstep (the k-means entry): the app's per-batch handler
            # owns fixed-shape padding and global assembly, so there is no
            # featurize stage to guard here; handler failures propagate to
            # the loop's abort path (alignment unknowable after a possible
            # partial dispatch)
            stream._process(statuses, batch_time)
            self.batches_processed += 1
            return
        # freshness lineage (r16): one open per lockstep batch, stamped
        # before featurize like FeatureStream._process (the failure paths
        # below re-featurize but never re-open)
        _lineage.open_batch(statuses)
        # intake journal (r21): append ONCE per lockstep batch — the
        # failure paths below re-featurize but never re-append
        _journal.record_intake(statuses)
        try:
            batch = stream._featurize(statuses)
        except Exception:
            log.exception(
                "featurize failed in lockstep mode; substituting an "
                "all-padding batch to keep the group's collective sequence "
                "aligned (these rows are lost)"
            )
            batch = stream._featurize([])
        if stream.bucket_overflow(batch):
            # single-host runs grow the bucket and recompile (benign); here
            # a grown shape means THIS host dispatches a differently-shaped
            # collective program than its peers → distributed hang. The
            # overflow is data-dependent (one long tweet), so it must not
            # kill the run either: drop the over-long rows, keep the rest.
            # conservative probe: the featurizer owns the canonical text
            # encoding (host-hash wire carries units-1 bigram tokens, so
            # <= token_bucket under-admits by at most one unit there)
            kept = [
                s for s in statuses
                if stream.featurizer.unit_len(s) <= stream.token_bucket
            ]
            rows, tokens = stream.batch_shape(batch)
            log.error(
                "batch shape (%d, %d) overflowed the pinned buckets "
                "(%d, %d) in a multi-host run; dropping %d over-long row(s) "
                "to keep the group's program shapes aligned — raise "
                "--batchBucket/--tokenBucket", rows, tokens,
                stream.row_bucket, stream.token_bucket,
                len(statuses) - len(kept),
            )
            # registry state, not log-only (r7): dropped rows must show on
            # /api/metrics next to the other ingest-loss counters
            _metrics.get_registry().counter(
                "ingest.rows_dropped_overflow"
            ).inc(len(statuses) - len(kept))
            batch = stream._featurize(kept)
            if stream.bucket_overflow(batch):
                # probe missed (e.g. a case fold changed the length):
                # last resort keeps alignment at the cost of the batch
                log.error("overflow persists; dropping the whole batch")
                _metrics.get_registry().counter(
                    "ingest.rows_dropped_overflow"
                ).inc(len(kept))
                batch = stream._featurize([])
        stream._record_metrics(batch)
        for fn in stream._outputs:
            fn(batch, batch_time)
        self.batches_processed += 1

    def _lockstep_loop(self) -> None:
        """Multi-host batch scheduler: every process must run the SAME
        sequence of collective programs, so batch cadence and termination
        are agreed per tick with one tiny all-process allgather of
        (has_rows, more_coming, abort). A host whose intake shard ran dry
        keeps dispatching all-padding batches (zero-sample steps are weight
        no-ops) until EVERY host is exhausted — otherwise the other hosts'
        psums would wait forever on its missing program.

        A batch failure AFTER featurize leaves this host's collective
        alignment unknowable, so it stops dispatching — but it keeps
        ticking the allgather with abort=1 until every peer has seen it
        (peers then stop too instead of stalling in their next collective),
        and the run is marked ``failed`` so the app can exit non-zero
        rather than report success.

        A hard-killed peer can never tick its abort flag, so the allgather
        itself runs under a progress watchdog (``_watched_allgather``,
        ``TWTML_LOCKSTEP_TIMEOUT_S``): when it fires — or the collective
        raises a transport error, the other way a dead peer surfaces —
        this host aborts LOUDLY (``failed=True`` → the app exits non-zero
        after its shutdown path flushes a final checkpoint) instead of
        hanging in the collective forever. Collectives INSIDE a dispatched
        step are covered separately: their results surface through the
        pooled stats fetch, whose own watchdog (apps/common.FetchWatchdog)
        aborts the same way.

        Drains are capped at the row bucket in BOTH modes (wall-clock rows
        beyond the bucket stay queued for the next tick): an uncapped drain
        could exceed --batchBucket and grow this host's program shape away
        from its peers'.

        **Per-host telemetry sideband (r8)**: the flags array WIDENS to
        carry each host's fixed sideband vector (telemetry/sideband.py —
        per-stage wall times, queue depth, fetch-RTT median, shed/rollback
        counters, health phase) on the SAME allgather: zero added
        collectives, zero added host fetches (the vector is host-side
        bookkeeping). Every host then holds the full ``[hosts, W]`` matrix
        per tick; the straggler attributor (telemetry/straggler.py) names
        the gating host + stage, and the view feeds the dashboard's
        ``Hosts`` tiles and the crash flight recorder."""
        import os

        import jax
        import numpy as np

        from . import faults as _faults
        from . import membership as _membership

        watch_s = float(
            os.environ.get(LOCKSTEP_TIMEOUT_ENV, "")
            or LOCKSTEP_TIMEOUT_DEFAULT_S
        )
        tele = _sideband.LockstepTelemetry(
            jax.process_index(), jax.process_count()
        )
        limit = getattr(self._stream, "row_bucket", 0)
        next_tick = time.monotonic() + self.batch_interval
        aborting = False
        tick_no = 0
        while not self._stop.is_set():
            if self.batch_interval > 0 and not aborting:
                delay = next_tick - time.monotonic()
                if delay > 0 and self._stop.wait(delay):
                    break
                next_tick += self.batch_interval
            elif limit and not aborting:
                # back-to-back fill gate, as in _scheduler_loop
                while (
                    self._queue.rows_queued < limit
                    and not self._source.exhausted
                    and not self._stop.is_set()
                ):
                    self._stop.wait(0.002)
            tick_no += 1
            # --chaos peer.kill/peer.pause: membership churn injectable
            # from the CLI like every other fault (streaming/faults.py) —
            # a hard exit or a long stall at a deterministic tick. The uid
            # selector (peer.kill:uid=N) targets the ORIGINAL process id,
            # stable across elastic epochs, so one shared --chaos spec
            # kills/pauses specific hosts (the lead included) from a
            # fleet-wide command line.
            _faults.lockstep_chaos(
                tick_no, self.batch_interval,
                uid=(
                    self.membership.uid if self.membership is not None
                    else jax.process_index()
                ),
            )
            local = self._drain(limit)
            rows = sum(getattr(s, "rows", 1) for s in local)
            more = (not self._source.exhausted) or self._queue.rows_queued > 0
            # the divergence sentinel's rollback count rides the SAME
            # cadence allgather (zero extra collectives): stats are
            # psum-global and deliveries deterministic, so every host
            # reaches the same verdict at the same step — the gathered
            # counts verify that instead of assuming it
            rollbacks = (
                int(self.rollback_count_fn())
                if self.rollback_count_fn is not None
                else 0
            )
            mem_cols = (
                self.membership.pre_tick()
                if self.membership is not None
                else np.zeros((_membership.WIDTH,), np.float64)
            )
            try:
                # the sideband AND the membership columns ride the SAME
                # allgather: flags widen from 4 ints to 4 + membership.WIDTH
                # + sideband.WIDTH floats (int flags are exact in float64)
                # — never a second collective
                flags = _watched_allgather(
                    np.concatenate([
                        np.array(
                            [rows > 0 and not aborting,
                             more and not aborting, aborting, rollbacks],
                            dtype=np.float64,
                        ),
                        mem_cols,
                        tele.vector(rollbacks=rollbacks),
                    ]),
                    watch_s,
                )
            except Exception:
                if self._elastic_recover(
                    local, "cadence allgather transport error"
                ):
                    tele = _sideband.LockstepTelemetry(
                        jax.process_index(), jax.process_count()
                    )
                    next_tick = time.monotonic() + self.batch_interval
                    continue
                log.critical(
                    "lockstep cadence allgather FAILED — a peer likely "
                    "died mid-run; aborting this host loudly (progress up "
                    "to the last checkpoint boundary is saved)",
                    exc_info=True,
                )
                _metrics.get_registry().counter(
                    "lockstep.watchdog_aborts"
                ).inc()
                self.request_abort("lockstep cadence allgather failed "
                                   "(peer death / transport error)")
                break
            tele.tick_done()  # waiting-in-collective ends here
            if flags is None:
                if self._elastic_recover(
                    local, f"no allgather progress in {watch_s:.0f}s"
                ):
                    tele = _sideband.LockstepTelemetry(
                        jax.process_index(), jax.process_count()
                    )
                    next_tick = time.monotonic() + self.batch_interval
                    continue
                log.critical(
                    "lockstep peer watchdog: the cadence allgather made no "
                    "progress in %.0fs — a peer is gone (hard kill or "
                    "network partition). Aborting this host loudly instead "
                    "of hanging in the collective; tune with %s (0 "
                    "disables).",
                    watch_s, LOCKSTEP_TIMEOUT_ENV,
                )
                _metrics.get_registry().counter(
                    "lockstep.watchdog_aborts"
                ).inc()
                _trace.get().instant("lockstep_watchdog", timeout_s=watch_s)
                self.request_abort(
                    f"lockstep peer watchdog: no allgather progress in "
                    f"{watch_s:.0f}s"
                )
                break
            # single-process gathers come back without the process axis
            flags = np.atleast_2d(np.asarray(flags))
            fi = flags[:, :4].astype(np.int64)  # the lockstep decisions
            mem_end = 4 + _membership.WIDTH
            if flags.shape[1] > mem_end:
                # per-host sideband matrix: straggler attribution + the
                # hosts[] view (pure host-side bookkeeping)
                tele.ingest(flags[:, mem_end:].astype(np.float64))
            if self.membership is not None:
                action = self.membership.ingest(
                    flags[:, 4:mem_end].astype(np.int64)
                )
                if action == "reform":
                    # a committed view change: this tick's rows go back to
                    # the queue, the group re-forms (members of the new
                    # view; a clean commit is loss-free — the lead
                    # checkpoints inside the transition), and the loop
                    # resumes on the new epoch
                    self._putback(local)
                    self.membership.execute_reform()
                    tele = _sideband.LockstepTelemetry(
                        jax.process_index(), jax.process_count()
                    )
                    next_tick = time.monotonic() + self.batch_interval
                    continue
                if action == "parked":
                    # evicted: leave the group, then poll for readmission
                    self._putback(local)
                    if self.membership.park():
                        tele = _sideband.LockstepTelemetry(
                            jax.process_index(), jax.process_count()
                        )
                        next_tick = time.monotonic() + self.batch_interval
                        continue
                    self.request_abort(
                        "elastic: evicted from the lockstep group and not "
                        "readmitted within the park window"
                    )
                    break
            if fi[:, 2].any():
                # this host (or a peer) aborted: everyone has now agreed on
                # it in the same tick, so everyone can stop dispatching
                if not aborting:
                    log.critical("a peer host aborted the lockstep run")
                self.request_abort(
                    "lockstep batch failure on this host"
                    if aborting else "a peer host aborted the lockstep run"
                )
                break
            if len(set(fi[:, 3].tolist())) > 1:
                # sentinel rollbacks must land on the SAME step on every
                # host (global stats + deterministic deliveries guarantee
                # it); disagreement means the hosts' model states have
                # diverged — abort the group rather than train past it
                log.critical(
                    "lockstep hosts disagree on sentinel rollback counts "
                    "%s — model states have diverged; aborting the group",
                    fi[:, 3].tolist(),
                )
                _metrics.get_registry().counter(
                    "lockstep.rollback_disagreements"
                ).inc()
                self.request_abort(
                    "lockstep hosts disagree on sentinel rollback counts "
                    f"{fi[:, 3].tolist()}"
                )
                break
            if fi[:, 0].any():
                # somebody has rows: EVERY host dispatches (local may be
                # empty — it pads to the pinned bucket)
                try:
                    self._run_batch_aligned(local, now_s())
                except Exception:
                    log.critical(
                        "lockstep batch failed after featurize; this host's "
                        "collective alignment is unknowable — aborting the "
                        "group (fail fast beats a distributed hang)",
                        exc_info=True,
                    )
                    aborting = True  # next tick broadcasts abort to peers
            if not aborting and not (fi[:, 0].any() or fi[:, 1].any()):
                break
        self._terminated.set()

    # -- lifecycle (ssc.start/awaitTermination, LinearRegression.scala:89-91) --
    def start(self, lockstep: bool = False) -> None:
        """``lockstep=True`` (multi-host runs) replaces the local scheduler
        with the collectively-agreed one (``_lockstep_loop``)."""
        if self._stream is None:
            raise ValueError("no stream registered")
        self._stop.clear()
        self._terminated.clear()
        self.failed = False
        self._source.start(self._queue.put)
        self._scheduler = threading.Thread(
            target=self._lockstep_loop if lockstep else self._scheduler_loop,
            name="twtml-batch-scheduler", daemon=True,
        )
        self._scheduler.start()

    def await_termination(self, timeout: float | None = None) -> bool:
        return self._terminated.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        # release a producer blocked on a full bounded queue FIRST, or the
        # source's join would time out against a wedged put()
        self._queue.close()
        if self._source is not None:
            self._source.stop()
        if self._scheduler is not None:
            self._scheduler.join(timeout=10)
        self._terminated.set()

    # -- deterministic replay mode (no wall clock) ---------------------------
    def run_to_completion(self, max_batch_size: int = 1024) -> int:
        """Drive the source synchronously: fill batches of up to
        ``max_batch_size`` tweets and process back-to-back. Returns number of
        batches run. Used by benchmarks and parity tests where the 5s cadence
        would only add idle time."""
        if self._stream is None:
            raise ValueError("no stream registered")
        self._source.start(self._queue.put)
        n0 = self.batches_processed
        pending: list[Status] = []
        while not self._stop.is_set():
            try:
                pending.append(self._queue.get(timeout=0.05))
                if len(pending) >= max_batch_size:
                    self._run_batch(pending, now_s())
                    pending = []
            except queue.Empty:
                if self._source.exhausted:
                    # re-drain: the source may have emitted between our
                    # timeout and the exhausted flag being set
                    pending.extend(self._drain())
                    break
        if pending and not self._stop.is_set():
            self._run_batch(pending, now_s())
        self._terminated.set()
        return self.batches_processed - n0
