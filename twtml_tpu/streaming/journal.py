"""Durable intake journal — crash-equals-clean replay recovery (ISSUE 19).

The reference delegates durability to Spark's receiver write-ahead log
(SURVEY §1); this repo's recovery paths historically *counted* rows lost
(sentinel skips, elastic in-flight discards, watchdog-abort restarts). The
journal closes that gap: every batch of raw rows is appended at the ONE
intake seam (post-parse, pre-featurize — ``FeatureStream._process`` /
``StreamingContext._run_batch_aligned``; lawcheck TW009 pins the seam) as a
CRC32-framed record with a monotonic lineage id, and every recovery path
re-ingests from the cursor its checkpoint stamped instead of skipping.

Design points, in the measured-law vocabulary of this repo:

- **Host-side only.** Appends are buffered file writes + one ``flush()``
  (no fsync — a SIGKILL'd process's flushed pages survive in the page
  cache; only a machine crash loses them, and the frame CRC turns that
  into a LOUD truncated tail, never silent corruption). Zero added device
  fetches, zero added collectives; multi-host replay rides the existing
  lockstep cadence (replayed rows re-enter the queue; dry hosts dispatch
  all-padding per the lockstep invariant).
- **Parity ground truth.** Object records serialize the ``Status`` fields
  the featurizer reads (recursively through ``retweeted_status``); block
  records preserve the ``ParsedBlock`` arrays bit-for-bit including the
  units dtype (uint8 ASCII wire vs uint16). Replayed rows re-enter the
  UNCHANGED featurize path, so replay is byte-identical to first ingest
  (differential-tested both paths, tests/test_journal.py).
- **Bounded disk.** Fixed-size segments rotate; a segment retires once a
  verified checkpoint covers every record in it (the cursor stamped into
  checkpoint meta by ``AppCheckpoint._save``), and ``--journalMaxMb`` is a
  hard ceiling enforced by dropping the OLDEST segments loudly (counted).
- **Replay suppression.** Replayed rows re-cross the intake seam; the
  journal suppresses re-appending exactly those rows (putback lands at the
  queue FRONT and the scheduler is single-threaded, so the first N rows
  through the seam after a replay ARE the N replayed rows) — without this
  a second rollback to the same checkpoint would double-train.

Frame format (little-endian):
``b"TWJL" | u32 payload_len | u32 crc32(payload) | payload`` where
``payload = u64 record_id | u64 rows_after | u8 kind | u32 nrows | body``.
``rows_after`` is the cumulative row count AFTER this record, so the tail
of the last segment alone recovers the journal position; a torn tail from
kill -9 mid-write fails the CRC (or length) check and is truncated loudly
(``journal.torn_tails``).
"""

from __future__ import annotations

import collections
import json
import operator
import os
import re
import struct
import threading
import zlib

from ..telemetry import metrics as _metrics
from ..utils import get_logger

log = get_logger("streaming.journal")

MAGIC = b"TWJL"
_FRAME = struct.Struct("<4sII")  # magic, payload_len, crc32(payload)
_RECORD = struct.Struct("<QQBI")  # record id, rows_after, kind, nrows
KIND_OBJ = 1
KIND_BLOCK = 2
# block body header: units dtype code (1 = uint8 ASCII wire, 2 = uint16)
_BLOCK = struct.Struct("<BQ")  # units dtype code, units length
_SEG_RE = re.compile(r"^seg-(\d{20})\.twj$")

# segments rotate at this size unless --journalMaxMb forces smaller (the
# retirement granularity: a segment only retires whole)
_SEGMENT_BYTES_DEFAULT = 16 * 1024 * 1024
_PAYLOAD_MAX = 1 << 31  # sanity bound when scanning possibly-garbage tails


# KIND_OBJ body: a JSON array of 9-element rows
# [text, retweet_count, followers_count, favourites_count, friends_count,
#  created_at_ms, lang, id, retweeted_status-row-or-null]. Rows, not
# key-value objects: the C-speed attrgetter + positional JSON encode is
# ~3.5x faster and ~4x smaller than per-status dicts, and the append sits
# on the hot intake seam (bench_journal.py gates the paired overhead).
_STATUS_FIELDS = operator.attrgetter(
    "text", "retweet_count", "followers_count", "favourites_count",
    "friends_count", "created_at_ms", "lang", "id", "retweeted_status",
)


def _status_to_row(s) -> tuple:
    row = _STATUS_FIELDS(s)
    if row[8] is None:
        return row
    return row[:8] + (_status_to_row(row[8]),)


def _row_to_status(v):
    from ..features.featurizer import Status

    rs = v[8]
    return Status(
        text=v[0], retweet_count=v[1], followers_count=v[2],
        favourites_count=v[3], friends_count=v[4],
        created_at_ms=v[5], lang=v[6], id=v[7],
        retweeted_status=_row_to_status(rs) if rs is not None else None,
    )


def _encode_block(block) -> bytes:
    import numpy as np

    units = np.ascontiguousarray(block.units)
    code = 1 if units.dtype == np.uint8 else 2
    return b"".join((
        _BLOCK.pack(code, units.size),
        np.ascontiguousarray(block.numeric, dtype=np.int64).tobytes(),
        units.tobytes(),
        np.ascontiguousarray(block.offsets, dtype=np.int64).tobytes(),
        np.ascontiguousarray(block.ascii, dtype=np.uint8).tobytes(),
    ))


def _decode_block(nrows: int, body: bytes):
    import numpy as np

    from ..features.blocks import ParsedBlock

    code, units_len = _BLOCK.unpack_from(body, 0)
    pos = _BLOCK.size
    numeric = np.frombuffer(
        body, np.int64, nrows * 5, pos).reshape(nrows, 5).copy()
    pos += nrows * 5 * 8
    units_dtype = np.uint8 if code == 1 else np.uint16
    units = np.frombuffer(body, units_dtype, units_len, pos).copy()
    pos += units_len * units_dtype().itemsize
    offsets = np.frombuffer(body, np.int64, nrows + 1, pos).copy()
    pos += (nrows + 1) * 8
    ascii_col = np.frombuffer(body, np.uint8, nrows, pos).copy()
    return ParsedBlock(numeric, units, offsets, ascii_col)


def _rows_of(items: list) -> int:
    # seam batches are homogeneous (Status objects OR parsed blocks, per
    # source kind — the same assumption ``_encode_items`` keys on). Probe
    # once: a per-item getattr-with-default over a Status batch pays a
    # swallowed AttributeError PER ROW, and this runs on the hot seam.
    if not items or getattr(items[0], "rows", None) is None:
        return len(items)
    return sum(item.rows for item in items)


class IntakeJournal:
    """Append-only, segment-rotated, CRC-framed row journal for one host.

    Thread-safety: appends happen on the scheduler thread only (the seam);
    replay/retire happen on the same thread (recovery runs inside the
    scheduler's delivery path or before the stream starts). The lock
    guards the cheap bookkeeping against telemetry readers.
    """

    def __init__(self, directory: str, max_mb: int = 512):
        self.directory = directory
        self.max_bytes = max(1, int(max_mb)) * 1024 * 1024
        self.segment_bytes = max(
            1024 * 1024, min(_SEGMENT_BYTES_DEFAULT, self.max_bytes // 4)
        )
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = None
        self._active_size = 0
        self._pending_replay = 0  # rows to suppress re-appending
        reg = _metrics.get_registry()
        self._appended = reg.counter("journal.appended_rows")
        self._replayed = reg.counter("journal.replayed_rows")
        self._torn = reg.counter("journal.torn_tails")
        self._dropped_segments = reg.counter("journal.segments_dropped")
        self._disk_gauge = reg.gauge("journal.disk_mb")
        self.next_id = 0
        self.rows_total = 0
        self._recover_tail()
        # dispatch-token cursor: the FetchPipeline dispatches AHEAD of
        # delivery, so the journal tail at save time can include records no
        # trained weight covers yet. Each seam crossing pushes its
        # post-append position; the delivery path pops in order and commits
        # a position only when its batch is FULLY admitted (note_delivered)
        # — the checkpoint stamps _committed, never the tail.
        self._inflight: "collections.deque" = collections.deque()
        self._delivery_pos: "tuple[int, int] | None" = None
        self._replay_draining = False
        self._committed = (self.next_id, self.rows_total)
        # incrementally-maintained disk total: the per-append gauge update
        # must not pay an os.listdir + stat sweep per batch on the one-core
        # host (recomputed exactly at open and on retire/drop)
        self._disk_bytes = self.disk_bytes()
        self._update_disk_gauge()

    # ---------------------------------------------------------------- disk

    def _segments(self) -> "list[tuple[int, str]]":
        """Sorted (first_record_id, path) of every on-disk segment."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.directory, name)))
        out.sort()
        return out

    def _seg_path(self, first_id: int) -> str:
        return os.path.join(self.directory, f"seg-{first_id:020d}.twj")

    def _scan_segment(self, path: str):
        """Yield (record_id, rows_after, kind, nrows, body, end_offset) for
        every CRC-valid frame, stopping at the first invalid one."""
        with open(path, "rb") as fh:
            data = fh.read()
        pos = 0
        while pos + _FRAME.size <= len(data):
            magic, plen, crc = _FRAME.unpack_from(data, pos)
            if magic != MAGIC or plen < _RECORD.size or plen > _PAYLOAD_MAX:
                return
            end = pos + _FRAME.size + plen
            if end > len(data):
                return  # torn mid-payload
            payload = data[pos + _FRAME.size: end]
            if zlib.crc32(payload) != crc:
                return  # torn mid-frame / bit rot
            rec_id, rows_after, kind, nrows = _RECORD.unpack_from(payload, 0)
            yield rec_id, rows_after, kind, nrows, payload[_RECORD.size:], end
            pos = end

    def _recover_tail(self) -> None:
        """Find the journal position (next_id, rows_total) from the newest
        segment holding a valid frame, truncating a torn tail LOUDLY."""
        segments = self._segments()
        for first_id, path in reversed(segments):
            size = os.path.getsize(path)
            valid_end = 0
            last = None
            for rec in self._scan_segment(path):
                last = rec
                valid_end = rec[5]
            if valid_end < size:
                self._torn.inc()
                log.error(
                    "journal: TORN TAIL in %s — %d byte(s) after the last "
                    "CRC-valid frame truncated (a kill mid-append); every "
                    "complete record before it survives", path,
                    size - valid_end,
                )
                with open(path, "r+b") as fh:
                    fh.truncate(valid_end)
            if last is not None:
                self.next_id = last[0] + 1
                self.rows_total = last[1]
                return
            if valid_end == 0 and first_id != 0:
                # fully-torn empty segment: position comes from the
                # previous segment's tail; drop the husk
                os.unlink(path)
                continue
            self.next_id = first_id
            return

    def _rotate_if_needed(self) -> None:
        if self._fh is not None and self._active_size < self.segment_bytes:
            return
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self._fh is None:
            path = self._seg_path(self.next_id)
            self._fh = open(path, "ab")
            self._active_size = self._fh.tell()

    def disk_bytes(self) -> int:
        return sum(os.path.getsize(p) for _, p in self._segments())

    def _update_disk_gauge(self) -> None:
        self._disk_gauge.set(round(self._disk_bytes / (1024 * 1024), 3))

    def _enforce_max_bytes(self) -> None:
        """--journalMaxMb is a HARD disk ceiling: drop the oldest whole
        segments (never the active one) until under it — loudly, because
        dropped records are rows a deep-enough rollback can no longer
        replay (the normal path retires them via checkpoint coverage
        first, so this only fires when the cadence lags the intake)."""
        if self._disk_bytes <= self.max_bytes:
            return
        for _, path in self._segments()[:-1]:
            if self._disk_bytes <= self.max_bytes:
                break
            size = os.path.getsize(path)
            os.unlink(path)
            self._disk_bytes -= size
            self._dropped_segments.inc()
            log.warning(
                "journal: disk ceiling --journalMaxMb exceeded — dropped "
                "oldest segment %s (%d bytes); rows in it are no longer "
                "replayable (counted in journal.segments_dropped)",
                os.path.basename(path), size,
            )

    # -------------------------------------------------------------- append

    def append(self, items: list) -> None:
        """Journal one seam batch (list of Status, or list of ParsedBlock).
        Empty batches (all-padding lockstep ticks, warmups) are skipped.
        Rows under replay suppression are NOT re-appended — their original
        records already cover them; a mixed batch (replayed head + fresh
        tail, one fill-gate drain) appends only the fresh tail."""
        rows = _rows_of(items)
        if rows == 0:
            return
        with self._lock:
            if self._pending_replay:
                if rows <= self._pending_replay:
                    self._pending_replay -= rows
                    return
                items = self._split_items(items, self._pending_replay)
                rows = _rows_of(items)
                self._pending_replay = 0
            kind, body, nrows = self._encode_items(items)
            payload = _RECORD.pack(
                self.next_id, self.rows_total + nrows, kind, nrows
            ) + body
            self._rotate_if_needed()
            self._fh.write(_FRAME.pack(MAGIC, len(payload), zlib.crc32(payload)))
            self._fh.write(payload)
            self._fh.flush()
            self._active_size += _FRAME.size + len(payload)
            self._disk_bytes += _FRAME.size + len(payload)
            self.next_id += 1
            self.rows_total += nrows
            self._appended.inc(nrows)
            if self._active_size >= self.segment_bytes:
                self._enforce_max_bytes()
            self._update_disk_gauge()

    # ------------------------------------------------- dispatch-token cursor

    def push_dispatch(self) -> None:
        """Called once per seam crossing, AFTER ``append`` (even for empty
        batches — all-padding lockstep ticks still dispatch a program).
        Pushes the post-append journal position, or ``None`` while replay
        suppression is still armed: a mid-replay batch's delivery must not
        move the committed cursor (its rows sit BELOW the replay cursor the
        current weights already lost). The batch that drains suppression to
        zero pushes the real tail — when IT delivers, every journaled row
        has been trained exactly once."""
        with self._lock:
            if self._pending_replay > 0:
                self._inflight.append(None)
            else:
                self._inflight.append((self.next_id, self.rows_total))

    def pop_dispatch(self) -> None:
        """Called once per delivered batch at the OUTERMOST delivery
        wrapper, before any admission filter can return early — deliveries
        arrive in dispatch order, so popping left re-pairs each delivery
        with its seam token even when an inner wrapper then skips it."""
        with self._lock:
            self._delivery_pos = (
                self._inflight.popleft() if self._inflight else None
            )

    def note_delivered(self) -> None:
        """Called from the INNERMOST delivery wrapper — only batches every
        admission filter accepted (no sentinel skip, no globally-empty
        no-op) reach it. Commits the popped token: records below it are now
        inside the trained weights, so a checkpoint may stamp it."""
        with self._lock:
            pos = self._delivery_pos
            self._delivery_pos = None
            if pos is not None and pos[0] >= self._committed[0]:
                self._committed = pos
                self._replay_draining = False

    def drop_newest(self) -> None:
        """A single-host empty batch was shed before dispatch: un-push its
        seam token (the scheduler is single-threaded, so the newest token
        is this batch's)."""
        with self._lock:
            if self._inflight:
                self._inflight.pop()

    def clear_inflight(self) -> None:
        """Elastic reform discards the fetch pipeline's in-flight
        deliveries wholesale (drain_discard) — their tokens would strand
        and desync every later pairing. Drop them; replay re-covers their
        rows."""
        with self._lock:
            self._inflight.clear()
            self._delivery_pos = None

    @property
    def save_allowed(self) -> bool:
        """False while a replay is still draining through the seam: a save
        now would stamp a cursor the weights do not cover yet (the final
        replayed batch has not delivered), and a crash after it would
        double-train on restore. Callers defer the save one boundary."""
        with self._lock:
            return not self._replay_draining

    @staticmethod
    def _split_items(items: list, skip_rows: int) -> list:
        """Drop the first ``skip_rows`` rows of a seam batch (the replayed
        head of a mixed drain)."""
        first = items[0]
        if getattr(first, "rows", None) is None:
            return items[skip_rows:]
        from ..features.blocks import merge_blocks, slice_block

        block = merge_blocks(list(items))
        return [slice_block(block, skip_rows, block.rows)]

    @staticmethod
    def _encode_items(items: list):
        first = items[0]
        if getattr(first, "rows", None) is not None:
            from ..features.blocks import merge_blocks

            block = merge_blocks(list(items))
            return KIND_BLOCK, _encode_block(block), block.rows
        body = json.dumps(
            [_status_to_row(s) for s in items],
            separators=(",", ":"), ensure_ascii=False,
        ).encode("utf-8")
        return KIND_OBJ, body, len(items)

    # -------------------------------------------------------------- replay

    def records_from(self, cursor: int):
        """Yield (record_id, items) for every record with id >= cursor, in
        id order. Items decode to exactly what crossed the seam: a list of
        Status for object records, a one-ParsedBlock list for block
        records. A CRC failure mid-history (bit rot in a non-tail segment)
        raises — silent partial replay would be silent data loss."""
        segments = self._segments()
        for i, (first_id, path) in enumerate(segments):
            next_first = (
                segments[i + 1][0] if i + 1 < len(segments) else self.next_id
            )
            if next_first <= cursor:
                continue
            expect = first_id
            for rec_id, _rows_after, kind, nrows, body, _end in (
                self._scan_segment(path)
            ):
                expect = rec_id + 1
                if rec_id < cursor:
                    continue
                if kind == KIND_BLOCK:
                    yield rec_id, [_decode_block(nrows, body)]
                else:
                    yield rec_id, [
                        _row_to_status(d)
                        for d in json.loads(body.decode("utf-8"))
                    ]
            if expect < next_first:
                raise RuntimeError(
                    f"journal segment {path} is corrupt mid-history "
                    f"(valid through record {expect - 1}, expected "
                    f"{next_first - 1}); replay would silently lose rows"
                )

    def replay_from(self, cursor: int) -> "tuple[list, int]":
        """Materialize every record with id >= cursor as queue items and
        ARM replay suppression for their rows (they will re-cross the
        seam). Returns (items, rows). Counted in journal.replayed_rows."""
        items: list = []
        for _rec_id, rec_items in self.records_from(cursor):
            items.extend(rec_items)
        rows = _rows_of(items)
        with self._lock:
            self._pending_replay += rows
            # the restored weights cover exactly [0, cursor): re-base the
            # committed position there and hold checkpoint saves until the
            # final replayed batch delivers (save_allowed)
            self._committed = (cursor, self.rows_total - rows)
            self._replay_draining = rows > 0
        if rows:
            self._replayed.inc(rows)
        return items, rows

    def cancel_pending_replay(self) -> int:
        """Rows of an earlier replay still awaiting their seam re-cross.
        A NEW replay supersedes them (its cursor sits at or below theirs,
        so its items re-cover the same rows): the caller must remove them
        from the queue front and this zeroes the suppression they armed —
        leaving both would putback the overlap twice and double-train."""
        with self._lock:
            stale = self._pending_replay
            self._pending_replay = 0
            return stale

    def rows_from(self, cursor: int) -> int:
        """Row count of records with id >= cursor (no decode of bodies
        beyond the record header — used for count-only assertions)."""
        rows = 0
        segments = self._segments()
        for i, (first_id, path) in enumerate(segments):
            next_first = (
                segments[i + 1][0] if i + 1 < len(segments) else self.next_id
            )
            if next_first <= cursor:
                continue
            for rec_id, _ra, _kind, nrows, _body, _end in (
                self._scan_segment(path)
            ):
                if rec_id >= cursor:
                    rows += nrows
        return rows

    # ---------------------------------------------------- checkpoint hooks

    def snapshot_for_checkpoint(self) -> dict:
        """The cursor stamp ``AppCheckpoint._save`` writes into verified
        checkpoint meta: every record with id < cursor is inside the saved
        state. This is the COMMITTED delivery position, not the journal
        tail — the fetch pipeline dispatches ahead of delivery, so at save
        time the tail can include in-flight records no trained weight
        covers yet; stamping those would lose them on the next rollback."""
        with self._lock:
            return {"cursor": self._committed[0], "rows": self._committed[1]}

    def retire_covered(self, cursor: int) -> int:
        """Unlink whole segments every record of which is < cursor — the
        oldest RETAINED verified checkpoint covers them, so no rollback
        can need them. Never touches the active (newest) segment."""
        segments = self._segments()
        retired = 0
        for i, (_first_id, path) in enumerate(segments[:-1]):
            if segments[i + 1][0] > cursor:
                break
            try:
                os.unlink(path)
                retired += 1
            except OSError:
                break
        if retired:
            log.info(
                "journal: retired %d segment(s) covered by verified "
                "checkpoint cursor %d", retired, cursor,
            )
            self._disk_bytes = self.disk_bytes()
            self._update_disk_gauge()
        return retired

    def reset(self) -> None:
        """Drop every journaled record (elastic rejoin: this host's
        pre-departure coverage was adopted by the survivors — replaying it
        would double-train). Record ids stay MONOTONIC: the next append
        opens a fresh segment at the current ``next_id``, so cursor
        comparisons against old checkpoint stamps remain ordered. Also
        clears any armed replay suppression — rows putback before a reset
        never re-cross the seam."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            for _first_id, path in self._segments():
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._active_size = 0
            self._pending_replay = 0
            self._inflight.clear()
            self._delivery_pos = None
            self._replay_draining = False
            self._committed = (self.next_id, self.rows_total)
            self._disk_bytes = self.disk_bytes()
            self._update_disk_gauge()
        log.warning(
            "journal: RESET — all segments dropped, next append starts a "
            "fresh segment at id %d", self.next_id,
        )

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# ------------------------------------------------------- module-global face
# (the blackbox/faults idiom: entry points install once, seams call the
# module-level hook, tests uninstall)

_JOURNAL: "IntakeJournal | None" = None


def install(directory: str, max_mb: int = 512) -> IntakeJournal:
    global _JOURNAL
    if _JOURNAL is not None:
        _JOURNAL.close()
    _JOURNAL = IntakeJournal(directory, max_mb=max_mb)
    log.info(
        "intake journal ON: %s (max %d MB, position id=%d rows=%d)",
        directory, max_mb, _JOURNAL.next_id, _JOURNAL.rows_total,
    )
    return _JOURNAL


def get() -> "IntakeJournal | None":
    return _JOURNAL


def uninstall() -> None:
    global _JOURNAL
    if _JOURNAL is not None:
        _JOURNAL.close()
    _JOURNAL = None


def record_intake(items: list) -> None:
    """THE intake seam hook (lawcheck TW009: only streaming/context.py may
    call this) — append one drained seam batch and push its dispatch token
    (the delivery path pops it to advance the committed cursor); no-op when
    the journal is off so ``--journal off`` is bit-exact pre-journal
    behavior."""
    if _JOURNAL is not None:
        _JOURNAL.append(items)
        _JOURNAL.push_dispatch()


def snapshot_for_checkpoint() -> "dict | None":
    return _JOURNAL.snapshot_for_checkpoint() if _JOURNAL is not None else None
