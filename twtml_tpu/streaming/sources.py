"""Stream sources — the receiver layer.

The reference's only receiver is ``TwitterUtils.createStream`` (a Twitter4j
long-lived socket pinned to one executor, LinearRegression.scala:44;
SURVEY.md §2.4.4 "receiver parallelism = 1"). Here a source is a small
supervised producer thread pushing parsed ``Status`` objects into the
micro-batcher's queue:

- ``ReplayFileSource`` — deterministic replay of a tweets .jsonl fixture
  (the BASELINE configs' replayed-tweet source), optionally rate-paced;
- ``SyntheticSource`` — parameterized synthetic tweet generator with a known
  ground-truth linear relationship (for parity tests and benchmarks);
- ``QueueSource`` — push-from-test source;
- the live ``TwitterSource`` lives in twitter.py (gated on credentials).

Supervision: a crashed producer thread is restarted with exponential backoff
(``max_restarts``), the upgrade over Spark's receiver defaults the survey
calls for (SURVEY.md §5.3).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Callable, Iterator

from ..features.featurizer import Status
from ..utils import get_logger

log = get_logger("streaming.sources")


class Source:
    """Base: override ``produce`` (a generator of Status) — the harness turns
    it into a supervised thread feeding ``emit``."""

    name = "source"

    def __init__(self, max_restarts: int = 3, restart_backoff: float = 1.0):
        self._emit: Callable[[Status], None] | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._exhausted = threading.Event()
        self.max_restarts = max_restarts
        self.restart_backoff = restart_backoff

    def produce(self) -> Iterator[Status]:  # pragma: no cover - abstract
        raise NotImplementedError

    def start(self, emit: Callable[[Status], None]) -> None:
        self._emit = emit
        self._stop.clear()
        self._exhausted.clear()
        self._thread = threading.Thread(
            target=self._run_supervised, name=f"twtml-source-{self.name}", daemon=True
        )
        self._thread.start()

    def _run_supervised(self) -> None:
        restarts = 0
        while not self._stop.is_set():
            try:
                for status in self.produce():
                    if self._stop.is_set():
                        return
                    self._emit(status)
                self._exhausted.set()
                return  # clean end of stream
            except Exception:
                restarts += 1
                if restarts > self.max_restarts:
                    log.exception("source %s died permanently", self.name)
                    self._exhausted.set()
                    return
                # cap the exponent too: restarts can reach the millions in
                # unbounded chaos runs and 2**n overflows float conversion
                backoff = min(
                    self.restart_backoff * (2 ** min(restarts - 1, 12)), 30.0
                )
                log.exception(
                    "source %s crashed; restart %d/%d in %.1fs",
                    self.name, restarts, self.max_restarts, backoff,
                )
                if self._stop.wait(backoff):
                    return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def exhausted(self) -> bool:
        return self._exhausted.is_set()


class ReplayFileSource(Source):
    """Replay a .jsonl file of tweet objects. ``speed`` = 0 replays as fast
    as possible; otherwise tweets are paced at ``speed`` × realtime using the
    inter-tweet gaps in their timestamps (missing timestamps → 10ms gap)."""

    name = "replay"

    def __init__(self, path: str, speed: float = 0.0, loop: bool = False, **kw):
        super().__init__(**kw)
        self.path = path
        self.speed = speed
        self.loop = loop

    def produce(self) -> Iterator[Status]:
        while True:
            prev_ms: int | None = None
            with open(self.path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    status = Status.from_json(json.loads(line))
                    if self.speed > 0:
                        gap_ms = 10.0
                        if prev_ms and status.created_at_ms > prev_ms:
                            gap_ms = status.created_at_ms - prev_ms
                        prev_ms = status.created_at_ms or prev_ms
                        if self._stop.wait(gap_ms / 1000.0 / self.speed):
                            return
                    yield status
            if not self.loop:
                return


class SyntheticSource(Source):
    """Generate tweets whose retweet counts follow a known linear function of
    the features — gives analytically checkable RMSE curves (SURVEY.md §7
    stage 3). ``rate`` = tweets/sec (0 = unpaced), ``total`` = stop after n."""

    name = "synthetic"

    _WORDS = (
        "tpu stream learn fast jax pallas shard mesh grad psum tweet viral "
        "scale batch online model predict train news data"
    ).split()

    def __init__(self, total: int = 0, rate: float = 0.0, seed: int = 0, **kw):
        super().__init__(**kw)
        self.total = total
        self.rate = rate
        self.seed = seed

    def produce(self) -> Iterator[Status]:
        import numpy as np

        rng = np.random.default_rng(self.seed)
        count = 0
        while self.total <= 0 or count < self.total:
            n_words = int(rng.integers(3, 9))
            words = rng.choice(self._WORDS, size=n_words)
            text = " ".join(words)
            followers = int(rng.integers(100, 2_000_000))
            # ground truth: label correlates with followers + text length
            label = int(
                np.clip(100 + followers * 4e-4 + len(text) * 2 + rng.normal(0, 20),
                        100, 1000)
            )
            original = Status(
                text=text,
                retweet_count=label,
                followers_count=followers,
                favourites_count=int(rng.integers(0, 50_000)),
                friends_count=int(rng.integers(0, 10_000)),
                created_at_ms=int(time.time() * 1000) - int(rng.integers(0, 86_400_000)),
            )
            yield Status(text="RT " + text, retweeted_status=original)
            count += 1
            if self.rate > 0 and self._stop.wait(1.0 / self.rate):
                return


class MultiSource(Source):
    """Sharded receiver fan-in: run N inner sources concurrently into one
    stream. The reference is hard-wired to a single Twitter4j receiver
    (SURVEY.md §2.4.4 "receiver parallelism = 1"); this is the single-host
    version of the N-way sharded stream in BASELINE config #5 (multi-host
    sharding lives in parallel/distributed.py)."""

    name = "multi"

    def __init__(self, sources: list[Source], **kw):
        super().__init__(**kw)
        self.sources = sources

    def start(self, emit) -> None:
        self._emit = emit
        self._stop.clear()
        self._exhausted.clear()
        for src in self.sources:
            src.start(emit)
        # watcher thread flips exhausted when every shard is done
        self._thread = threading.Thread(
            target=self._watch, name="twtml-source-multi", daemon=True
        )
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.is_set():
            if all(s.exhausted for s in self.sources):
                self._exhausted.set()
                return
            if self._stop.wait(0.05):
                return

    def stop(self) -> None:
        for src in self.sources:
            src.stop()
        super().stop()

    def produce(self):  # pragma: no cover - inner sources produce directly
        return iter(())


class QueueSource(Source):
    """Test source: push Status objects from the test thread."""

    name = "queue"

    def __init__(self, **kw):
        super().__init__(**kw)
        self._q: "queue.Queue[Status | None]" = queue.Queue()

    def push(self, status: Status) -> None:
        self._q.put(status)

    def close(self) -> None:
        self._q.put(None)

    def produce(self) -> Iterator[Status]:
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return  # interruptible without close()
                continue
            if item is None:
                return
            yield item
