"""Stream sources — the receiver layer.

The reference's only receiver is ``TwitterUtils.createStream`` (a Twitter4j
long-lived socket pinned to one executor, LinearRegression.scala:44;
SURVEY.md §2.4.4 "receiver parallelism = 1"). Here a source is a small
supervised producer thread pushing parsed ``Status`` objects into the
micro-batcher's queue:

- ``ReplayFileSource`` — deterministic replay of a tweets .jsonl fixture
  (the BASELINE configs' replayed-tweet source), optionally rate-paced;
- ``SyntheticSource`` — parameterized synthetic tweet generator with a known
  ground-truth linear relationship (for parity tests and benchmarks);
- ``QueueSource`` — push-from-test source;
- the live ``TwitterSource`` lives in twitter.py (gated on credentials).

Supervision: a crashed producer thread is restarted with exponential backoff
(``max_restarts``), the upgrade over Spark's receiver defaults the survey
calls for (SURVEY.md §5.3).
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
from typing import Callable, Iterator

from ..features.featurizer import Status
from ..telemetry import sideband as _sideband
from ..utils import get_logger

log = get_logger("streaming.sources")

# lazily-bound faults module (faults.py imports Source from here, so a
# module-scope import back would be circular); cached so the per-emit hot
# path pays one global read + one is-None check when chaos is off
_faults_mod = None


def _burst_extra() -> int:
    global _faults_mod
    if _faults_mod is None:
        from . import faults

        _faults_mod = faults
    if _faults_mod._CHAOS is None:
        return 0
    return _faults_mod.burst_extra()


def _record_event_lag(created_at_ms: int) -> None:
    """Ingest event-time lag gauge (ISSUE 16 satellite): arrival wall-clock
    minus the tweet's own ``created_at_ms`` — the gap the paced replay
    branch has computed (and dropped) since r1. Lazy metrics import keeps
    the sources module import-light; the clock goes through the
    ``TWTML_NOW_MS`` seam so tests pin it."""
    if created_at_ms <= 0:
        return
    from ..telemetry import metrics as _metrics
    from ..utils.clock import now_ms

    _metrics.get_registry().gauge("ingest.event_time_lag_ms").set(
        float(max(0, now_ms() - int(created_at_ms)))
    )


def _maybe_corrupt(data: bytes) -> bytes:
    global _faults_mod
    if _faults_mod is None:
        from . import faults

        _faults_mod = faults
    if _faults_mod._CHAOS is None:
        return data
    return _faults_mod.maybe_corrupt_block(data)


def _count_parse_drops(n: int) -> None:
    """Malformed/garbage lines the block parser skipped — registry state
    (``ingest.rows_dropped_parse``) instead of log-only, so wire damage is
    visible on /api/metrics next to the other ingest-loss counters."""
    from ..telemetry import metrics as _metrics

    _metrics.get_registry().counter("ingest.rows_dropped_parse").inc(n)


class Source:
    """Base: override ``produce`` (a generator of Status) — the harness turns
    it into a supervised thread feeding ``emit``."""

    name = "source"

    def __init__(self, max_restarts: int = 3, restart_backoff: float = 1.0):
        self._emit: Callable[[Status], None] | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._exhausted = threading.Event()
        self.max_restarts = max_restarts
        self.restart_backoff = restart_backoff

    def produce(self) -> Iterator[Status]:  # pragma: no cover - abstract
        raise NotImplementedError

    def start(self, emit: Callable[[Status], None]) -> None:
        self._emit = emit
        self._stop.clear()
        self._exhausted.clear()
        self._thread = threading.Thread(
            target=self._run_supervised, name=f"twtml-source-{self.name}", daemon=True
        )
        self._thread.start()

    def _run_supervised(self) -> None:
        restarts = 0
        while not self._stop.is_set():
            emitted_any = False
            try:
                for status in self.produce():
                    if self._stop.is_set():
                        return
                    self._emit(status)
                    emitted_any = True
                    extra = _burst_extra()  # --chaos source.burst rate spike
                    for _ in range(extra):
                        self._emit(status)
                self._exhausted.set()
                return  # clean end of stream
            except Exception as exc:
                if emitted_any:
                    # a run that produced data was a healthy (re)connection:
                    # max_restarts bounds CONSECUTIVE failures and the
                    # backoff ladder restarts from the bottom (the Twitter
                    # reconnect rules reset on successful connection; a
                    # receiver that streamed for hours must not die on its
                    # 4th lifetime disconnect)
                    restarts = 0
                restarts += 1
                if restarts > self.max_restarts:
                    log.exception("source %s died permanently", self.name)
                    self._exhausted.set()
                    return
                backoff = self._backoff(exc, restarts)
                # a flapping stream must be VISIBLE, not a silent retry
                # loop buried in logs: restarts are first-class registry
                # state (total + per source name) for /api/metrics
                from ..telemetry import metrics as _metrics

                reg = _metrics.get_registry()
                reg.counter("source.restarts").inc()
                reg.counter(f"source.{self.name}.restarts").inc()
                log.exception(
                    "source %s crashed; restart %d/%d in %.1fs",
                    self.name, restarts, self.max_restarts, backoff,
                )
                if self._stop.wait(backoff):
                    return

    # restart backoff ceiling; class-level so a subclass (or a test) can
    # tighten it without re-deriving the ladder
    BACKOFF_CAP_S = 30.0

    def _backoff(self, exc: Exception, restarts: int) -> float:
        """Seconds to sleep before restart ``restarts`` (1-based) after
        ``exc``. Default: exponential from ``restart_backoff``, JITTERED
        (uniform in [0.5x, 1x] of the ladder value — N restarting shards
        of one dead upstream must not reconnect in phase) and capped at
        ``BACKOFF_CAP_S``. Subclasses override for error-class-aware
        policies (the live Twitter receiver distinguishes rate-limit vs
        HTTP vs transport failures, twitter.py). The exponent is capped
        too: restarts can reach the millions in unbounded chaos runs and
        2**n overflows."""
        del exc
        base = min(
            self.restart_backoff * (2 ** min(restarts - 1, 12)),
            self.BACKOFF_CAP_S,
        )
        return base * (0.5 + 0.5 * random.random())

    # how long stop() waits for the producer thread; class-level so tests
    # can shrink it without monkeypatching join()
    JOIN_TIMEOUT_S = 5.0

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=self.JOIN_TIMEOUT_S)
            if thread.is_alive():
                # a silent timed-out join here used to make stuck shutdowns
                # invisible — name the wedged thread so the operator can
                # see WHICH producer is blocked (daemon threads die with
                # the process, so shutdown still completes)
                log.warning(
                    "source %s did not stop: producer thread %r still "
                    "running %.1fs after the stop request (wedged in a "
                    "blocking call?); proceeding with shutdown",
                    self.name, thread.name, self.JOIN_TIMEOUT_S,
                )

    @property
    def exhausted(self) -> bool:
        return self._exhausted.is_set()


class ReplayFileSource(Source):
    """Replay a .jsonl file of tweet objects. ``speed`` = 0 replays as fast
    as possible; otherwise tweets are paced at ``speed`` × realtime using the
    inter-tweet gaps in their timestamps (missing timestamps → 10ms gap)."""

    name = "replay"

    def __init__(self, path: str, speed: float = 0.0, loop: bool = False, **kw):
        super().__init__(**kw)
        self.path = path
        self.speed = speed
        self.loop = loop

    # tweets per aggregated ``parse`` span: per-line spans would swamp the
    # trace at the ~1.2M tweets/s parse rate, so the source thread batches
    # its parse time into one complete event per this many lines
    PARSE_SPAN_EVERY = 1024

    def produce(self) -> Iterator[Status]:
        from ..telemetry import trace as _trace

        while True:
            prev_ms: int | None = None
            tr = _trace.get()
            t_parse, n_parse = 0.0, 0
            n_lag = 0
            with open(self.path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    if tr.enabled:
                        t0 = time.perf_counter()
                        status = Status.from_json(json.loads(line))
                        t_parse += time.perf_counter() - t0
                        n_parse += 1
                        if n_parse >= self.PARSE_SPAN_EVERY:
                            tr.complete(
                                "parse", time.perf_counter() - t_parse,
                                t_parse, items=n_parse,
                            )
                            _sideband.record_stage("parse", t_parse)
                            t_parse, n_parse = 0.0, 0
                    else:
                        # per-line timing stays trace-gated: two clock
                        # reads per tweet would tax the ~1.2M tweets/s
                        # parser — the sideband's parse attribution on
                        # OBJECT ingest therefore needs --trace (the block
                        # parser below always contributes)
                        status = Status.from_json(json.loads(line))
                    if self.speed > 0:
                        gap_ms = 10.0
                        if prev_ms and status.created_at_ms > prev_ms:
                            gap_ms = status.created_at_ms - prev_ms
                        prev_ms = status.created_at_ms or prev_ms
                        # paced replays record per status: the pacing wait
                        # dwarfs one clock read
                        _record_event_lag(status.created_at_ms)
                        if self._stop.wait(gap_ms / 1000.0 / self.speed):
                            return
                    else:
                        # as-fast-as-possible replays sample every
                        # PARSE_SPAN_EVERY statuses — per-tweet clock reads
                        # would tax the ~1.2M tweets/s parser
                        n_lag += 1
                        if n_lag >= self.PARSE_SPAN_EVERY:
                            n_lag = 0
                            _record_event_lag(status.created_at_ms)
                    yield status
            if n_parse:
                tr.complete(
                    "parse", time.perf_counter() - t_parse, t_parse,
                    items=n_parse,
                )
                _sideband.record_stage("parse", t_parse)
            if not self.loop:
                return


class BlockParserMixin:
    """The bytes → ParsedBlock stage both block sources share (file replay
    below and the live ``BlockTwitterSource``, twitter.py): the native C
    parser with the pure-Python ground-truth fallback. Consumers set
    ``begin``/``end`` (the retweet-interval filter) and ``copy``.

    ``wire=True`` parses through the zero-copy wire emitter
    (``native.parse_tweet_block_wire``): one C pass from raw bytes to the
    ragged wire's unit representation — blocks then carry **uint8** units
    whenever every kept row is ASCII (the narrow wire dtype, decided by the
    parser's per-row metadata, so the featurizer's downcast pass
    disappears). Kept rows and every emitted array are byte-identical to
    the legacy parser (tests/test_blockwire.py); only bad-line COUNTS may
    undercount on keyless malformed lines (the prescreen skips whole-line
    validation there — native/tweetjson.cpp banner). Degrades in order:
    wire emitter → legacy C parser (stale library without the symbol,
    counted + warned once by features/native.py) → Python ground truth."""

    begin: int
    end: int
    copy: bool = True
    wire: bool = False

    def parse_buffer(self, data: bytes) -> "list":
        """Parse a whole byte buffer (must end at a line boundary) into
        ParsedBlocks, looping over the parser's capacity bounds so an
        oversized buffer cannot drop its tail."""
        blocks = []
        while data.strip():
            if not data.endswith(b"\n"):
                data += b"\n"
            block, rest = self._parse(data)
            if block is not None and block.rows:
                blocks.append(block)
            if not rest or rest == data:
                break
            data = rest
        return blocks

    def _parse(self, data: bytes):
        """(ParsedBlock | None, carry bytes) for one buffered chunk —
        instrumented as the ``parse`` stage (one real span per chunk; the
        block path parses MB-scale buffers, so per-chunk spans are cheap).
        The parse rate and byte volume are first-class registry state
        (``ingest.parse_tweets_per_s`` gauge, ``ingest.parse_bytes``
        counter): the bottleneck ladder's parse rung is readable off
        /api/metrics without a bench run, and the PR 5 straggler ladder's
        ``parse`` attribution keeps riding the same ``record_stage`` clock
        whichever parser (wire / legacy / Python) ran."""
        from ..telemetry import metrics as _metrics
        from ..telemetry import trace as _trace

        tr = _trace.get()
        t0 = time.perf_counter()
        if not tr.enabled:
            out = self._parse_impl(data)
            dt = time.perf_counter() - t0
            _sideband.record_stage("parse", dt)
            self._record_parse_metrics(_metrics, len(data), out[0], dt)
            return out
        with tr.span("parse", bytes=len(data)) as sp:
            block, rest = self._parse_impl(data)
            if block is not None:
                sp.add(rows=int(block.rows))
        dt = time.perf_counter() - t0
        _sideband.record_stage("parse", dt)
        self._record_parse_metrics(_metrics, len(data), block, dt)
        return block, rest

    @staticmethod
    def _record_parse_metrics(_metrics, nbytes: int, block, dt: float) -> None:
        reg = _metrics.get_registry()
        reg.counter("ingest.parse_bytes").inc(nbytes)
        if block is not None and dt > 0:
            reg.gauge("ingest.parse_tweets_per_s").set(
                round(block.rows / dt, 1)
            )

    def _parse_impl(self, data: bytes):
        from ..features import native
        from ..features.blocks import ParsedBlock

        # --chaos source.garbage: damage the buffer BEFORE the parser —
        # the skip-and-count contract below is what absorbs it
        data = _maybe_corrupt(data)
        out = (
            native.parse_tweet_block_wire(
                data, self.begin, self.end, copy=self.copy
            )
            if self.wire
            else None
        )
        if out is None:
            out = native.parse_tweet_block(
                data, self.begin, self.end, copy=self.copy
            )
        if out is not None:
            numeric, units, offsets, ascii_flags, consumed, bad = out
            if bad:
                log.warning("block parser skipped %d malformed lines", bad)
                _count_parse_drops(bad)
            return (
                ParsedBlock(numeric, units, offsets, ascii_flags),
                data[consumed:],
            )
        return self._py_parse(data)

    def _py_parse(self, data: bytes):
        """Ground-truth fallback: json.loads + Status per line."""
        import numpy as np

        from ..features.blocks import ParsedBlock
        from ..features.native import MAX_TEXT_UNITS, encode_texts

        # the C parser's documented wire-format bound (kMaxTextUnits,
        # native/tweetjson.cpp): a retweeted status with ANY "text"/
        # "full_text" occurrence (duplicate JSON keys included — the C
        # scanner caps every occurrence, while plain dicts keep only the
        # last) over the unit bound makes the whole line a counted bad
        # line — pinned here so both block paths agree on adversarial
        # input (the object-ingest Status path keeps such rows)
        class _Obj(dict):
            oversized = False  # a DIRECT text/full_text value too big
            rt_oversized = False  # ANY retweeted_status value oversized

        def _pairs_hook(pairs):
            d = _Obj(pairs)
            for k, v in pairs:
                if (
                    k in ("text", "full_text")
                    and isinstance(v, str)
                    and len(v.encode("utf-16-le", "surrogatepass")) // 2
                    > MAX_TEXT_UNITS
                ):
                    d.oversized = True
                # any-occurrence, not last-wins: the C scanner caps EVERY
                # duplicate retweeted_status occurrence, while dict(pairs)
                # would keep only the last
                if k == "retweeted_status" and getattr(v, "oversized", False):
                    d.rt_oversized = True
            return d

        def oversized(obj) -> bool:
            # only the retweeted_status object's DIRECT text fields are
            # bounded (the C parser skips all other strings uncapped, incl.
            # anything nested inside the retweeted status)
            return getattr(obj, "rt_oversized", False)

        nl = data.rfind(b"\n")
        if nl < 0:
            return None, data
        lines, carry = data[:nl].split(b"\n"), data[nl + 1 :]
        numerics, texts = [], []
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                obj = json.loads(ln, object_pairs_hook=_pairs_hook)
                if oversized(obj):
                    raise ValueError("text exceeds the wire-format unit bound")
                status = Status.from_json(obj)
            except (ValueError, AttributeError, TypeError):
                # same contract as the C parser: malformed lines (including
                # valid JSON that isn't a tweet object) skip, never crash
                log.warning("block parser skipped a malformed line")
                _count_parse_drops(1)
                continue
            o = status.retweeted_status
            if o is not None and self.begin <= o.retweet_count <= self.end:
                numerics.append((
                    o.retweet_count, o.followers_count, o.favourites_count,
                    o.friends_count, o.created_at_ms,
                ))
                texts.append(o.text)
        units, offsets = encode_texts(texts)
        block = ParsedBlock(
            np.array(numerics, np.int64).reshape(len(texts), 5),
            units[: offsets[-1]],
            offsets,
            np.array([1 if t.isascii() else 0 for t in texts], np.uint8),
        )
        return block, carry




class BlockReplayFileSource(BlockParserMixin, Source):
    """Replay a .jsonl file through the NATIVE data loader: each yielded
    item is a columnar ParsedBlock (features/blocks.py) straight from the C
    parser (native/tweetjson.cpp), with the isRetweet + retweet-interval
    filter already applied — no per-tweet Python objects at all, an order of
    magnitude faster than the json.loads path. Pure-Python fallback (the
    ground truth) kicks in when the C library is unavailable. As-fast-as-
    possible only (block ingest has no per-tweet pacing).

    ``shard_index``/``shard_count`` select a BYTE-RANGE shard of the file
    (r5, multi-host block ingest — the Spark analog of shipping
    deserialization to every executor, SURVEY.md §2.4 L0): the file's byte
    span splits into ``shard_count`` equal ranges, and a line belongs to
    the shard containing its FIRST byte, so each host reads AND parses only
    ~1/N of the file with no coordination and no line read twice. Unlike
    ``ShardedSource``'s per-item round robin this keeps each shard's IO
    sequential — the point of the block loader."""

    name = "replay-block"

    def __init__(
        self,
        path: str,
        num_retweet_begin: int = 100,
        num_retweet_end: int = 1000,
        block_bytes: int = 1 << 20,
        loop: bool = False,
        copy: bool = True,
        wire: bool = False,
        shard_index: int = 0,
        shard_count: int = 1,
        **kw,
    ):
        super().__init__(**kw)
        self.path = path
        self.begin = num_retweet_begin
        self.end = num_retweet_end
        self.block_bytes = block_bytes
        self.loop = loop
        # copy=False: blocks are views into per-call buffers (see
        # native.parse_tweet_block) — for consumers that featurize each
        # block promptly (the bench pipeline), not for accumulation
        self.copy = copy
        # wire=True: parse through the zero-copy wire emitter (see
        # BlockParserMixin) — apps enable it for the ragged device wire
        self.wire = wire
        if not 0 <= shard_index < max(1, shard_count):
            raise ValueError(
                f"shard index {shard_index} out of range for {shard_count}"
            )
        self.shard_index = shard_index
        self.shard_count = max(1, shard_count)

    def _shard_range(self) -> "tuple[int, int]":
        """This shard's [start, stop) byte range, line-aligned: a raw range
        boundary is pushed forward past the line containing it (unless it
        already sits at a line start), identically for this shard's stop
        and the next shard's start — so every line lands in exactly one
        shard."""
        import os

        size = os.path.getsize(self.path)
        if self.shard_count <= 1:
            return 0, size

        def boundary(pos: int) -> int:
            if pos <= 0 or pos >= size:
                return min(max(pos, 0), size)
            with open(self.path, "rb") as fh:
                fh.seek(pos - 1)
                if fh.read(1) != b"\n":
                    fh.readline()  # mid-line: the line belongs to the left
                return fh.tell()

        lo = size * self.shard_index // self.shard_count
        hi = size * (self.shard_index + 1) // self.shard_count
        return boundary(lo), boundary(hi)

    def produce(self) -> Iterator:
        while True:
            lo, hi = self._shard_range()
            with open(self.path, "rb") as fh:
                fh.seek(lo)
                remaining = hi - lo
                carry = b""
                while True:
                    chunk = (
                        fh.read(min(self.block_bytes, remaining))
                        if remaining > 0
                        else b""
                    )
                    remaining -= len(chunk)
                    if not chunk:
                        # drain the tail through the shared capacity-bound
                        # loop (parse_buffer — one copy of the stall guard
                        # for both block sources, r5 review)
                        for block in self.parse_buffer(carry):
                            yield block
                        break
                    block, carry = self._parse(carry + chunk)
                    if block is not None and block.rows:
                        yield block
            if not self.loop:
                return



class SyntheticSource(Source):
    """Generate tweets whose retweet counts follow a known linear function of
    the features — gives analytically checkable RMSE curves (SURVEY.md §7
    stage 3). ``rate`` = tweets/sec (0 = unpaced), ``total`` = stop after n."""

    name = "synthetic"

    _WORDS = (
        "tpu stream learn fast jax pallas shard mesh grad psum tweet viral "
        "scale batch online model predict train news data"
    ).split()

    def __init__(
        self,
        total: int = 0,
        rate: float = 0.0,
        seed: int = 0,
        base_ms: int | None = None,
        **kw,
    ):
        super().__init__(**kw)
        self.total = total
        self.rate = rate
        self.seed = seed
        # created_at base: wall clock by default; pin it for BIT-exact
        # reproducibility across processes/runs (multi-host assembly
        # requires every process to build identical global batches)
        self.base_ms = base_ms

    def produce(self) -> Iterator[Status]:
        import numpy as np

        rng = np.random.default_rng(self.seed)
        count = 0
        while self.total <= 0 or count < self.total:
            n_words = int(rng.integers(3, 9))
            words = rng.choice(self._WORDS, size=n_words)
            text = " ".join(words)
            followers = int(rng.integers(100, 2_000_000))
            # ground truth: label correlates with followers + text length
            label = int(
                np.clip(100 + followers * 4e-4 + len(text) * 2 + rng.normal(0, 20),
                        100, 1000)
            )
            original = Status(
                text=text,
                retweet_count=label,
                followers_count=followers,
                favourites_count=int(rng.integers(0, 50_000)),
                friends_count=int(rng.integers(0, 10_000)),
                created_at_ms=(
                    self.base_ms
                    if self.base_ms is not None
                    else int(time.time() * 1000)
                ) - int(rng.integers(0, 86_400_000)),
            )
            yield Status(text="RT " + text, retweeted_status=original)
            count += 1
            if self.rate > 0 and self._stop.wait(1.0 / self.rate):
                return


class ShardedSource(Source):
    """Take items ``index``-of-``count`` (round-robin) from an inner source —
    the per-host intake shard of a multi-host run (SURVEY.md §7 stage 5):
    every host opens the same replay/synthetic source and keeps 1/N of the
    stream, so the union of all hosts' shards is exactly the single-host
    stream and host i's k-th batch interleaves with the others into the
    same global row set a single-host run would batch.

    **Elastic rebalance (r16)**: the shard key is a RESIDUE SET, not a
    single index — ``count`` stays the LAUNCH process count forever, and a
    departed host's residue classes are adopted by survivors
    (``adopt_residues``), so coverage going forward is exact without
    re-keying anyone's position. ``produce`` reads the set per item, so an
    adoption takes effect mid-stream from each adopter's current position
    (items of the departed residues between the death and the takeover are
    the counted loss window — streaming/membership.py)."""

    name = "shard"

    def __init__(self, inner: Source, index: int, count: int, **kw):
        super().__init__(**kw)
        if not 0 <= index < count:
            raise ValueError(f"shard index {index} out of range for {count}")
        self.inner = inner
        self.index = index
        self.count = count
        self.residues = {index}

    def adopt_residues(self, residues) -> None:
        """Take over the given residue classes (a departed host's shard),
        effective from this source's current stream position."""
        self.residues |= {int(r) % self.count for r in residues}
        log.warning(
            "intake shard rebalanced: now serving residues %s of %d",
            sorted(self.residues), self.count,
        )

    def release_residues(self, residues) -> None:
        """Hand residue classes back (a rejoined live host resumes its own
        slice); this host's original residue is never released."""
        self.residues -= {int(r) % self.count for r in residues}
        self.residues.add(self.index)

    def produce(self) -> Iterator[Status]:
        for i, status in enumerate(self.inner.produce()):
            if i % self.count in self.residues:
                yield status


class IdShardedSource(Source):
    """Take rows whose status id ≡ ``index`` (mod ``count``) from an inner
    source — the LIVE-stream intake shard of a multi-host run (BASELINE
    config #5's "4-way sharded stream" for ``--source twitter``, r5). A
    live sample stream has no deterministic item order across separately
    opened connections, so the round-robin ``ShardedSource`` cannot shard
    it; the tweet's snowflake id CAN — every host opens its own connection
    (duplicated ingress, tens of KB/s at real stream rates) and keeps a
    disjoint id-residue slice, so the union of all hosts' rows is the
    stream and no tweet trains twice. Rows without an id (id 0 — not
    produced by the real API) land on shard 0."""

    name = "idshard"

    def __init__(self, inner: Source, index: int, count: int, **kw):
        # supervision runs on THIS wrapper, so the inner source's restart
        # budget/backoff must carry through (the live receiver retries
        # indefinitely — twitter.py)
        kw.setdefault("max_restarts", inner.max_restarts)
        kw.setdefault("restart_backoff", inner.restart_backoff)
        super().__init__(**kw)
        if not 0 <= index < count:
            raise ValueError(f"shard index {index} out of range for {count}")
        self.inner = inner
        self.index = index
        self.count = count
        self.residues = {index}

    def adopt_residues(self, residues) -> None:
        """Elastic rebalance (r16): serve a departed host's id-residue
        classes too, from this connection, going forward — exact coverage
        for a live stream (ids are position-free, unlike replay indexes)."""
        self.residues |= {int(r) % self.count for r in residues}
        log.warning(
            "live intake shard rebalanced: now serving id residues %s of %d",
            sorted(self.residues), self.count,
        )

    def release_residues(self, residues) -> None:
        self.residues -= {int(r) % self.count for r in residues}
        self.residues.add(self.index)

    def produce(self) -> Iterator[Status]:
        for status in self.inner.produce():
            if status.id % self.count in self.residues:
                yield status

    def _backoff(self, exc: Exception, restarts: int) -> float:
        # delegate to the live source's error-class-aware ladder (420 vs
        # HTTP vs transport) — the supervisor wraps THIS source, so the
        # inner one's policy must carry through
        return self.inner._backoff(exc, restarts)


class SkipRowsSource(Source):
    """Discard the first ``skip_rows`` ROWS of an inner source — the boot
    half of journal replay recovery (apps/common.journal_boot_replay): on a
    restart, every row this host ever journaled is either inside the
    restored checkpoint (id < cursor) or re-enqueued from the journal
    (id >= cursor), so the deterministic source must fast-forward past ALL
    of them instead of re-producing from the top (which is what a bare
    checkpoint-restart of a replay file does — re-trained rows). A
    ParsedBlock item counts its rows and is SPLIT at the skip boundary
    (features/blocks.slice_block), matching the journal's row arithmetic.

    Wraps the OUTERMOST (post-shard) source: the journal records this
    host's post-shard stream, so the skip count is in the same row space.
    Exposes ``.inner`` for the elastic residue-rebalance chain walk."""

    name = "skiprows"

    def __init__(self, inner: Source, skip_rows: int, **kw):
        kw.setdefault("max_restarts", inner.max_restarts)
        kw.setdefault("restart_backoff", inner.restart_backoff)
        super().__init__(**kw)
        self.inner = inner
        self.skip_rows = int(skip_rows)

    def produce(self) -> Iterator[Status]:
        # a supervised restart re-enters produce(): the inner replay source
        # re-produces from its top, so the skip re-applies from its top too
        remaining = self.skip_rows
        for item in self.inner.produce():
            if remaining > 0:
                take = getattr(item, "rows", None)
                if take is None:
                    remaining -= 1
                    continue
                if take <= remaining:
                    remaining -= take
                    continue
                from ..features.blocks import slice_block

                cut = remaining
                remaining = 0
                item = slice_block(item, cut, take)
                if item.rows == 0:
                    continue
            yield item

    def _backoff(self, exc: Exception, restarts: int) -> float:
        return self.inner._backoff(exc, restarts)


class MultiSource(Source):
    """Sharded receiver fan-in: run N inner sources concurrently into one
    stream. The reference is hard-wired to a single Twitter4j receiver
    (SURVEY.md §2.4.4 "receiver parallelism = 1"); this is the single-host
    version of the N-way sharded stream in BASELINE config #5 (multi-host
    sharding lives in parallel/distributed.py)."""

    name = "multi"

    def __init__(self, sources: list[Source], **kw):
        super().__init__(**kw)
        self.sources = sources

    def start(self, emit) -> None:
        self._emit = emit
        self._stop.clear()
        self._exhausted.clear()
        for src in self.sources:
            src.start(emit)
        # watcher thread flips exhausted when every shard is done
        self._thread = threading.Thread(
            target=self._watch, name="twtml-source-multi", daemon=True
        )
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.is_set():
            if all(s.exhausted for s in self.sources):
                self._exhausted.set()
                return
            if self._stop.wait(0.05):
                return

    def stop(self) -> None:
        for src in self.sources:
            src.stop()
        super().stop()

    def produce(self):  # pragma: no cover - inner sources produce directly
        return iter(())


class QueueSource(Source):
    """Test source: push Status objects from the test thread."""

    name = "queue"

    def __init__(self, **kw):
        super().__init__(**kw)
        self._q: "queue.Queue[Status | None]" = queue.Queue()

    def push(self, status: Status) -> None:
        self._q.put(status)

    def close(self) -> None:
        self._q.put(None)

    def produce(self) -> Iterator[Status]:
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return  # interruptible without close()
                continue
            if item is None:
                return
            yield item
