"""Live Twitter stream source (reference: TwitterUtils.createStream +
Twitter4j receiver, LinearRegression.scala:44; OAuth creds from system
properties, ConfArguments.scala:58-76).

The receiver connects to the streaming endpoint with the four
``twitter4j.oauth.*`` credentials from the process property table, parses one
JSON tweet per line, and yields ``Status`` objects. Connection handling is
delegated to the ``Source`` supervision harness (sources.py): drops and HTTP
errors raise, the supervisor restarts with exponential backoff — the upgrade
over the reference, whose receiver restart policy was whatever Spark defaults
did (SURVEY.md §5.3).

This build environment has zero egress, so the live path is exercised in
tests through ``connect_fn`` injection (a fake endpoint yielding canned
lines); against the real service, OAuth1 request signing applies
(oauth_sign_fn hook — Twitter's v1.1 streaming API contract).
"""

from __future__ import annotations

import json
from typing import Callable, Iterator

from .. import config as _config
from ..features.featurizer import Status
from ..utils import get_logger
from .sources import Source

log = get_logger("streaming.twitter")

STREAM_URL = "https://stream.twitter.com/1.1/statuses/sample.json"

OAUTH_KEYS = (
    "twitter4j.oauth.consumerKey",
    "twitter4j.oauth.consumerSecret",
    "twitter4j.oauth.accessToken",
    "twitter4j.oauth.accessTokenSecret",
)


class TwitterSource(Source):
    """Supervised live-stream receiver. ``connect_fn()`` must return an
    iterator of raw JSON lines; the default implementation opens the sample
    stream with the configured credentials."""

    name = "twitter"

    def __init__(
        self,
        credentials: dict[str, str],
        connect_fn: Callable[[], Iterator[str]] | None = None,
        url: str = STREAM_URL,
        **kw,
    ):
        super().__init__(**kw)
        self.credentials = credentials
        self.url = url
        self._connect_fn = connect_fn

    @classmethod
    def from_properties(cls, **kw) -> "TwitterSource":
        """Build from the twitter4j.oauth.* property table (the reference's
        system-property contract)."""
        creds = {k: _config.get_property(k, "") for k in OAUTH_KEYS}
        missing = [k for k, v in creds.items() if not v]
        if missing:
            raise SystemExit(
                "Twitter credentials missing: "
                + ", ".join(missing)
                + " — pass --consumerKey/--consumerSecret/--accessToken/"
                "--accessTokenSecret or set them in application.conf"
            )
        return cls(creds, **kw)

    def _connect(self) -> Iterator[str]:
        if self._connect_fn is not None:
            return self._connect_fn()
        raise ConnectionError(
            "live Twitter streaming requires network egress and OAuth1 request "
            "signing; provide connect_fn or run with --source replay/synthetic"
        )

    def produce(self) -> Iterator[Status]:
        for line in self._connect():
            line = line.strip()
            if not line:
                continue  # keep-alive newline
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                log.debug("skipping non-JSON stream line")
                continue
            if "text" not in obj:
                continue  # delete/limit notices
            yield Status.from_json(obj)
