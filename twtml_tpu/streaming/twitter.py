"""Live Twitter stream source (reference: TwitterUtils.createStream +
Twitter4j receiver, LinearRegression.scala:44; OAuth creds from system
properties, ConfArguments.scala:58-76).

The receiver connects to the streaming endpoint with the four
``twitter4j.oauth.*`` credentials from the process property table, parses one
JSON tweet per line, and yields ``Status`` objects. Connection handling is
delegated to the ``Source`` supervision harness (sources.py): drops and HTTP
errors raise, the supervisor restarts with exponential backoff — the upgrade
over the reference, whose receiver restart policy was whatever Spark defaults
did (SURVEY.md §5.3).

The full protocol path is native and stdlib-only: OAuth1 HMAC-SHA1 request
signing (oauth1.py, pinned by published test vectors) over a chunked
streaming HTTP/1.1 client (httpstream.py). The build environment has zero
egress, so tests drive the identical code path against a LOCAL server
speaking the v1.1 stream protocol — delimited JSON, keep-alive blank lines,
mid-stream disconnects, HTTP 420 — in tests/test_twitter_live.py;
``connect_fn`` injection remains for protocol-free unit tests.

Reconnect policy mirrors the Twitter streaming rules the Twitter4j client
implements: transport errors retry fast-linear (250 ms, +250 ms per attempt,
cap 16 s); HTTP errors retry exponentially from 5 s (cap 320 s); HTTP 420
rate limiting retries exponentially from a full minute.
"""

from __future__ import annotations

import json
from typing import Callable, Iterator

from .. import config as _config
from ..features.featurizer import Status
from ..utils import get_logger
from .httpstream import RateLimitedError, StreamHTTPError, open_stream
from .oauth1 import authorization_header
from .sources import BlockParserMixin, Source

log = get_logger("streaming.twitter")

STREAM_URL = "https://stream.twitter.com/1.1/statuses/sample.json"

OAUTH_KEYS = (
    "twitter4j.oauth.consumerKey",
    "twitter4j.oauth.consumerSecret",
    "twitter4j.oauth.accessToken",
    "twitter4j.oauth.accessTokenSecret",
)


class TwitterSource(Source):
    """Supervised live-stream receiver. ``connect_fn()`` must return an
    iterator of raw JSON lines; the default implementation opens the sample
    stream with the configured credentials."""

    name = "twitter"

    def __init__(
        self,
        credentials: dict[str, str],
        connect_fn: Callable[[], Iterator[str]] | None = None,
        url: str = STREAM_URL,
        **kw,
    ):
        # a live receiver retries indefinitely (Twitter4j semantics): the
        # backoff ladder, not a restart cap, is the pressure valve — the
        # generic max_restarts=3 would kill the stream on a 2s network blip
        # (three consecutive failed connects emit nothing, so the
        # healthy-production reset never fires)
        kw.setdefault("max_restarts", 1_000_000)
        super().__init__(**kw)
        self.credentials = credentials
        self.url = url
        self._connect_fn = connect_fn

    @classmethod
    def from_properties(cls, **kw) -> "TwitterSource":
        """Build from the twitter4j.oauth.* property table (the reference's
        system-property contract)."""
        creds = {k: _config.get_property(k, "") for k in OAUTH_KEYS}
        missing = [k for k, v in creds.items() if not v]
        if missing:
            raise SystemExit(
                "Twitter credentials missing: "
                + ", ".join(missing)
                + " — pass --consumerKey/--consumerSecret/--accessToken/"
                "--accessTokenSecret or set them in application.conf"
            )
        # twitter4j's own endpoint-override property, honored here so the
        # full CLI path can be driven against a local v1.1-protocol server
        kw.setdefault(
            "url", _config.get_property("twitter4j.streamBaseURL", STREAM_URL)
        )
        return cls(creds, **kw)

    def _connect(self) -> Iterator[str]:
        if self._connect_fn is not None:
            return self._connect_fn()
        auth = authorization_header(
            "GET",
            self.url,
            consumer_key=self.credentials.get("twitter4j.oauth.consumerKey", ""),
            consumer_secret=self.credentials.get(
                "twitter4j.oauth.consumerSecret", ""
            ),
            token=self.credentials.get("twitter4j.oauth.accessToken", ""),
            token_secret=self.credentials.get(
                "twitter4j.oauth.accessTokenSecret", ""
            ),
        )
        # 90s read timeout: the stream keep-alives every ~30s, so a silent
        # socket for 90s is a stall and must raise into the supervisor
        return open_stream(self.url, headers={"Authorization": auth})

    def _backoff(self, exc: Exception, restarts: int) -> float:
        """Twitter streaming reconnect rules (what Twitter4j implements for
        the reference): 420 → exponential from 60 s; other HTTP errors →
        exponential from 5 s capped 320 s; transport errors → linear 250 ms
        steps capped 16 s."""
        n = min(restarts - 1, 16)
        if isinstance(exc, RateLimitedError):
            return min(60.0 * (2**n), 960.0)
        if isinstance(exc, StreamHTTPError):
            return min(5.0 * (2**n), 320.0)
        return min(0.25 * restarts, 16.0)

    def produce(self) -> Iterator[Status]:
        for line in self._connect():
            line = line.strip()
            if not line:
                continue  # keep-alive newline
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                log.debug("skipping non-JSON stream line")
                continue
            if "text" not in obj:
                continue  # delete/limit notices
            yield Status.from_json(obj)
        if self._connect_fn is None:
            # a live stream never ends on purpose: a server-side close is a
            # disconnect, and the supervisor must reconnect (Twitter4j does
            # the same). Injected test streams DO end meaningfully.
            raise ConnectionError("stream ended by server; reconnecting")


class BlockTwitterSource(BlockParserMixin, TwitterSource):
    """The live stream through the NATIVE block parser (r5 — live
    ``--ingest block``): raw JSON lines from the connection accumulate into
    byte blocks and each block goes through ``native.parse_tweet_block``
    (the same C scanner + filter as replay block ingest, differential-
    tested against the Status path), yielding columnar ParsedBlocks with no
    per-tweet Python objects between the socket and the featurizer.

    Why: config #2's full-app rate sat ~2× below its protocol stage —
    the gap is exactly the per-line ``json.loads`` + Status assembly on the
    one usable core, which the replay path already deletes with this
    parser (~14× — BENCHMARKS.md component rates).

    Flush policy: a block parses when the buffer reaches ``block_bytes``
    OR the first stream activity (line or keep-alive) at least
    ``flush_seconds`` after its first buffered line. The clock is checked
    when the blocking line iterator yields, so on a QUIET stream the real
    latency bound is the protocol's ~30 s keep-alive cadence, not
    ``flush_seconds`` — acceptable for this source's regimes (the real
    sample stream runs 50–100 tweets/s and measurement streams far
    faster; a latency-critical quiet stream should keep object ingest)."""

    name = "twitter-block"

    def __init__(
        self,
        credentials: "dict[str, str]",
        num_retweet_begin: int = 100,
        num_retweet_end: int = 1000,
        block_bytes: int = 1 << 18,
        flush_seconds: float = 0.5,
        wire: bool = False,
        **kw,
    ):
        super().__init__(credentials, **kw)
        self.begin = num_retweet_begin
        self.end = num_retweet_end
        self.block_bytes = block_bytes
        self.flush_seconds = flush_seconds
        # zero-copy wire emitter (BlockParserMixin) — same opt-in as the
        # replay block source
        self.wire = wire

    @classmethod
    def from_properties(cls, **kw) -> "BlockTwitterSource":
        src = TwitterSource.from_properties()
        kw.setdefault("url", src.url)
        return cls(src.credentials, **kw)

    def _parse_block(self, data: bytes):
        """bytes → merged ParsedBlock | None (the shared C-parser stage
        with its Python ground-truth fallback, sources.BlockParserMixin)."""
        from ..features.blocks import merge_blocks

        blocks = self.parse_buffer(data)
        if not blocks:
            return None
        merged = merge_blocks(blocks)
        return merged if merged.rows else None

    def produce(self) -> "Iterator":
        import time as _time

        buf: list[bytes] = []
        nbytes = 0
        first_t = 0.0
        for line in self._connect():
            line = line.strip()
            now = _time.monotonic()
            if line:
                if not buf:
                    first_t = now
                raw = line.encode("utf-8") + b"\n"
                buf.append(raw)
                nbytes += len(raw)
            if buf and (
                nbytes >= self.block_bytes
                or now - first_t >= self.flush_seconds
            ):
                block = self._parse_block(b"".join(buf))
                buf, nbytes = [], 0
                if block is not None:
                    yield block
        if buf:
            block = self._parse_block(b"".join(buf))
            if block is not None:
                yield block
        if self._connect_fn is None:
            raise ConnectionError("stream ended by server; reconnecting")
