"""Lead-coordinated membership epochs for the lockstep fleet
(``--elastic on``) — the control plane that lets the group SHRINK when a
host dies or persistently gates, REBALANCE intake across survivors, and
ADMIT a recovered host back, all without a restart.

The in-band protocol rides the EXISTING per-tick cadence allgather (the
PR 1/5 law: zero new collectives per healthy tick — counted by the same
acceptance test style the sideband used): the flag row widens by the
``WIDTH`` membership columns below. A membership change is a two-tick
dance over those columns:

    tick T:   the lead's row carries (proposed epoch P, proposed member
              mask) — every member sees it in the same gather;
    tick T+1: every member's row acks P; the commit condition (lead
              proposal P present AND every member row acks P) is evaluated
              on the SAME gathered matrix by every host, so the commit is
              simultaneous and deterministic. Members of the new view
              re-form at epoch P's derived port; members outside it park.

A HARD-dead peer can never ack in-band — the gather itself wedges. That
path goes out-of-band through the lead's beacon (parallel/elastic.py): the
survivors' lockstep watchdogs fire, each survivor reports "wedged" to the
beacon, the lead takes (reporters ∪ itself) ∩ members as the survivor set,
publishes the rescue plan, and everyone re-forms. The beacon is host-side
TCP — never a collective, never touched on a healthy tick.

When the DEAD peer is the lead itself (r20: the last single point of
failure), the wedge reports hit connection-refused — the beacon died with
its owner — and the survivors run ``_elect``: rank-staggered candidates
race ``take_over_beacon()`` (the OS bind on the beacon port is the
election lock), the lowest live uid wins, adopts ``lead_uid``, and runs
the SAME lead-rescue machinery; losers re-report to the winner's beacon.
Leadership is sticky from then on — a rejoining ex-lead parks, adopts the
winner from the beacon's responses, and trains as a follower.

Columns (float64-exact ints, appended between the 4 lockstep flags and the
telemetry sideband):

    0 epoch       this host's current epoch
    1 uid         this host's ORIGINAL process id (stable across epochs)
    2 view        bitmask of member uids in this host's current epoch
    3 prop_epoch  lead: proposed next epoch (0 = no proposal)
    4 prop_view   lead: proposed member mask (may include a joiner's uid)
    5 ack         newest proposed epoch this host agrees to (0 = none)
    6 reason      proposal reason bit (1 evict, 2 join, 3 rescue-rejoin)
    7 spare       reserved (future agreed values may ride here)

No module-scope jax import (the lockstep conftest law); time.monotonic
only (pure intervals — the TWTML_NOW_MS seam is for feature clocks).
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..utils import get_logger

log = get_logger("streaming.membership")

FIELDS = (
    "epoch", "uid", "view", "prop_epoch", "prop_view", "ack", "reason",
    "spare",
)
WIDTH = len(FIELDS)

REASON_EVICT = 1
REASON_JOIN = 2
REASON_RESCUE = 3
REASON_NAMES = {REASON_EVICT: "evict", REASON_JOIN: "join",
                REASON_RESCUE: "rescue"}

# rescue: how long the lead collects wedge reports after its own watchdog
# fires before declaring the silent members dead (alive survivors' watchdogs
# fire within ~one timeout of each other, so a small multiple suffices)
RESCUE_GRACE_ENV = "TWTML_ELASTIC_RESCUE_GRACE_S"
RESCUE_GRACE_DEFAULT_S = 5.0

# park: how long an evicted/wedged-out host polls for (re)admission before
# giving up and aborting
PARK_TIMEOUT_ENV = "TWTML_ELASTIC_PARK_TIMEOUT_S"
PARK_TIMEOUT_DEFAULT_S = 120.0

# a join request is only proposable while fresh: the joiner re-sends it on
# every poll, so a stale one means the joiner is gone — admitting it would
# wedge the new epoch's formation on a no-show
JOIN_FRESH_S = 5.0

# election: successor candidates rank by uid and each waits rank × stagger
# (probing the orphaned beacon port throughout) before attempting the bind,
# so the lowest LIVE uid wins the race deterministically; the OS bind is
# the lock, the stagger only prevents needless bind contention
ELECT_STAGGER_ENV = "TWTML_ELASTIC_ELECT_STAGGER_S"
ELECT_STAGGER_DEFAULT_S = 0.3

# bounded election rounds: each retry means the beacon owner died again
# mid-election; three corpses in one rescue window is a lost fleet
ELECT_MAX_ROUNDS = 3


def election_candidates(members, lead_uid) -> "list[int]":
    """Successor order for a dead lead: every OTHER member of the committed
    view, ascending uid — rank in this list is the election stagger slot.
    Pure (unit-tested directly); dead candidates simply never bind."""
    return sorted(int(u) for u in members if int(u) != int(lead_uid))


class MembershipPlane:
    """One per lockstep run on every host. The scheduler drives it:
    ``pre_tick`` → columns for the flag row; ``ingest`` on the gathered
    block → an action string; ``execute_reform``/``park``/``rescue`` for
    the transitions. The heavy lifting (pipeline drain, group teardown and
    re-formation, model rebuild, checkpoint broadcast, intake rebalance)
    lives in two injected callbacks:

    - ``detach_cb()``             — drain in-flight work, abandon the epoch
    - ``attach_cb(plan, reason)`` — form the new epoch and rebuild on it

    so this module stays a pure protocol machine (unit-testable without
    jax or sockets: tests/test_membership.py drives ingest matrices
    directly)."""

    def __init__(self, runtime, detach_cb, attach_cb,
                 evict_ticks: int = 0, evict_skew_ms: float = 250.0,
                 rejoin: bool = True):
        self.runtime = runtime
        self._detach = detach_cb
        self._attach = attach_cb
        self.evict_ticks = int(evict_ticks)
        self.evict_skew_ms = float(evict_skew_ms)
        self.rejoin = bool(rejoin)
        self.uid = runtime.uid
        # active proposal state (lead publishes; everyone tracks)
        self._prop_epoch = 0
        self._prop_view = 0
        self._prop_reason = 0
        self._ack = 0
        # straggler eviction scoring (lead)
        self._gating_uid = -1
        self._gating_ticks = 0
        self._plan: "dict | None" = None
        from ..telemetry import metrics as _metrics

        reg = _metrics.get_registry()
        self._epoch_gauge = reg.gauge("elastic.epoch")
        self._hosts_gauge = reg.gauge("elastic.live_hosts")
        self._lead_gauge = reg.gauge("elastic.lead_uid")
        self._reforms = reg.counter("elastic.reforms")
        self._departed = reg.counter("elastic.hosts_departed")
        self._rejoined = reg.counter("elastic.hosts_rejoined")
        self._rows_lost = reg.counter("elastic.rows_lost_estimate")
        self._elections = reg.counter("elastic.elections")
        self._handoffs = reg.counter("elastic.lead_handoffs")
        self._epoch_gauge.set(runtime.epoch)
        self._hosts_gauge.set(len(runtime.members))
        self._lead_gauge.set(self.lead_uid)

    # -- helpers -------------------------------------------------------------

    @property
    def epoch(self) -> int:
        return self.runtime.epoch

    @property
    def members(self) -> "list[int]":
        return self.runtime.members

    @property
    def lead_uid(self) -> int:
        return int(getattr(self.runtime, "lead_uid", 0))

    @property
    def lead(self) -> bool:
        """Whether THIS host is the current lead. Dynamic — leadership is
        sticky on ``runtime.lead_uid`` and only moves at an election (a
        rejoining ex-lead stays a follower even though its uid is again
        the minimum)."""
        return self.uid == self.lead_uid

    def _adopt_lead(self, resp: "dict | None", how: str) -> None:
        """Adopt the lead uid a beacon response advertises. Any response
        from a HANDED-OFF beacon carries the winner's uid; counting the
        change here gives every survivor/rejoiner its own handoff record
        (``elastic.lead_handoffs``)."""
        if not resp or "lead_uid" not in resp:
            return
        new = int(resp["lead_uid"])
        if new == self.lead_uid:
            return
        old = self.lead_uid
        self.runtime.set_lead(new)
        self._lead_gauge.set(new)
        self._handoffs.inc()
        log.warning(
            "elastic: lead handoff observed (%s): uid %d -> uid %d",
            how, old, new,
        )

    @staticmethod
    def _grace_s() -> float:
        return float(
            os.environ.get(RESCUE_GRACE_ENV, "") or RESCUE_GRACE_DEFAULT_S
        )

    @staticmethod
    def _park_timeout_s() -> float:
        return float(
            os.environ.get(PARK_TIMEOUT_ENV, "") or PARK_TIMEOUT_DEFAULT_S
        )

    # -- per-tick protocol ---------------------------------------------------

    def pre_tick(self) -> np.ndarray:
        """Build this host's membership columns; on the lead, first fold in
        out-of-band join requests and the straggler-eviction score to maybe
        open a proposal. Pure host-side work."""
        from ..parallel.elastic import mask_from_uids

        if self.lead and self._prop_epoch == 0:
            self._maybe_propose()
        return np.array([
            self.epoch, self.uid, mask_from_uids(self.members),
            self._prop_epoch, self._prop_view, self._ack,
            self._prop_reason, 0,
        ], dtype=np.float64)

    def _maybe_propose(self) -> None:
        from ..parallel.elastic import mask_from_uids

        beacon = self.runtime.beacon
        joiners = []
        if beacon is not None and self.rejoin:
            joiners = [
                u for u in beacon.fresh_joins(JOIN_FRESH_S)
                if u not in self.members
            ]
        evictee = self._straggler_evictee()
        if not joiners and evictee < 0:
            return
        view = set(self.members) | set(joiners)
        reason = REASON_JOIN if joiners else REASON_EVICT
        if evictee >= 0:
            view.discard(evictee)
        self._prop_epoch = self.epoch + 1
        self._prop_view = mask_from_uids(sorted(view))
        self._prop_reason = reason
        self._ack = self._prop_epoch  # the lead trivially acks its own
        from ..telemetry import blackbox as _blackbox

        _blackbox.record(
            "membership_propose", epoch=self._prop_epoch,
            members=sorted(view), reason=REASON_NAMES.get(reason, "?"),
        )
        log.warning(
            "elastic: proposing epoch %d with members %s (%s%s)",
            self._prop_epoch, sorted(view), REASON_NAMES.get(reason, "?"),
            f", evicting uid {evictee}" if evictee >= 0 else "",
        )

    def _straggler_evictee(self) -> int:
        """Uid to evict when the sideband's straggler attribution has named
        the same non-lead host for ``evict_ticks`` consecutive ticks with
        skew over the threshold; -1 otherwise. Off when evict_ticks == 0."""
        if not self.evict_ticks or len(self.members) <= 1:
            return -1
        from ..telemetry import sideband as _sideband

        view = _sideband.last_hosts()
        if not view:
            return -1
        pid = view.get("straggler", -1)
        skew = float(view.get("skew_ms", 0.0))
        uid = (
            self.members[pid]
            if 0 <= pid < len(self.members) else -1
        )
        if uid < 0 or uid == self.lead_uid or skew < self.evict_skew_ms:
            # the CURRENT lead is never evicted (it owns the beacon and
            # the checkpoint truth — losing it is an election, not an
            # eviction); reset the run
            self._gating_uid, self._gating_ticks = -1, 0
            return -1
        if uid == self._gating_uid:
            self._gating_ticks += 1
        else:
            self._gating_uid, self._gating_ticks = uid, 1
        if self._gating_ticks >= self.evict_ticks:
            return uid
        return -1

    def ingest(self, mem: np.ndarray) -> str:
        """Consume the gathered ``[hosts, WIDTH]`` membership block (row
        order = current epoch pid order). Returns one of:

        - ``""``       — steady state, run the tick normally
        - ``"reform"`` — a view change committed and this host is in the
                         new view: call ``execute_reform`` now
        - ``"parked"`` — a view change committed WITHOUT this host (it was
                         evicted): call ``park`` now
        """
        rows = np.asarray(mem, dtype=np.int64)
        # proposals are read from the LEAD's row. After an election the
        # lead is no longer pid 0 whenever a lower uid rejoined (the
        # ex-lead comes back as a follower but still sorts first), so the
        # row index follows lead_uid through the member list.
        lead_pid = (
            self.members.index(self.lead_uid)
            if self.lead_uid in self.members else 0
        )
        lead_prop = int(rows[lead_pid, FIELDS.index("prop_epoch")])
        lead_view = int(rows[lead_pid, FIELDS.index("prop_view")])
        lead_reason = int(rows[lead_pid, FIELDS.index("reason")])
        if lead_prop > self.epoch:
            # record/refresh the proposal; ack it from the NEXT tick on
            self._prop_epoch = lead_prop
            self._prop_view = lead_view
            self._prop_reason = lead_reason
            if self._ack != lead_prop:
                self._ack = lead_prop
                if not self.lead:
                    log.info(
                        "elastic: acking proposed epoch %d (members %s)",
                        lead_prop, self._decode_view(lead_view),
                    )
                return ""  # commit needs every row's ack in ONE gather
        if lead_prop <= self.epoch or lead_prop == 0:
            return ""
        acks = rows[:, FIELDS.index("ack")]
        if not bool((acks == lead_prop).all()):
            return ""
        members = self._decode_view(lead_view)
        self._plan = {
            "epoch": lead_prop, "members": members,
            "reason": REASON_NAMES.get(lead_reason, "?"),
        }
        if self.uid in members:
            return "reform"
        return "parked"

    @staticmethod
    def _decode_view(mask: int) -> "list[int]":
        from ..parallel.elastic import uids_from_mask

        return uids_from_mask(mask)

    # -- transitions ---------------------------------------------------------

    def _clear_proposal(self) -> None:
        self._prop_epoch = 0
        self._prop_view = 0
        self._prop_reason = 0
        self._ack = 0

    def _count_departed(self, old_members, new_members) -> None:
        """Departed hosts' last-known queue depths (from the sideband's
        final healthy gather) become the counted row-loss estimate — the
        honest form of 'drained': their queued rows died with them, and
        their source shards' future rows are adopted by survivors."""
        departed = [u for u in old_members if u not in new_members]
        if not departed:
            return
        self._departed.inc(len(departed))
        from ..telemetry import sideband as _sideband

        view = _sideband.last_hosts()
        est = 0
        if view:
            by_pid = {h["host"]: h for h in view.get("hosts", [])}
            for u in departed:
                if u in old_members:
                    pid = old_members.index(u)
                    est += int(by_pid.get(pid, {}).get("queue_rows", 0))
        if est:
            self._rows_lost.inc(est)
        log.warning(
            "elastic: host(s) %s departed; ~%d queued row(s) lost with "
            "them (counted in elastic.rows_lost_estimate)", departed, est,
        )

    def _finish_transition(self, old_members, reason: str) -> None:
        self._reforms.inc()
        self._epoch_gauge.set(self.epoch)
        self._hosts_gauge.set(len(self.members))
        rejoined = [u for u in self.members if u not in old_members]
        if rejoined:
            self._rejoined.inc(len(rejoined))
        from ..telemetry import blackbox as _blackbox

        _blackbox.record(
            "membership_commit", epoch=self.epoch, members=self.members,
            reason=reason, departed=[
                u for u in old_members if u not in self.members
            ], rejoined=rejoined,
        )
        if self.runtime.beacon is not None:
            # the plan stays briefly for late pollers; the live state is
            # authoritative for hello
            self.runtime.beacon.publish("live", self.epoch, self.members)
            self.runtime.beacon.clear_wedges()
        self._clear_proposal()

    def execute_reform(self) -> None:
        """Run the committed plan on a member of the new view (clean
        commit path: every old member is alive and synchronized at this
        tick, so the lead may first snapshot a loss-free checkpoint inside
        ``detach_cb``)."""
        plan = self._plan
        assert plan is not None
        old = list(self.members)
        self._count_departed(old, plan["members"])
        if self.lead and self.runtime.beacon is not None:
            # publish BEFORE forming: a parked/fresh joiner polls this to
            # learn its admission, and formation blocks until it connects
            self.runtime.beacon.publish_plan(
                {"epoch": plan["epoch"], "members": plan["members"]}
            )
        self._detach(clean=True)
        self._attach(plan, plan.get("reason", "?"))
        self._finish_transition(old, plan.get("reason", "?"))
        self._plan = None

    def park(self) -> bool:
        """This host was evicted (clean commit without it) or woke up past
        a rescue it missed: leave the group, then poll the beacon for
        (re)admission until the park timeout. True → rejoined (the run
        continues); False → give up (the caller aborts)."""
        old = list(self.members)
        self._detach(clean=False)
        self._clear_proposal()
        if not self.rejoin:
            log.warning("elastic: parked with --elasticRejoin off; exiting")
            return False
        client = self.runtime.beacon_client()
        deadline = time.monotonic() + self._park_timeout_s()
        log.warning(
            "elastic: parked (uid %d); polling the beacon for readmission",
            self.uid,
        )
        while time.monotonic() < deadline:
            resp = client.request("join", self.uid)
            if resp is None:
                time.sleep(1.0)
                continue
            # a parked ex-lead learns its successor here — admission into
            # a post-election fleet is the demotion path (the beacon that
            # answers is the winner's)
            self._adopt_lead(resp, "parked")
            plan = (client.request("plan", self.uid) or {}).get("plan")
            if plan and self.uid in plan.get("members", []) and (
                plan["epoch"] > self.epoch
            ):
                self._adopt_lead(plan, "admission plan")
                plan = dict(plan, reason="rejoin")
                self._attach(plan, "rejoin")
                self._finish_transition(old, "rejoin")
                return True
            time.sleep(0.5)
        log.critical(
            "elastic: park timed out after %.0fs without readmission",
            self._park_timeout_s(),
        )
        return False

    def rescue(self, why: str) -> bool:
        """Out-of-band recovery after a wedged/failed cadence collective
        (a hard-dead peer). Lead: collect wedge reports for the grace
        window, shrink to the reporters ∪ itself, publish the plan, and
        re-form. Follower: report the wedge, then follow the lead's plan
        (or park if the plan excludes this host). True → the run continues
        on the new epoch; False → unrecoverable (the caller aborts)."""
        from ..telemetry import blackbox as _blackbox

        _blackbox.record(
            "membership_rescue", epoch=self.epoch, uid=self.uid, why=why,
        )
        if self.lead:
            return self._rescue_lead(why)
        return self._rescue_follower(why)

    def _rescue_lead(self, why: str, extra_grace_s: float = 0.0) -> bool:
        beacon = self.runtime.beacon
        if beacon is None:
            return False
        grace = self._grace_s() + float(extra_grace_s)
        log.critical(
            "elastic: lockstep wedged (%s); collecting survivor reports "
            "for %.1fs before shrinking", why, grace,
        )
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            time.sleep(0.2)
        survivors = sorted(
            ({self.uid} | set(beacon.wedge_reports(self.epoch)))
            & set(self.members)
        )
        if survivors == self.members:
            # everyone reported alive: the wedge was a transient (or the
            # watchdog was too tight) — re-form with the same view, which
            # also re-synchronizes state off the lead's checkpoint
            log.warning(
                "elastic: every member reported alive; re-forming the "
                "same view to clear the wedge"
            )
        old = list(self.members)
        plan = {
            "epoch": self.epoch + 1, "members": survivors,
            "reason": "rescue",
        }
        self._plan = plan
        self._count_departed(old, survivors)
        beacon.publish_plan(
            {"epoch": plan["epoch"], "members": plan["members"]}
        )
        self._detach(clean=False)
        self._attach(plan, "rescue")
        self._finish_transition(old, "rescue")
        self._plan = None
        return True

    def _rescue_follower(self, why: str, round_no: int = 0) -> bool:
        client = self.runtime.beacon_client()
        wedge_epoch = self.epoch
        resp = client.request("wedged", self.uid, epoch=wedge_epoch)
        if resp is None:
            # the beacon is ORPHANED: a merely-paused lead's beacon thread
            # still answers, so an unreachable beacon means the lead DIED
            # with it. PR 13 aborted here ("the lead is this fleet's
            # driver"); the survivors now elect a successor instead.
            log.critical(
                "elastic: lockstep wedged (%s) and the lead's beacon is "
                "unreachable — the lead (uid %d) is gone; electing a "
                "successor from the committed view", why, self.lead_uid,
            )
            return self._elect(why, round_no)
        self._adopt_lead(resp, "wedge report")
        # wait for the lead's plan: its grace window + margin
        deadline = time.monotonic() + self._grace_s() + max(
            10.0, self._grace_s()
        )
        while time.monotonic() < deadline:
            hello = client.request("hello", self.uid)
            if hello and hello.get("epoch", -1) > wedge_epoch and not (
                hello.get("member")
            ) and not (hello.get("plan") or {}).get("members"):
                # the group already re-formed without us long ago (a woken
                # paused host missed the whole rescue): park and rejoin
                return self.park()
            plan = (resp or {}).get("plan")
            if plan and plan["epoch"] > wedge_epoch:
                old = list(self.members)
                if self.uid not in plan.get("members", []):
                    # the group moved on without us (we were presumed
                    # dead — e.g. a long GC pause): park and rejoin
                    return self.park()
                self._adopt_lead(plan, "rescue plan")
                plan = dict(plan, reason="rescue")
                self._plan = plan
                self._detach(clean=False)
                self._attach(plan, "rescue")
                self._finish_transition(old, "rescue")
                self._plan = None
                return True
            time.sleep(0.3)
            resp = client.request("wedged", self.uid, epoch=wedge_epoch)
        from ..parallel.elastic import probe_port

        if not probe_port(self.runtime.host, self.runtime.beacon_port):
            # the lead died DURING the window (answered the first wedge
            # report, then went down): the beacon is orphaned now — elect
            log.critical(
                "elastic: the lead's beacon went dark mid-rescue (%s); "
                "electing a successor", why,
            )
            return self._elect(why, round_no)
        log.critical(
            "elastic: no rescue plan from the lead within the window (%s)",
            why,
        )
        return False

    def _elect(self, why: str, round_no: int = 0) -> bool:
        """Lead election over the orphaned beacon port (the lead died; its
        ``os._exit`` released the bind). Deterministic successor rule: the
        candidates are every OTHER member of the committed view ascending
        by uid; each waits rank × stagger while probing the port, then
        races ``take_over_beacon()`` — the OS bind arbitrates, so exactly
        one survivor wins (the lowest LIVE uid, because lower ranks bind
        first and dead candidates never do). The winner runs the normal
        lead rescue (losers' wedge reports land on ITS beacon within the
        grace window); losers re-enter the follower rescue against the
        winner's beacon."""
        if round_no >= ELECT_MAX_ROUNDS:
            log.critical(
                "elastic: %d election rounds exhausted (%s) — every "
                "successor died mid-election; aborting", round_no, why,
            )
            return False
        from ..parallel.elastic import probe_port
        from ..telemetry import blackbox as _blackbox

        candidates = election_candidates(self.members, self.lead_uid)
        if self.uid not in candidates:
            return False  # not in the committed view — nothing to lead
        rank = candidates.index(self.uid)
        stagger = float(
            os.environ.get(ELECT_STAGGER_ENV, "") or ELECT_STAGGER_DEFAULT_S
        )
        _blackbox.record(
            "lead_election", epoch=self.epoch, uid=self.uid, rank=rank,
            candidates=candidates, dead_lead=self.lead_uid, why=why,
        )
        log.warning(
            "elastic: election — uid %d is successor rank %d of %s "
            "(stagger %.1fs)", self.uid, rank, candidates, rank * stagger,
        )
        deadline = time.monotonic() + rank * stagger
        while time.monotonic() < deadline:
            if probe_port(self.runtime.host, self.runtime.beacon_port,
                          timeout_s=0.2):
                # a lower-ranked survivor already owns the beacon: follow
                return self._rescue_follower(why, round_no + 1)
            time.sleep(0.1)
        old_lead = self.lead_uid
        if not self.runtime.take_over_beacon():
            # lost the bind race — the winner's beacon is up; follow it
            return self._rescue_follower(why, round_no + 1)
        self._lead_gauge.set(self.uid)
        self._elections.inc()
        self._handoffs.inc()
        _blackbox.record(
            "lead_elected", epoch=self.epoch, uid=self.uid,
            dead_lead=old_lead, why=why,
        )
        _blackbox.record(
            "beacon_handoff", port=self.runtime.beacon_port,
            from_uid=old_lead, to_uid=self.uid,
        )
        log.critical(
            "elastic: uid %d WON the election (beacon :%d re-bound, "
            "ex-lead uid %d) — coordinating the rescue as the new lead",
            self.uid, self.runtime.beacon_port, old_lead,
        )
        # the losers' probes see the bind within one stagger step; the
        # grace window stretches by the full stagger span so even the
        # highest-ranked live candidate's re-report lands inside it
        self.runtime.beacon.publish("rescuing", self.epoch, self.members)
        return self._rescue_lead(why, extra_grace_s=stagger * len(candidates))
