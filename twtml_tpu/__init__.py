"""twtml-tpu: a TPU-native streaming-ML framework.

A ground-up re-design of the capabilities of ``QilinGu/twitter-stream-ml``
(Spark Streaming + MLlib + Socko dashboard) as an idiomatic JAX/XLA stack:

- ``twtml_tpu.config``     — layered config + CLI (reference: ConfArguments.scala)
- ``twtml_tpu.features``   — tweet filter/featurizer (reference: MllibHelper.scala)
- ``twtml_tpu.models``     — streaming learners: linear / logistic / k-means
                             (reference: MLlib Streaming{LinearRegression,KMeans}WithSGD)
- ``twtml_tpu.ops``        — device ops: sparse featurization, batch stats, pallas kernels
- ``twtml_tpu.streaming``  — micro-batch streaming runtime (reference: Spark DStream)
- ``twtml_tpu.parallel``   — mesh/sharding/collectives (reference: Spark treeAggregate/Netty)
- ``twtml_tpu.telemetry``  — stats publishing (reference: SessionStats/WebClient/Lightning)
- ``twtml_tpu.web``        — dashboard web server (reference: twtml-web Socko server)
- ``twtml_tpu.checkpoint`` — model checkpoint/resume (absent in reference; upgrade)
- ``twtml_tpu.utils``      — rounding/logging/tracing helpers

Design notes: the reference's distributed runtime is Apache Spark (external JVM
dependency); here the runtime is JAX itself — weights live resident in device
HBM as donated jit state, the per-batch gradient reduce is a ``psum`` over the
``data`` axis of a ``jax.sharding.Mesh`` (ICI), and multi-host scale-out uses
``jax.distributed`` (DCN for process formation, ICI for collectives).
"""

__version__ = "0.1.0"
