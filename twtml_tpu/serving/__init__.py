"""Serving plane: batched, pipelined low-latency inference from verified
snapshots (ISSUE 9 / ROADMAP item 1).

The reference's entire upper half (SURVEY §1, L4-L6) is a live *read* path —
the trained model exists to answer queries — but through PR 8 prediction only
existed fused inside the train step. This package splits it out as a product:

- ``snapshot``  — verified-checkpoint snapshots + the ONE promotion predicate
                  (finite + quality level <= warn) shared with
                  ``tools/model_report.py --gate``, and the hot-swap promoter;
- ``engine``    — the jitted predict-only program over a device-resident
                  snapshot (the fused train step with ``num_iterations=0``:
                  the SAME traced prediction prologue, so serve-path
                  predictions are BIT-identical to the train step's
                  pre-update predictions — the parity law on the read path);
- ``plane``     — the bounded-latency request coalescer + depth-K pipelined
                  result fetches through ``apps/common.FetchPipeline`` (the
                  measured 6.2x-at-depth-8 transport trick, BENCHMARKS r3);
- ``client``    — the library-level HTTP client (``POST /api/predict``) for
                  load generation and ops scripts;
- ``fleet``     — the read-fleet router (ISSUE 11): N serve replicas behind
                  one front door — least-p99/consistent-hash routing,
                  health checks, ejection behind a jittered backoff;
- ``abtest``    — champion/challenger on the tenant stack: the champion
                  answers live traffic, challengers shadow-score the same
                  mirrored batch, and per-tenant quality stamps
                  auto-promote through the ONE ``is_promotable`` gate.

Import discipline: ``snapshot``, ``client``, and ``fleet`` are jax-free
(ops tools — ``tools/model_report.py --gate`` — must not initialize a
backend to answer "is this checkpoint servable?", and the router process
holds no model at all); the engine/plane/abtest modules import jax lazily
via ``__getattr__``.
"""

from __future__ import annotations

from .client import ServingClient
from .fleet import FleetRouter
from .snapshot import (
    ServingSnapshot,
    SnapshotPromoter,
    is_promotable,
    load_servable,
)

__all__ = [
    "ChampionEngine",
    "ChampionSelector",
    "FleetRouter",
    "ServingClient",
    "ServingPlane",
    "ServingSnapshot",
    "SnapshotPromoter",
    "is_promotable",
    "load_servable",
]

_LAZY = {
    # lazy: these pull in jax via the model layer
    "ServingPlane": ("plane", "ServingPlane"),
    "ChampionEngine": ("abtest", "ChampionEngine"),
    "ChampionSelector": ("abtest", "ChampionSelector"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is not None:
        import importlib

        module = importlib.import_module(f".{target[0]}", __name__)
        return getattr(module, target[1])
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
