"""The serving plane: bounded-latency request coalescing over the predict
engine, with depth-K pipelined result fetches.

Why this shape (the measured record, BENCHMARKS r2/r3): a host fetch through
this build's TPU tunnel is a ~70-100 ms RTT-bound REQUEST — naive
per-request serving pays that full round trip PER QUERY, while CONCURRENT
``device_get``s pipeline the transport (6.2x paired at depth 8). So the
plane:

- **coalesces** requests into one featurize + ONE dispatch per batch: admit
  until ``--serveBatchRows`` rows or ``--serveMaxWaitMs`` since the oldest
  admitted request (the bounded-latency knob) — batching is where device
  FLOPs are free and transfers amortize;
- **pipelines** the result fetches through the EXISTING
  ``apps/common.FetchPipeline`` at ``--serveDepth`` (default 8): micro-batch
  N+1..N+K dispatch while batch N's predictions are still in flight, so
  tunnel RTT amortizes across in-flight batches. Dispatch and any
  ``device_put`` stay on the ONE serve-loop thread — the r2 throughput
  collapse is put-specific, fetches are exactly what the 6.2x measurement
  exercised;
- **hot-swaps** snapshots ATOMICALLY: the promoter hands a new snapshot to
  ``hot_swap`` (any thread), the serve loop installs it BETWEEN dispatches —
  a batch in flight completes against the weights it dispatched with, so no
  request is ever served by a half-applied swap (each batch carries its
  dispatch-time snapshot step into its response);
- **fails loudly, never hangs**: the FetchPipeline's FetchWatchdog owns
  stalled/failed fetches (--chaos injectable) — retries, then a clean abort
  that REJECTS every in-flight and queued request future instead of leaving
  clients waiting on a wedged tunnel.

The train path is untouched: the plane reads verified snapshots from DISK
(checkpoint handoff), issues zero fetches against a co-located trainer's
device state, and shares no mutable state with the train loop.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..apps.common import FetchAbort, FetchPipeline
from ..telemetry import blackbox as _blackbox
from ..telemetry import metrics as _metrics
from ..utils import get_logger
from ..utils.clock import now_ms, now_s
from .engine import PredictEngine

log = get_logger("serving.plane")

# rolling completion window for the QPS/latency view (stats()); bounded so a
# days-long server never grows it
COMPLETION_WINDOW = 4096
QPS_WINDOW_S = 30.0


class _Request:
    __slots__ = ("statuses", "future", "t_arrival")

    def __init__(self, statuses, future, t_arrival):
        self.statuses = statuses
        self.future = future
        self.t_arrival = t_arrival


class ServingPlane:
    """Request front end over one ``PredictEngine``. ``submit`` is
    thread-safe (the web server's event loop and load generators call it);
    featurize/dispatch/swap all happen on the single serve-loop thread."""

    def __init__(
        self,
        snapshot,
        *,
        num_text_features: int = 1000,
        batch_rows: int = 256,
        max_wait_ms: float = 5.0,
        depth: int = 8,
        model_cls=None,
        tenant_key: str = "hash",
        dtype=None,
        featurizer=None,
        engine: "PredictEngine | None" = None,
        stale_slo_s: float = 0.0,
    ) -> None:
        from ..features.featurizer import Featurizer

        self.batch_rows = max(1, int(batch_rows))
        self.max_wait_s = max(0.0, float(max_wait_ms) / 1e3)
        self.depth = max(1, int(depth))
        self._engine = engine if engine is not None else PredictEngine(
            num_text_features=num_text_features,
            num_tenants=snapshot.num_tenants,
            tenant_key=tenant_key,
            dtype=dtype,
            model_cls=model_cls,
        )
        self._feat = featurizer if featurizer is not None else Featurizer(
            num_text_features=num_text_features
        )
        self._cond = threading.Condition()
        self._queue: "collections.deque[_Request]" = collections.deque()
        self._inflight: "set[_Request]" = set()
        self._pending_snapshot = None
        self._snapshot_level = ""
        self._stopping = False
        self.failed = False
        self._thread: "threading.Thread | None" = None
        reg = _metrics.get_registry()
        self._req_count = reg.counter("serve.requests")
        self._row_count = reg.counter("serve.rows")
        self._err_count = reg.counter("serve.errors")
        self._batch_count = reg.counter("serve.batches")
        self._swap_count = reg.counter("serve.hot_swaps")
        self._queue_gauge = reg.gauge("serve.queue_depth")
        self._step_gauge = reg.gauge("serve.snapshot_step")
        # serving staleness (ISSUE 16): installed-at stamp through the
        # TWTML_NOW_MS seam → serving.snapshot_age_s on /api/serving and a
        # dispatch-time model-staleness figure in every predict response;
        # --servingStaleSloS > 0 arms a warn-only breach episode
        self._age_gauge = reg.gauge("serving.snapshot_age_s")
        self._stale_breach_count = reg.counter("serve.stale_breaches")
        self.stale_slo_s = max(0.0, float(stale_slo_s or 0.0))
        self._installed_at_s = -1.0
        self._in_stale_episode = False
        self._latency = reg.histogram("serve.latency_s")
        self._batch_fill = reg.histogram("serve.batch_rows")
        # per-tenant served-row totals (the dashboard's per-tenant query
        # tiles); None on the single-model plane
        self._tenant_rows = (
            np.zeros((self._engine.num_tenants,), np.int64)
            if self._engine.num_tenants > 1 else None
        )
        # rolling completion record for the QPS view: (t_done, rows)
        self._completions: "collections.deque[tuple[float, int]]" = (
            collections.deque(maxlen=COMPLETION_WINDOW)
        )
        self._started_s = time.monotonic()
        # depth-K pipelined result fetches — the measured 6.2x transport
        # trick, reused verbatim from the train path (apps/common.py); the
        # --chaos fetch/step injection points and the FetchWatchdog come
        # with it, so a wedged tunnel aborts cleanly instead of hanging
        # every client
        self._pipe = FetchPipeline(
            self._engine, self._deliver, depth=self.depth,
            # the lean one-buffer wire, exactly like the train path (the
            # measured +11.4% packed-ragged win; the tenant engine's pack
            # IS its routed tenant wire)
            pack=self._engine.accepts_packed,
            abort=self._on_abort,
        )
        self._install(snapshot)

    @classmethod
    def from_conf(cls, conf, snapshot, model_cls=None, featurizer=None,
                  engine=None):
        import jax.numpy as jnp

        return cls(
            snapshot,
            num_text_features=conf.numTextFeatures,
            batch_rows=int(getattr(conf, "serveBatchRows", 256) or 256),
            max_wait_ms=float(getattr(conf, "serveMaxWaitMs", 5.0) or 0.0),
            depth=int(getattr(conf, "serveDepth", 8) or 8),
            model_cls=model_cls,
            tenant_key=getattr(conf, "tenantKey", "hash"),
            dtype=jnp.dtype(getattr(conf, "dtype", "float32")),
            featurizer=featurizer,
            engine=engine,
            stale_slo_s=float(getattr(conf, "servingStaleSloS", 0.0) or 0.0),
        )

    # -- request intake ------------------------------------------------------
    @property
    def snapshot_step(self) -> int:
        return self._engine.snapshot_step

    @property
    def num_tenants(self) -> int:
        return self._engine.num_tenants

    def submit(self, statuses) -> Future:
        """Enqueue one predict request (a list of featurizer ``Status``
        rows; see ``statuses_from_rows`` for the JSON face). Returns a
        future resolving to ``{"predictions": [...], "snapshot_step": N}``.
        Thread-safe; never blocks on device work."""
        fut: Future = Future()
        if self.failed:
            fut.set_exception(RuntimeError(
                "serving plane aborted (fetch watchdog); restart the server"
            ))
            return fut
        if self._stopping:
            fut.set_exception(RuntimeError("serving plane is shutting down"))
            return fut
        statuses = list(statuses)
        if not statuses:
            fut.set_result({
                "predictions": [], "snapshot_step": self.snapshot_step,
            })
            return fut
        if len(statuses) > self.batch_rows:
            fut.set_exception(ValueError(
                f"request carries {len(statuses)} rows; the serve batch "
                f"bucket is {self.batch_rows} (--serveBatchRows) — split "
                "the request"
            ))
            return fut
        self._req_count.inc()
        self._row_count.inc(len(statuses))
        req = _Request(statuses, fut, time.perf_counter())
        with self._cond:
            self._queue.append(req)
            self._queue_gauge.set(len(self._queue))
            self._cond.notify_all()
        return fut

    @staticmethod
    def statuses_from_rows(rows):
        """The JSON request face → featurizer ``Status`` rows. Each row is
        either a plain object ``{"text": ..., "followers_count": ...,
        "favourites_count": ..., "friends_count": ..., "created_at_ms": ...,
        "retweet_count": ...}`` (a bare string is shorthand for
        ``{"text": ...}``) describing the ORIGINAL tweet the model scores,
        or a full standard-API tweet JSON carrying ``retweeted_status`` —
        then the reference's exact object path (``Status.from_json``)
        parses it. ``created_at_ms`` defaults to NOW (age feature 0) for
        queries about fresh tweets — read through the TWTML_NOW_MS seam so
        pinned replays see pinned ages (utils/clock)."""
        from ..features.featurizer import Status

        default_created_ms = now_ms()
        out = []
        for row in rows:
            if isinstance(row, str):
                row = {"text": row}
            if not isinstance(row, dict):
                raise ValueError(f"bad predict row: {row!r}")
            if row.get("retweeted_status"):
                status = Status.from_json(row)
            else:
                original = Status(
                    text=str(row.get("text", "")),
                    retweet_count=int(row.get("retweet_count") or 0),
                    followers_count=int(row.get("followers_count") or 0),
                    favourites_count=int(row.get("favourites_count") or 0),
                    friends_count=int(row.get("friends_count") or 0),
                    created_at_ms=int(
                        row.get("created_at_ms") or default_created_ms
                    ),
                    lang=str(row.get("lang") or ""),
                )
                status = Status(
                    text=original.text, retweeted_status=original,
                    lang=original.lang,
                )
            out.append(status)
        return out

    # -- snapshot management -------------------------------------------------
    def hot_swap(self, snapshot) -> None:
        """Stage a snapshot for atomic installation. Callable from any
        thread (the promoter's); the serve loop applies it BETWEEN
        dispatches, so an in-flight batch always completes against the
        weights it dispatched with — no request is ever torn across two
        snapshots."""
        with self._cond:
            self._pending_snapshot = snapshot
            self._cond.notify_all()

    def _install(self, snapshot) -> None:
        self._engine.set_snapshot(snapshot)
        self._snapshot_level = snapshot.quality_level
        self._step_gauge.set(self._engine.snapshot_step)
        # snapshot-age epoch: the swap moment through the pinnable clock
        # seam (TW006), so replayed runs see replayed ages
        self._installed_at_s = now_s()
        self._age_gauge.set(0.0)

    def _apply_pending_swap(self) -> None:
        with self._cond:
            snap, self._pending_snapshot = self._pending_snapshot, None
        if snap is not None:
            self._install(snap)
            self._swap_count.inc()
            log.info("hot-swapped serving snapshot to step %d", snap.step)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServingPlane":
        self._thread = threading.Thread(
            target=self._loop, name="twtml-serve-loop", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop intake, drain queued + in-flight requests, join the loop."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)

    def warmup(self) -> None:
        """Compile + fetch one all-padding-shaped batch BEFORE traffic so
        the first request doesn't pay the XLA compile (the serve-side
        analog of ``apps/common.warmup_compile``; the ragged units bucket
        is data-dependent, so real batches may still compile one or two
        more buckets in-flight)."""
        import jax

        from ..features.featurizer import Status

        warm = Status(text="warmup", retweeted_status=Status(
            text="warmup", created_at_ms=now_ms(),
        ))
        batch = self._featurize([warm])
        wire = self._engine.pack_for_wire(batch) if (
            self._engine.accepts_packed
        ) else batch
        jax.device_get(self._engine.step(wire))  # lawcheck: disable=TW002 -- one-off pre-traffic compile warmup on the serve-loop thread, before the FetchPipeline takes over; never on a per-request path

    # -- the serve loop -------------------------------------------------------
    def _featurize(self, statuses):
        return self._feat.featurize_batch_ragged(
            statuses, row_bucket=self.batch_rows, pre_filtered=True,
        )

    def _take_group(self):
        """Admit requests until the row bucket fills or the oldest admitted
        request has waited ``max_wait_s`` — the bounded-latency coalescer.
        Returns a list of requests, or None on an idle/stop tick (the
        caller polls the fetch pipeline then)."""
        group: "list[_Request]" = []
        rows = 0
        with self._cond:
            while True:
                while self._queue and (
                    rows + len(self._queue[0].statuses) <= self.batch_rows
                ):
                    req = self._queue.popleft()
                    group.append(req)
                    rows += len(req.statuses)
                self._queue_gauge.set(len(self._queue))
                if rows >= self.batch_rows or (group and self._queue):
                    # bucket full, or the next request no longer fits —
                    # dispatch what we have (never split one request)
                    return group
                if group:
                    wait_end = group[0].t_arrival + self.max_wait_s
                    left = wait_end - time.perf_counter()
                    if left <= 0 or self._stopping:
                        return group
                    self._cond.wait(timeout=left)
                    continue
                if self._stopping or self._pending_snapshot is not None:
                    return None
                # idle: short tick while fetches are in flight (results
                # must deliver promptly), longer when fully quiet
                self._cond.wait(
                    timeout=0.002 if self._pipe.pending_fetches else 0.05
                )
                if not self._queue:
                    return None

    def _loop(self) -> None:
        while True:
            group = self._take_group()
            if group is None:
                self._apply_pending_swap()
                try:
                    self._pipe.poll()
                except FetchAbort:
                    self._abort_requests()
                if self._stopping and not self._queue:
                    break
                if self.failed:
                    break
                continue
            # swaps land BETWEEN dispatches — the atomic hot-swap point
            self._apply_pending_swap()
            for req in group:
                self._inflight.add(req)
            statuses = [s for req in group for s in req.statuses]
            batch = self._featurize(statuses)
            self._batch_fill.observe(len(statuses))
            self._batch_count.inc()
            try:
                # ONE dispatch per coalesced batch; the snapshot step rides
                # the payload so the response names the weights that served
                # it even if a swap lands before the fetch returns
                self._pipe.on_batch(
                    batch,
                    (group, self._engine.snapshot_step,
                     self._installed_at_s),
                )
            except FetchAbort:
                self._abort_requests()
                break
        try:
            self._pipe.flush()
        except Exception:
            log.exception("serve pipeline flush failed")
        self._abort_requests(
            reason="serving plane stopped" if not self.failed else None
        )

    def _deliver(self, host_out, batch, payload, at_boundary=True) -> None:
        """FetchPipeline handler: slice the batch's predictions back to the
        requests that rode it and resolve their futures."""
        group, step, *rest = payload
        installed = rest[0] if rest else self._installed_at_s
        # dispatch-time model staleness: how old the serving weights were
        # when THIS batch dispatched — the per-response freshness figure
        # (ISSUE 16); a swap landing mid-flight doesn't rewrite history
        staleness = (
            max(0.0, now_s() - installed) if installed >= 0.0 else -1.0
        )
        preds = self._engine.predictions_for(host_out, batch)
        counts = self._engine.tenant_row_counts(batch)
        if counts is not None:
            self._tenant_rows += counts
        now = time.perf_counter()
        offset = 0
        for req in group:
            n = len(req.statuses)
            self._inflight.discard(req)
            self._latency.observe(now - req.t_arrival)
            self._completions.append((time.monotonic(), n))
            req.future.set_result({
                "predictions": [float(v) for v in preds[offset:offset + n]],
                "snapshot_step": int(step),
                "model_staleness_s": round(staleness, 3),
            })
            offset += n

    def _on_abort(self) -> None:
        self.failed = True
        with self._cond:
            self._cond.notify_all()

    def _abort_requests(self, reason: "str | None" = None) -> None:
        """Reject every in-flight and queued request future — the fetch
        watchdog already logged WHY; clients get an error, never a hang."""
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
            self._queue_gauge.set(0)
        stranded = pending + list(self._inflight)
        self._inflight.clear()
        if not stranded:
            return
        why = reason or (
            "serving fetch aborted by the watchdog (wedged transport); "
            "see the critical log"
        )
        for req in stranded:
            self._err_count.inc()
            if not req.future.done():
                req.future.set_exception(RuntimeError(why))
        log.warning("rejected %d stranded predict request(s): %s",
                    len(stranded), why)

    # -- telemetry view -------------------------------------------------------
    def stats(self) -> dict:
        """The ``Serving`` jsonClass view (QPS over the rolling window,
        latency quantiles from the serve histogram, active snapshot, per-
        tenant served rows) — plain host bookkeeping, zero device work."""
        now = time.monotonic()
        window = min(QPS_WINDOW_S, max(now - self._started_s, 1e-3))
        lo = now - window
        reqs = rows = 0
        for t_done, n in reversed(self._completions):
            if t_done < lo:
                break
            reqs += 1
            rows += n
        tenants = []
        if self._tenant_rows is not None:
            tenants = [
                {"tenant": m, "rows": int(r)}
                for m, r in enumerate(self._tenant_rows)
            ]
        age = (
            max(0.0, now_s() - self._installed_at_s)
            if self._installed_at_s >= 0.0 else -1.0
        )
        self._age_gauge.set(round(age, 1))
        if self.stale_slo_s > 0.0 and age > self.stale_slo_s:
            if not self._in_stale_episode:
                # one blackbox event + counter per breach episode — the
                # warn-only PR 8 shape (no serving behavior change)
                self._in_stale_episode = True
                self._stale_breach_count.inc()
                _blackbox.record(
                    "serving_stale_breach", age_s=round(age, 1),
                    slo_s=self.stale_slo_s, step=int(self.snapshot_step),
                )
                log.warning(
                    "serving snapshot is stale: age %.1f s > SLO %.1f s "
                    "(step %d) — promotion/handoff may be wedged",
                    age, self.stale_slo_s, self.snapshot_step,
                )
        else:
            self._in_stale_episode = False
        view = {
            "qps": round(reqs / window, 2),
            "rowsPerSec": round(rows / window, 1),
            "p50Ms": round(self._latency.percentile(0.50) * 1e3, 2),
            "p95Ms": round(self._latency.percentile(0.95) * 1e3, 2),
            "p99Ms": round(self._latency.percentile(0.99) * 1e3, 2),
            "snapshotAgeS": round(age, 1),
            "snapshotStep": int(self.snapshot_step),
            "level": self._snapshot_level,
            "requests": int(self._req_count.snapshot()),
            "rows": int(self._row_count.snapshot()),
            "errors": int(self._err_count.snapshot()),
            "tenants": tenants,
        }
        # champion/challenger slice (serving/abtest.py): the live champion
        # + per-tenant shadow divergence ride the same view, so the router
        # and the dashboard learn the A/B state from the health check they
        # already make
        ab = getattr(self._engine, "abtest_view", None)
        if ab is not None:
            view.update(ab())
        return view
