"""Champion/challenger serving on the tenant stack (ISSUE 11).

The reference's whole loop is predict-then-train on ONE model; PR 7 made M
model variants train in ONE jit program, and PR 8 gave every variant an
online quality vector. This module closes the A/B loop at serve time:

- **one program, M variants, zero added dispatches**: the engine is the PR 9
  predict-only trick on a ``TenantStackModel(num_iterations=0)``, but every
  variant sees the SAME rows — the coalesced predict batch is MIRRORED to
  all M tenants (``prepare_wire_from_parts([batch] * M)``), so challengers
  ride the champion's dispatch and fetch instead of costing their own
  (device FLOPs are µs and nowhere near binding; fetches are what cost —
  the r2 law);
- **the champion answers**: live responses select the champion tenant's row
  of the already-fetched ``[M, B]`` predictions. The champion index is
  captured at DISPATCH time and rides the device round trip with the
  output, so a batch in flight across a champion swap still answers with
  the tenant it dispatched under — the same no-torn-batch discipline as
  the snapshot hot-swap;
- **challengers are shadow-scored for free**: per-challenger divergence
  against the champion is plain host numpy over the predictions the ONE
  fetch already delivered (zero added fetches, the PR 8 pattern), and the
  authoritative online score is the PR 8 quality vector the TRAINER stamps
  per tenant into every verified checkpoint
  (``meta["quality"]["tenants"]``);
- **auto-promotion through the ONE gate**: when a new snapshot installs,
  the selector compares challengers' quality stamps against the
  champion's; a strictly better challenger is promoted by swapping the
  champion pointer — but only if ``serving.snapshot.is_promotable`` says
  its stamp may serve. An alert-stamped challenger is REFUSED and counted
  (``abtest.promotions_refused``), exactly like an alert-stamped snapshot
  at the promoter tier. Promotion fires at most once per stamped step, and
  the verdict is a pure function of the stamps — every replica of a read
  fleet converges on the same champion for the same snapshot.
"""

from __future__ import annotations

import numpy as np

from ..telemetry import metrics as _metrics
from ..utils import get_logger
from .engine import PredictEngine
from .snapshot import is_promotable

log = get_logger("serving.abtest")

# shadow divergence EWMA smoothing (host-side telemetry only)
_SHADOW_ALPHA = 0.2


def _score(entry: "dict | None") -> float:
    """The A/B ranking metric over per-tenant quality stamps — smaller is
    better: the trainer's ONLINE loss EWMA (``loss``, the PR 8 fast EWMA
    of per-tenant mse). Deliberately loss-ONLY: health never ranks here —
    whether a winner may serve is ``is_promotable``'s job, the one gate,
    so an alert-stamped challenger with the best loss is REFUSED there
    (and counted) instead of being silently out-ordered. A missing or
    invalid stamp scores worst: no evidence never promotes."""
    if not isinstance(entry, dict):
        return float("inf")
    loss = entry.get("loss", -1.0)
    try:
        loss = float(loss)
    except (TypeError, ValueError):
        loss = -1.0
    return loss if loss >= 0 else float("inf")


class ChampionSelector:
    """The champion pointer + the promotion rule. ``consider`` is called by
    the engine when a snapshot installs (serve-loop thread, between
    dispatches) and returns the new champion index, or None when nothing
    changes. Deterministic given (stamps, current champion)."""

    def __init__(self, num_tenants: int, champion: int = 0):
        if not 0 <= champion < num_tenants:
            raise ValueError(
                f"champion {champion} out of range for {num_tenants} tenants"
            )
        self.num_tenants = num_tenants
        self.champion = champion
        self._last_step: "int | None" = None
        reg = _metrics.get_registry()
        self._promotions = reg.counter("abtest.promotions")
        self._refused = reg.counter("abtest.promotions_refused")

    def consider(self, meta: "dict | None", step: int) -> "int | None":
        """One promotion decision per stamped step: gate every strictly
        better challenger through ``is_promotable`` (an alert stamp refuses
        — counted), then swap to the best survivor."""
        if self._last_step is not None and step == self._last_step:
            return None
        self._last_step = step
        quality = (meta or {}).get("quality") or {}
        tenants = quality.get("tenants") or []
        entries: dict[int, dict] = {}
        for i, e in enumerate(tenants):
            if isinstance(e, dict):
                entries[int(e.get("tenant", i))] = e
        if len(entries) < 2:
            return None  # no per-tenant stamps: nothing to compare
        best, best_entry = self.champion, entries.get(self.champion)
        for m in sorted(entries):
            if m == self.champion or not 0 <= m < self.num_tenants:
                continue
            entry = entries[m]
            if _score(entry) >= _score(best_entry):
                continue
            ok, reason = is_promotable({"finite": True, "quality": entry})
            if not ok:
                self._refused.inc()
                log.warning(
                    "challenger tenant %d REFUSED promotion at step %d "
                    "(champion stays %d): %s", m, step, self.champion,
                    reason,
                )
                continue
            best, best_entry = m, entry
        if best == self.champion:
            return None
        prev, self.champion = self.champion, best
        self._promotions.inc()
        log.info(
            "champion AUTO-promoted: tenant %d -> %d at snapshot step %d "
            "(stamp %s beats %s)", prev, best, step,
            _score(best_entry), _score(entries.get(prev)),
        )
        return best


class _ShadowTrack:
    """Rolling shadow score for one challenger: rows mirrored, EWMA of the
    mean |challenger − champion| prediction divergence."""

    __slots__ = ("rows", "divergence")

    def __init__(self):
        self.rows = 0
        self.divergence: "float | None" = None

    def observe(self, diff_mean: float, rows: int) -> None:
        self.rows += rows
        if self.divergence is None:
            self.divergence = diff_mean
        else:
            self.divergence += _SHADOW_ALPHA * (diff_mean - self.divergence)


class ChampionEngine(PredictEngine):
    """A ``PredictEngine`` over the tenant stack where live traffic is
    answered by the CHAMPION tenant and mirrored shadow-mode to every
    challenger. Drop-in for ``ServingPlane`` (same step/pack/predictions
    surface the FetchPipeline drives)."""

    def __init__(self, *args, champion: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        if self.num_tenants < 2:
            raise ValueError(
                "champion/challenger needs a tenant stack (num_tenants >= "
                f"2), got {self.num_tenants} — train with --tenants M"
            )
        self.selector = ChampionSelector(self.num_tenants, champion)
        self._shadows = [_ShadowTrack() for _ in range(self.num_tenants)]
        self._live_rows = np.zeros((self.num_tenants,), np.int64)

    @property
    def champion(self) -> int:
        return self.selector.champion

    # -- snapshot install + auto-promotion ----------------------------------
    def set_snapshot(self, snapshot) -> None:
        """Install the stack AND run the promotion rule on its per-tenant
        quality stamps — both happen on the serve-loop thread between
        dispatches (ServingPlane._install), so a swap of (weights,
        champion) is one atomic event w.r.t. dispatches."""
        super().set_snapshot(snapshot)
        self.selector.consider(
            getattr(snapshot, "meta", None), int(snapshot.step)
        )

    # -- FetchPipeline surface ----------------------------------------------
    def pack_for_wire(self, batch):
        """The MIRRORED tenant wire: every variant sees the same rows —
        challengers ride the champion's coalesced batch through the one
        mapped program instead of costing their own dispatch."""
        return self.model.prepare_wire_from_parts(
            [batch] * self.num_tenants
        )

    def step(self, wire):
        """Dispatch the mirrored program; the dispatch-time champion rides
        the payload so delivery answers with the tenant this batch was
        dispatched under, even across a swap (no torn batch)."""
        return self.model.step(wire), int(self.champion)

    # -- result extraction ---------------------------------------------------
    def predictions_for(self, host_out, batch) -> np.ndarray:
        """Champion row of the fetched [M, B] predictions (mirrored wire →
        every tenant is already in original row order), plus the free
        shadow scoring pass over the challengers."""
        out, champ = host_out
        mask = np.asarray(batch.mask) > 0
        tenant_preds = np.asarray(out.predictions)
        live = tenant_preds[champ][mask]
        rows = int(mask.sum())
        self._live_rows[champ] += rows
        if rows:
            for m in range(self.num_tenants):
                if m == champ:
                    continue
                diff = float(
                    np.abs(tenant_preds[m][mask] - live).mean()
                )
                self._shadows[m].observe(diff, rows)
        return live

    def tenant_row_counts(self, batch) -> "np.ndarray | None":
        """Live-answered rows land on the champion (challengers see the
        mirror shadow-mode; their exposure is the shadow view, not served
        traffic)."""
        counts = np.zeros((self.num_tenants,), np.int64)
        counts[self.champion] = int((np.asarray(batch.mask) > 0).sum())
        return counts

    # -- telemetry -----------------------------------------------------------
    def abtest_view(self) -> dict:
        """The champion/challenger slice of the Serving view: the live
        champion plus per-tenant shadow divergence/exposure."""
        reg = _metrics.get_registry()
        shadows = []
        for m in range(self.num_tenants):
            track = self._shadows[m]
            shadows.append({
                "tenant": m,
                "live": m == self.champion,
                "liveRows": int(self._live_rows[m]),
                "shadowRows": int(track.rows),
                "divergence": round(track.divergence or 0.0, 4),
            })
        return {
            "champion": int(self.champion),
            "shadows": shadows,
            "promotions": int(reg.counter("abtest.promotions").snapshot()),
            "refusedPromotions": int(
                reg.counter("abtest.promotions_refused").snapshot()
            ),
        }
