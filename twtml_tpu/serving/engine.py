"""The jitted predict-only program over a device-resident snapshot.

Parity by CONSTRUCTION, not by re-implementation: the predict program is the
fused train step built with ``num_iterations=0`` (``models/sgd.py
make_sgd_train_step``). The reference's predict-then-train ordering means the
train step's reported predictions are computed with PRE-update weights
(LinearRegression.scala:85-86) by exactly the prologue this program runs —
wire unpack, ragged re-pad + ASCII fold, device bigram hash, raw margin,
``prediction_fn``, HALF_UP rounding — and a zero-iteration ``fori_loop``
leaves the weights untouched (XLA drops the dead update). Serve-path
predictions are therefore BIT-identical to what the train step would report
for the same snapshot and batch (tests/test_serving.py asserts it), and every
future change to the prediction semantics lands on both paths at once.

``use_gram=False`` always: the Gram build (config #4's [B, B] matmul) exists
for the ITERATIONS, which serving never runs — with the scatter formulation
chosen and zero iterations, the whole training half is dead code and the
compiled program is predict + stats only. ``quality=False`` likewise (the
model-watch side channel is a training telemetry surface).

The tenant stack (PR 7, ``[M, F+4]``) serves through the same trick:
``TenantStackModel`` with zero-iteration steps — ONE ``lax.map``-mapped
program for all M tenants, host-side ``tenant_route_keys`` routing, one
stacked fetch; ``predictions_for`` re-orders the ``[M, B]`` output back to
original request rows via the recomputed deterministic route (the
aggregate_tenant_output rule).
"""

from __future__ import annotations

import numpy as np

from ..utils import get_logger

log = get_logger("serving.engine")


class PredictEngine:
    """Snapshot-resident predict program with the model surface
    ``apps/common.FetchPipeline`` drives (``step``/``pack_for_wire``/
    ``accepts_packed`` delegate to the underlying zero-iteration model).

    ``model_cls`` supplies the reference gradient-family knobs
    (``residual_fn``/``prediction_fn``/``round_predictions`` — linear by
    default, logistic serves the sentiment family); ``num_tenants`` > 1
    builds the stacked tenant program instead."""

    def __init__(
        self,
        num_text_features: int = 1000,
        num_tenants: int = 1,
        tenant_key: str = "hash",
        dtype=None,
        model_cls=None,
        use_sparse: "bool | None" = None,
    ) -> None:
        import jax.numpy as jnp

        from ..models.linear import StreamingLinearRegressionWithSGD

        model_cls = model_cls or StreamingLinearRegressionWithSGD
        dtype = jnp.float32 if dtype is None else dtype
        self.num_text_features = num_text_features
        self.num_tenants = int(num_tenants)
        if self.num_tenants > 1:
            from ..parallel.tenants import TenantStackModel

            self.model = TenantStackModel(
                self.num_tenants,
                num_text_features=num_text_features,
                num_iterations=0,  # predict-only: the whole update is dead
                dtype=dtype,
                residual_fn=model_cls.residual_fn,
                prediction_fn=model_cls.prediction_fn,
                round_predictions=model_cls.round_predictions,
                use_sparse=use_sparse,
                use_gram=False,  # G exists for iterations serving never runs
                tenant_key=tenant_key,
                quality=False,
            )
        else:
            self.model = model_cls(
                num_text_features=num_text_features,
                num_iterations=0,
                dtype=dtype,
                use_sparse=use_sparse,
                use_gram=False,
                quality=False,
            )
        self.snapshot_step = -1

    @classmethod
    def from_conf(cls, conf, num_tenants: int = 1, model_cls=None):
        import jax.numpy as jnp

        return cls(
            num_text_features=conf.numTextFeatures,
            num_tenants=num_tenants,
            tenant_key=getattr(conf, "tenantKey", "hash"),
            dtype=jnp.dtype(getattr(conf, "dtype", "float32")),
            model_cls=model_cls,
        )

    # -- snapshot state ------------------------------------------------------
    def set_snapshot(self, snapshot) -> None:
        """Install a snapshot's weights device-side. The zero-iteration step
        never changes them, so the device copy IS the snapshot until the
        next swap; callers swap only between dispatches (serving/plane.py),
        which is what makes the swap tear-free."""
        weights = np.asarray(snapshot.weights)
        want = 2 if self.num_tenants > 1 else 1
        if weights.ndim != want:
            raise ValueError(
                f"snapshot weights ndim {weights.ndim} does not fit a "
                f"{self.num_tenants}-tenant predict program"
            )
        self.model.set_initial_weights(weights)
        self.snapshot_step = int(snapshot.step)

    # -- FetchPipeline model surface ----------------------------------------
    @property
    def accepts_packed(self) -> bool:
        return bool(getattr(self.model, "accepts_packed", False))

    def step(self, wire):
        return self.model.step(wire)

    def pack_for_wire(self, batch):
        packer = getattr(self.model, "pack_for_wire", None)
        if packer is not None:
            return packer(batch)
        from ..features.batch import pack_batch

        return pack_batch(batch)

    # -- result extraction ---------------------------------------------------
    def predictions_for(self, host_out, batch) -> np.ndarray:
        """The fetched StepOutput's predictions re-ordered to the ORIGINAL
        batch rows, valid rows only ([n] float array). Single-model output
        is already row-ordered; the tenant stack's [M, B] per-tenant-order
        output re-orders through the recomputed deterministic route exactly
        like ``aggregate_tenant_output`` (routing is host-side metadata —
        PARITY.md)."""
        mask = np.asarray(batch.mask) > 0
        if self.num_tenants == 1:
            return np.asarray(host_out.predictions)[mask]
        from ..features.batch import tenant_rows

        tenant_preds = np.asarray(host_out.predictions)
        preds = np.zeros(tenant_preds.shape[1:], tenant_preds.dtype)
        rows_per = tenant_rows(
            batch, self.model.route_ids(batch), self.num_tenants
        )
        for m, rows in enumerate(rows_per):
            preds[rows] = tenant_preds[m][: rows.shape[0]]
        return preds[mask]

    def tenant_row_counts(self, batch) -> "np.ndarray | None":
        """[M] valid-row counts this batch routed per tenant (None on the
        single-model plane) — the per-tenant query telemetry, recomputed
        host-side from the same deterministic route as the wire."""
        if self.num_tenants == 1:
            return None
        ids = np.asarray(self.model.route_ids(batch))
        valid = np.asarray(batch.mask) > 0
        return np.bincount(
            ids[valid], minlength=self.num_tenants
        ).astype(np.int64)
