"""Read-fleet router: N serve replicas behind ONE front door (ISSUE 11).

The serving plane (PR 9) made the read path a product, but one process over
one snapshot directory. The "millions of users" story needs a horizontal
read fleet: N ``apps/serve.py`` replicas — each polling the SAME verified-
snapshot directory through its own ``SnapshotPromoter``, so replicas promote
independently but converge on the same stamped step via the shared
``is_promotable`` predicate — fronted by this router, which:

- **load-balances** ``POST /api/predict`` over the healthy replicas.
  ``--routePolicy p99`` picks the replica with the lowest EXPECTED p99
  cost — rolling forward p99 x (in-flight forwards + 1), the router's own
  view of each replica's line, no replica cooperation needed (raw
  least-p99 herds open-loop bursts onto one stale-lowest replica —
  measured); ``--routePolicy hash`` consistent-hashes the request key onto
  a vnode ring, so a given key sticks to a replica across requests
  (cache-friendly routing) and only 1/N of keys move when a replica joins
  or dies;
- **health-checks** replicas via ``GET /api/serving`` on a background
  cadence (the same view the dashboard reads — no new replica surface);
- **drains and ejects** a failing replica instead of surfacing its errors:
  a connection-refused/timeout/5xx forward retries on ANOTHER replica
  (counted in ``router.retries``) while the failing one is ejected
  (``fleet.replica_ejections``) behind a jittered exponential re-probe
  backoff — the ``Source._backoff`` cap+jitter ladder applied at the fleet
  tier, for the same reason: N routers re-probing a dead replica must not
  reconnect in phase. A recovered probe restores the replica and resets
  its ladder (the Twitter-reconnect rule: health resets backoff).

jax-free on purpose (like ``snapshot``/``client``): the router is a pure
HTTP process — it holds no model, so a fleet front door boots in
milliseconds and never competes with replicas for the one host core's
device runtime.
"""

from __future__ import annotations

import collections
import hashlib
import json
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

from ..telemetry import metrics as _metrics
from ..utils import get_logger

log = get_logger("serving.fleet")

# rolling per-replica forward latencies backing the least-p99 policy and the
# Fleet view; bounded so a days-long router never grows it
LATENCY_WINDOW = 512
QPS_WINDOW_S = 30.0

# ejection backoff ladder (the Source._backoff shape: exponential, jittered
# to [0.5x, 1x], capped; the exponent is capped so unbounded flapping can't
# overflow 2**n)
BACKOFF_BASE_S = 0.5
BACKOFF_CAP_S = 15.0

# consistent-hash ring: vnodes per replica (enough that key movement on a
# replica death is ~1/N, small enough that ring walks stay trivial)
VNODES = 64

HEALTH_EVERY_S = 1.0
HEALTH_TIMEOUT_S = 2.0

# concurrent forward budget: forwards are IO-bound urllib calls that sleep
# on replica sockets (threads hide IO waits — the one-core law), and the
# fleet's aggregate in-flight ceiling is N replicas x serve depth, so the
# router must hold MORE in flight than any one replica can. asyncio's
# default executor (cpu+4 = 5 threads on the one-core host) capped a
# 4-replica modeled-RTT fleet at ONE replica's throughput — measured, see
# BENCHMARKS.md "Read fleet"
FORWARD_WORKERS = 64


def _jittered_backoff(ejections: int) -> float:
    """Seconds an ejected replica sits out before its next probe — the
    ``Source._backoff`` cap+jitter ladder (streaming/sources.py) applied to
    replicas instead of stream reconnects."""
    base = min(
        BACKOFF_BASE_S * (2 ** min(max(ejections, 1) - 1, 12)),
        BACKOFF_CAP_S,
    )
    return base * (0.5 + 0.5 * random.random())


class Replica:
    """Router-side state for one serve replica. All mutation happens under
    the router's lock; reads for the Fleet view copy plain values."""

    def __init__(self, index: int, url: str):
        self.index = index
        self.url = url.rstrip("/")
        self.healthy = True  # optimistic: the first forward/probe decides
        self.ejections = 0
        self.ejected_until = 0.0
        self.requests = 0
        self.errors = 0
        self.inflight = 0
        self.latencies: "collections.deque[float]" = collections.deque(
            maxlen=LATENCY_WINDOW
        )
        self.completions: "collections.deque[float]" = collections.deque(
            maxlen=LATENCY_WINDOW
        )
        self.last_view: dict = {}

    def p99_s(self) -> float:
        if not self.latencies:
            return 0.0
        vs = sorted(self.latencies)
        return vs[min(len(vs) - 1, int(0.99 * len(vs)))]

    def qps(self, now: float) -> float:
        lo = now - QPS_WINDOW_S
        n = sum(1 for t in self.completions if t >= lo)
        return n / QPS_WINDOW_S


class FleetRouter:
    """The fleet front door's routing core. ``predict`` is thread-safe and
    called from the web server's executor threads; the health loop runs on
    its own daemon thread. Pure stdlib HTTP (urllib), like ServingClient."""

    POLICIES = ("p99", "hash")

    def __init__(
        self,
        urls,
        policy: str = "p99",
        timeout: float = 30.0,
        health_every_s: float = HEALTH_EVERY_S,
    ):
        urls = [u for u in urls if u]
        if not urls:
            raise ValueError("a fleet router needs at least one replica URL")
        if policy not in self.POLICIES:
            raise ValueError(
                f"routePolicy must be one of {self.POLICIES}, got {policy!r}"
            )
        self.policy = policy
        self.timeout = float(timeout)
        self.health_every_s = max(0.05, float(health_every_s))
        self.replicas = [Replica(i, u) for i, u in enumerate(urls)]
        self._lock = threading.Lock()
        self._rr = 0  # round-robin tiebreak cursor
        self._ring: "list[tuple[int, int]]" = []  # (point, replica index)
        for rep in self.replicas:
            for v in range(VNODES):
                digest = hashlib.md5(
                    f"{rep.url}#{v}".encode("utf-8")
                ).digest()
                self._ring.append(
                    (int.from_bytes(digest[:8], "big"), rep.index)
                )
        self._ring.sort()
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        # the front door's forward pool (web/server.py runs predict
        # forwards here instead of asyncio's tiny default executor)
        self.executor = ThreadPoolExecutor(
            max_workers=FORWARD_WORKERS,
            thread_name_prefix="twtml-fleet-fwd",
        )
        reg = _metrics.get_registry()
        self._req_count = reg.counter("router.requests")
        self._retry_count = reg.counter("router.retries")
        self._err_count = reg.counter("router.errors")
        self._eject_count = reg.counter("fleet.replica_ejections")
        self._restore_count = reg.counter("fleet.replica_restores")

    # -- replica selection ---------------------------------------------------
    def _available(self, now: float, exclude: set) -> "list[Replica]":
        """Replicas a forward may try: healthy first; if none, ejected ones
        whose backoff expired (last resort — better a probe-by-forward than
        a guaranteed 503)."""
        healthy = [
            r for r in self.replicas
            if r.index not in exclude and r.healthy
        ]
        if healthy:
            return healthy
        return [
            r for r in self.replicas
            if r.index not in exclude and now >= r.ejected_until
        ]

    def _pick(self, key: bytes, exclude: set) -> "Replica | None":
        now = time.monotonic()
        with self._lock:
            candidates = self._available(now, exclude)
            if not candidates:
                return None
            if self.policy == "hash":
                point = int.from_bytes(
                    hashlib.md5(key).digest()[:8], "big"
                )
                ok = {r.index for r in candidates}
                # walk the ring from the key's point to the first live vnode
                lo, hi = 0, len(self._ring)
                while lo < hi:
                    mid = (lo + hi) // 2
                    if self._ring[mid][0] < point:
                        lo = mid + 1
                    else:
                        hi = mid
                for off in range(len(self._ring)):
                    idx = self._ring[(lo + off) % len(self._ring)][1]
                    if idx in ok:
                        rep = self.replicas[idx]
                        break
                else:  # pragma: no cover - candidates is non-empty
                    rep = candidates[0]
            else:
                # least-p99, QUEUE-AWARE: score = rolling p99 x (in-flight
                # + 1) — the expected completion cost of joining that
                # replica's line. Raw least-p99 herds an open-loop burst:
                # every request routes before any completes, so a stale
                # lower p99 would take the WHOLE burst (measured — a
                # 2-replica fleet ran at one replica's throughput).
                # Round-robin breaks exact ties.
                self._rr += 1
                rep = min(
                    candidates,
                    key=lambda r: (
                        max(r.p99_s(), 1e-3) * (r.inflight + 1),
                        (r.index - self._rr) % max(len(self.replicas), 1),
                    ),
                )
            rep.inflight += 1
            rep.requests += 1
            return rep

    # -- forwarding ----------------------------------------------------------
    def predict(self, body: bytes, key: "bytes | None" = None):
        """Forward one ``POST /api/predict`` body. Returns
        ``(http_status, response_bytes)``. A replica-side failure
        (connection refused, timeout, 5xx) ejects that replica and retries
        the NEXT one — the client sees an error only when EVERY replica is
        down this instant. 4xx pass through untouched (the request's fault,
        not the fleet's)."""
        self._req_count.inc()
        key = body if key is None else key
        tried: set = set()
        first_failure = ""
        while True:
            rep = self._pick(key, tried)
            if rep is None:
                self._err_count.inc()
                detail = first_failure or "no replica available"
                return 503, json.dumps({
                    "error": f"fleet has no live replica ({detail}); "
                    "replicas re-probe on a jittered backoff",
                }).encode("utf-8")
            tried.add(rep.index)
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(
                    rep.url + "/api/predict", data=body,
                    headers={"content-type": "application/json",
                             "accept": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(
                    req, timeout=self.timeout
                ) as resp:
                    payload = resp.read()
                self._record_success(rep, time.perf_counter() - t0)
                return 200, payload
            except urllib.error.HTTPError as exc:
                detail = exc.read()
                if exc.code < 500:
                    # the request itself is bad; every replica would agree
                    self._record_success(rep, time.perf_counter() - t0)
                    return exc.code, detail
                why = f"HTTP {exc.code} from {rep.url}"
            except (urllib.error.URLError, TimeoutError, OSError) as exc:
                why = f"{rep.url} unreachable ({getattr(exc, 'reason', exc)})"
            first_failure = first_failure or why
            self._record_failure(rep, why)
            if len(tried) < len(self.replicas):
                self._retry_count.inc()
                log.warning(
                    "predict forward failed (%s); retrying on another "
                    "replica (%d/%d tried)", why, len(tried),
                    len(self.replicas),
                )

    def _record_success(self, rep: Replica, dt: float) -> None:
        now = time.monotonic()
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
            rep.latencies.append(dt)
            rep.completions.append(now)
            if not rep.healthy:
                rep.healthy = True
                rep.ejected_until = 0.0
                self._restore_count.inc()
                log.info("replica %s recovered (forward succeeded)", rep.url)

    def _record_failure(self, rep: Replica, why: str) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
            rep.errors += 1
            if rep.healthy or rep.ejected_until <= time.monotonic():
                rep.healthy = False
                rep.ejections += 1
                backoff = _jittered_backoff(rep.ejections)
                rep.ejected_until = time.monotonic() + backoff
                self._eject_count.inc()
                log.warning(
                    "ejecting replica %s for %.1fs (ejection #%d): %s",
                    rep.url, backoff, rep.ejections, why,
                )

    # -- health checks -------------------------------------------------------
    def health_check_once(self) -> None:
        """Probe every probe-eligible replica's ``GET /api/serving``: a live
        view restores (or confirms) it; a failure ejects it. Ejected
        replicas are skipped until their jittered backoff expires."""
        now = time.monotonic()
        for rep in self.replicas:
            if not rep.healthy and now < rep.ejected_until:
                continue
            try:
                req = urllib.request.Request(
                    rep.url + "/api/serving",
                    headers={"accept": "application/json"},
                )
                with urllib.request.urlopen(
                    req, timeout=HEALTH_TIMEOUT_S
                ) as resp:
                    view = json.loads(resp.read().decode("utf-8"))
                with self._lock:
                    rep.last_view = view if isinstance(view, dict) else {}
                    if not rep.healthy:
                        rep.healthy = True
                        rep.ejected_until = 0.0
                        self._restore_count.inc()
                        log.info(
                            "replica %s recovered (health probe)", rep.url
                        )
            except Exception as exc:  # lawcheck: disable=TW005 -- not a swallow: the failure drives the ejection ladder right here
                self._record_failure_probe(rep, exc)

    def _record_failure_probe(self, rep: Replica, exc: Exception) -> None:
        with self._lock:
            if rep.healthy or rep.ejected_until <= time.monotonic():
                rep.healthy = False
                rep.ejections += 1
                backoff = _jittered_backoff(rep.ejections)
                rep.ejected_until = time.monotonic() + backoff
                self._eject_count.inc()
                log.warning(
                    "health probe failed for %s; ejected for %.1fs "
                    "(ejection #%d): %s", rep.url, backoff, rep.ejections,
                    exc,
                )

    def start(self) -> "FleetRouter":
        self._thread = threading.Thread(
            target=self._health_loop, name="twtml-fleet-health", daemon=True
        )
        self._thread.start()
        return self

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_every_s):
            try:
                self.health_check_once()
            except Exception:
                log.exception("fleet health sweep failed; retrying")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.executor.shutdown(wait=False)

    # -- the Fleet view ------------------------------------------------------
    def stats(self) -> dict:
        """The ``Fleet`` jsonClass view (/api/fleet + the dashboard's fleet
        tile row): per-replica health/latency/traffic plus the router's
        retry/ejection story. Plain host bookkeeping."""
        now = time.monotonic()
        with self._lock:
            replicas = []
            champion = -1
            for r in self.replicas:
                view = r.last_view or {}
                step = int(view.get("snapshotStep", -1))
                champ = int(view.get("champion", -1))
                if champ >= 0:
                    champion = champ
                replicas.append({
                    "replica": r.index,
                    "url": r.url,
                    "healthy": bool(r.healthy),
                    "p99Ms": round(r.p99_s() * 1e3, 2),
                    "qps": round(r.qps(now), 2),
                    "requests": int(r.requests),
                    "errors": int(r.errors),
                    "ejections": int(r.ejections),
                    "snapshotStep": step,
                })
        return {
            "policy": self.policy,
            "replicas": replicas,
            "requests": int(self._req_count.snapshot()),
            "retries": int(self._retry_count.snapshot()),
            "ejections": int(self._eject_count.snapshot()),
            "champion": champion,
        }
