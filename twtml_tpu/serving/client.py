"""Library-level client for the serving front door (``POST /api/predict``).

Same stdlib-urllib shape as ``telemetry/web_client.py`` — no external HTTP
dependency — but predict calls RAISE on failure instead of the telemetry
client's best-effort ``Try`` semantics: a load generator or an ops script
must see a refused/aborted predict, not silently drop it. The paired serving
bench (``tools/bench_serving.py``) and the serve-smoke tests drive this
client as their load face.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

DEFAULT_SERVER = "http://localhost:8888"


class ServingError(RuntimeError):
    """A predict request failed server-side (watchdog abort, bad rows, or
    serving not attached); ``status`` carries the HTTP code when known."""

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


class ServingClient:
    def __init__(self, server: str = "", timeout: float = 10.0):
        self.server = server or DEFAULT_SERVER
        self.timeout = timeout

    def predict(self, rows) -> dict:
        """POST rows (each a dict with ``text`` + optional author numerics,
        or a bare string) to ``/api/predict``; returns the response dict:
        ``{"predictions": [...], "snapshotStep": N, "servedRows": n}``."""
        body = json.dumps({"rows": list(rows)}).encode("utf-8")
        req = urllib.request.Request(
            self.server + "/api/predict",
            data=body,
            headers={
                "content-type": "application/json",
                "accept": "application/json",
            },
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            detail = ""
            try:
                detail = json.loads(exc.read().decode("utf-8")).get("error", "")
            except Exception:  # lawcheck: disable=TW005 -- not a swallow: only the optional error-detail parse degrades; ServingError is raised right below either way
                pass
            raise ServingError(
                detail or f"predict failed: HTTP {exc.code}", status=exc.code
            ) from exc
        except urllib.error.URLError as exc:
            raise ServingError(f"predict failed: {exc.reason}") from exc

    def predict_texts(self, texts) -> "list[float]":
        """Convenience: predict bare texts, return just the predictions."""
        return [
            float(v)
            for v in self.predict([{"text": t} for t in texts])["predictions"]
        ]

    def serving(self) -> dict:
        """GET the latest ``Serving`` telemetry view (``/api/serving``)."""
        req = urllib.request.Request(
            self.server + "/api/serving",
            headers={"accept": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
