"""Library-level client for the serving front door (``POST /api/predict``).

Same stdlib-urllib shape as ``telemetry/web_client.py`` — no external HTTP
dependency — but predict calls RAISE on failure instead of the telemetry
client's best-effort ``Try`` semantics: a load generator or an ops script
must see a refused/aborted predict, not silently drop it. The paired serving
bench (``tools/bench_serving.py``) and the serve-smoke tests drive this
client as their load face.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request

DEFAULT_SERVER = "http://localhost:8888"

# client retry ladder (ISSUE 11): jittered exponential with a cap — the
# Source._backoff shape (streaming/sources.py), for the same reason at the
# client tier: N clients retrying one briefly-503ing front door must not
# reconnect in phase. Small values on purpose: a predict client rides OVER
# the router's own replica failover, so a retry here only covers the window
# where the WHOLE fleet (or a single-process server) is momentarily down.
RETRY_BACKOFF_BASE_S = 0.1
RETRY_BACKOFF_CAP_S = 2.0
# HTTP statuses worth a retry: 503 (plane not attached yet / fleet draining)
# and 0 (connection refused / reset — the URLError face of a dead server)
RETRYABLE_STATUSES = (0, 502, 503)


class ServingError(RuntimeError):
    """A predict request failed server-side (watchdog abort, bad rows, or
    serving not attached); ``status`` carries the HTTP code when known."""

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


class ServingClient:
    def __init__(self, server: str = "", timeout: float = 10.0,
                 retries: int = 2):
        self.server = server or DEFAULT_SERVER
        self.timeout = timeout
        self.retries = max(0, int(retries))

    @staticmethod
    def _backoff(attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based): exponential, jittered
        to [0.5x, 1x], capped — the ``Source._backoff`` ladder."""
        base = min(
            RETRY_BACKOFF_BASE_S * (2 ** min(attempt - 1, 12)),
            RETRY_BACKOFF_CAP_S,
        )
        return base * (0.5 + 0.5 * random.random())

    def predict(self, rows) -> dict:
        """POST rows (each a dict with ``text`` + optional author numerics,
        or a bare string) to ``/api/predict``; returns the response dict:
        ``{"predictions": [...], "snapshotStep": N, "servedRows": n}``.

        503/connection-refused failures retry up to ``retries`` times on a
        jittered backoff (counted in ``serve.client_retries``); anything
        else — a 400 bad request, a watchdog abort surfaced as plain 500 —
        raises immediately."""
        body = json.dumps({"rows": list(rows)}).encode("utf-8")
        attempt = 0
        while True:
            try:
                return self._predict_once(body)
            except ServingError as exc:
                attempt += 1
                if (
                    exc.status not in RETRYABLE_STATUSES
                    or attempt > self.retries
                ):
                    raise
                from ..telemetry import metrics as _metrics

                _metrics.get_registry().counter("serve.client_retries").inc()
                time.sleep(self._backoff(attempt))

    def _predict_once(self, body: bytes) -> dict:
        req = urllib.request.Request(
            self.server + "/api/predict",
            data=body,
            headers={
                "content-type": "application/json",
                "accept": "application/json",
            },
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            detail = ""
            try:
                detail = json.loads(exc.read().decode("utf-8")).get("error", "")
            except Exception:  # lawcheck: disable=TW005 -- not a swallow: only the optional error-detail parse degrades; ServingError is raised right below either way
                pass
            raise ServingError(
                detail or f"predict failed: HTTP {exc.code}", status=exc.code
            ) from exc
        except urllib.error.URLError as exc:
            raise ServingError(f"predict failed: {exc.reason}") from exc

    def predict_texts(self, texts) -> "list[float]":
        """Convenience: predict bare texts, return just the predictions."""
        return [
            float(v)
            for v in self.predict([{"text": t} for t in texts])["predictions"]
        ]

    def serving(self) -> dict:
        """GET the latest ``Serving`` telemetry view (``/api/serving``)."""
        req = urllib.request.Request(
            self.server + "/api/serving",
            headers={"accept": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def fleet(self) -> dict:
        """GET the latest ``Fleet`` view (``/api/fleet`` — live router
        state on a router process, the cached view elsewhere)."""
        req = urllib.request.Request(
            self.server + "/api/fleet",
            headers={"accept": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
