"""Serving snapshots: verified checkpoints + the ONE promotion predicate.

A snapshot is the weight state of a VERIFIED checkpoint (CRC'd, finite —
checkpoint/checkpointer.py already refuses corrupt and non-finite archives at
restore), gated on the quality stamp PR 8 writes into every checkpoint meta
(``meta["quality"]``, tools/model_report.py renders the history): ``ok`` and
``warn`` snapshots serve, ``alert`` refuses. ``is_promotable`` is that
predicate — tools/model_report.py ``--gate`` imports THIS function, so an ops
script's yes/no and the server's promoter can never disagree.

The promoter is a polling thread over the checkpoint directory (the train
process writes, the serve process reads — decoupled through the filesystem,
ZERO fetches against the training device path): a new promotable step
hot-swaps through ``ServingPlane.hot_swap``, which applies it between
dispatches so an in-flight batch is never torn (serving/plane.py).

jax-free on purpose: the gate tool answers "is this checkpoint servable?"
without initializing any backend.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from ..utils import get_logger

log = get_logger("serving.snapshot")

# quality levels that may serve (the PR 8 graduated ladder); anything else —
# today only "alert" — refuses promotion. Unstamped checkpoints (saved with
# --modelWatch off, or predating the stamp) carry no evidence of trouble and
# stay servable: the stamp gates on KNOWN bad health, it is not a required
# certificate.
SERVABLE_LEVELS = ("ok", "warn")


def is_promotable(meta: "dict | None") -> "tuple[bool, str]":
    """THE promotion predicate over a verified checkpoint's meta:
    (servable?, reason). Shared verbatim by the serving promoter and
    ``tools/model_report.py --gate`` so ops scripts and the serving plane
    can never disagree.

    ``meta`` is the checkpoint meta dict (restore() already verified the
    archive bytes; the ``finite`` flag is re-checked here so a caller
    holding only the meta — the gate tool — reaches the same verdict)."""
    if not isinstance(meta, dict):
        return False, "no checkpoint meta"
    if not meta.get("finite", True):
        return False, "non-finite weights (quarantined save)"
    quality = meta.get("quality")
    if quality is None:
        return True, "servable (unstamped — no quality evidence against it)"
    level = str(quality.get("level", "ok"))
    if level not in SERVABLE_LEVELS:
        return False, (
            f"quality level {level!r} (drift z "
            f"{float(quality.get('drift_score', 0.0)):.2f}, loss trend "
            f"{float(quality.get('loss_trend', 0.0)) * 100:+.1f}%)"
        )
    return True, f"servable (quality level {level!r})"


@dataclass
class ServingSnapshot:
    """One device-promotable weight state. ``weights`` is the checkpoint's
    host array — ``[F+4]`` single-model or the PR 7 tenant stack
    ``[M, F+4]`` (``num_tenants`` reads the stack width)."""

    step: int
    weights: np.ndarray
    meta: dict = field(default_factory=dict)

    @property
    def num_tenants(self) -> int:
        return int(self.weights.shape[0]) if self.weights.ndim == 2 else 1

    @property
    def quality_level(self) -> str:
        quality = self.meta.get("quality") or {}
        return str(quality.get("level", ""))

    @property
    def snapshot_id(self) -> str:
        return f"ckpt-{self.step}"


def load_servable(directory: str) -> "tuple[ServingSnapshot | None, str]":
    """(newest VERIFIED checkpoint as a snapshot, reason) — or (None, why).

    The verified half (CRC + finiteness fallback) is ``Checkpointer.restore``;
    the quality half is ``is_promotable`` on its meta. A newest-verified
    checkpoint that FAILS the quality gate returns (None, reason): the
    promoter's contract is "serve the newest healthy state", not "skip back
    to whatever old state still looks healthy" — a sustained alert should
    hold the CURRENT snapshot, loudly, until training recovers."""
    from ..checkpoint import Checkpointer

    restored = Checkpointer(directory).restore()
    if restored is None:
        return None, f"no verified checkpoint in {directory!r}"
    state, meta = restored
    if isinstance(state, dict):
        # flat-dict states (k-means centers etc.) have no serving program
        return None, (
            "checkpoint state is a pytree, not an SGD weight vector — "
            "not servable by the SGD predict program"
        )
    ok, reason = is_promotable(meta)
    if not ok:
        return None, f"step {meta.get('step', '?')} refused: {reason}"
    return (
        ServingSnapshot(
            step=int(meta.get("step", 0)),
            weights=np.asarray(state),
            meta=dict(meta),
        ),
        reason,
    )


class SnapshotPromoter:
    """Background promotion: poll the checkpoint directory every ``poll_s``
    and hand any NEW promotable step to ``plane.hot_swap`` (atomic — the
    plane applies it between dispatches). Refusals (alert-stamped or
    non-finite newest) are counted and logged ONCE per refused step; the
    plane keeps serving its current snapshot.

    Disk-only by design: promotion never touches a device or issues a host
    fetch, so a co-located trainer's transport path is untouched (the
    zero-added-train-fetches acceptance, tests/test_serving.py)."""

    def __init__(self, directory: str, plane, poll_s: float = 5.0):
        from ..telemetry import metrics as _metrics

        self.directory = directory
        self.plane = plane
        self.poll_s = max(0.05, float(poll_s))
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._refused_step: "int | None" = None
        reg = _metrics.get_registry()
        self._promotions = reg.counter("serve.promotions")
        self._refused = reg.counter("serve.promotions_refused")

    def poll_once(self) -> bool:
        """One promotion check; True when a hot-swap happened. Exposed for
        tests and for the serve app's startup (first snapshot synchronous)."""
        from ..checkpoint import Checkpointer

        latest = Checkpointer(self.directory).latest_step()
        current = self.plane.snapshot_step
        if latest is None or latest <= current:
            return False
        snap, reason = load_servable(self.directory)
        if snap is None:
            if self._refused_step != latest:
                self._refused_step = latest
                self._refused.inc()
                log.warning(
                    "snapshot promotion REFUSED (serving stays on step %d): "
                    "%s", current, reason,
                )
            return False
        if snap.step <= current:
            return False
        self.plane.hot_swap(snap)
        self._promotions.inc()
        self._refused_step = None
        log.info(
            "promoted snapshot step %d -> %d (%s)", current, snap.step, reason
        )
        return True

    def start(self) -> "SnapshotPromoter":
        self._thread = threading.Thread(
            target=self._loop, name="twtml-serve-promoter", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:
                log.exception("snapshot promotion poll failed; retrying")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
