"""Streaming logistic regression with SGD (BASELINE config #3).

Equivalent of MLlib's ``StreamingLogisticRegressionWithSGD``: the same
mini-batch SGD core as the linear model with the logistic gradient
(multiplier σ(w·x) − y, MLlib LogisticGradient) and thresholded class
predictions (σ(w·x) > 0.5 → 1.0, MLlib's default 0.5 threshold). The
reference repo never shipped this model; it's part of the measured baseline
configs (BASELINE.md #3: binary sentiment on the same stream).
"""

from __future__ import annotations

import jax

from .sgd import StreamingSGDModel


def _logistic_residual(raw, label):
    return jax.nn.sigmoid(raw) - label


def _threshold_prediction(raw):
    return (jax.nn.sigmoid(raw) > 0.5).astype(raw.dtype)


class StreamingLogisticRegressionWithSGD(StreamingSGDModel):
    residual_fn = staticmethod(_logistic_residual)
    prediction_fn = staticmethod(_threshold_prediction)
    round_predictions = False  # already a hard 0/1 class
    default_step_size = 0.1  # MLlib StreamingLogisticRegressionWithSGD default
