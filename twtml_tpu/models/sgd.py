"""Fused streaming-SGD step builder — the compute core of the framework.

This is the TPU re-expression of MLlib's ``GradientDescent.runMiniBatchSGD``
driven by ``StreamingLinearRegressionWithSGD.trainOn`` (the reference's hot
loop, SURVEY.md §3.3): per micro-batch, ``numIterations`` rounds of
  sample(miniBatchFraction) → gradient → reduce → w ← w − stepSize/√i · ∇
with the treeAggregate reduction replaced by an in-program ``psum`` over the
``data`` mesh axis when running sharded, and the whole loop compiled as one
XLA program (``lax.fori_loop``) so weights never leave HBM.

MLlib semantics preserved:
- per-iteration learning rate stepSize/√i, 1-indexed (SimpleUpdater);
- L2: w scaled by (1 − η·λ) before the gradient step (SquaredL2Updater) when
  l2_reg > 0 (the reference runs regParam 0; BASELINE config #4 adds L2);
- Bernoulli mini-batch sampling per iteration, seeded by iteration number
  (MLlib uses seed 42+i) — deterministic replay;
- convergence tolerance on successive weight vectors:
  ‖w_{i} − w_{i−1}‖₂ < tol · max(‖w_i‖₂, 1), early-stop;
- an iteration that samples zero points leaves weights unchanged;
- predictions for the batch are computed with pre-update weights
  (predict-then-train, LinearRegression.scala:85-86).

Two feature regimes (see ops/sparse.py): dense [B,F]×[F] MXU matmuls for
small models, gather/scatter for 2^18-dim hashed features.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.backend import axis_size as _axis_size

from ..features.batch import (
    NUM_NUMBER_FEATURES,
    FeatureBatch,
    PackedBatch,
    RaggedUnitBatch,
    UnitBatch,
    unpack_batch,
)
from ..ops.gram import (
    add_numeric_block,
    dual_norm_sq,
    dual_writeback,
    fits_gram,
    gram_matrix,
    text_gram,
)
from ..ops.quality import quality_vector
from ..ops.ragged import ragged_repad
from ..ops.sparse import densify_text, sparse_grad_text, sparse_predict
from ..ops.stats import batch_stats
from ..ops.text_hash import hash_bigrams_device
from ..utils.rounding import jnp_round_half_up
from .base import StepOutput

# Above this text-feature count the dense [B, F] design matrix stops paying
# for itself and the gather/scatter path wins (2^18 dims ≈ 1 GB dense at B=1k).
DENSE_TEXT_FEATURE_LIMIT = 8192

MLLIB_SAMPLING_SEED = 42  # GradientDescent samples with seed 42+i


def sgd_inner_loop(
    weights,
    *,
    num_iterations: int,
    step_size: float,
    mini_batch_fraction: float,
    l2_reg: float,
    convergence_tol: float,
    mask,
    sample_key,
    grad_and_count: Callable,
    norm_sq: Callable | None = None,
    vary_axis: str | None = None,
):
    """The MLlib GradientDescent iteration loop over an arbitrary weight
    pytree — the ONE place the parity-critical semantics live (1-indexed
    eta = stepSize/√i, SquaredL2Updater pre-scale, Bernoulli sampling,
    zero-sample skip, convergence test on successive weight vectors,
    converged-freeze). Both the single-device step below and the
    feature-sharded step (parallel/sharding.py) drive it.

    ``grad_and_count(w, sel)`` must return (gradient-sum pytree, selected
    count), already globally reduced across any mesh axes. ``norm_sq(a, b)``
    returns the global ‖a−b‖² for convergence (default: local sum over
    leaves; sharded layouts pass a psum-ing version). ``vary_axis`` marks
    the loop carry as varying over a manual mesh axis — required when the
    body consumes axis-varying values (e.g. an all-gathered batch) whose
    varying-ness would otherwise mismatch the constant-initialized carry.
    """
    dtype = jax.tree_util.tree_leaves(weights)[0].dtype

    if norm_sq is None:
        def norm_sq(a, b):
            return sum(
                jnp.sum((la - lb) ** 2)
                for la, lb in zip(
                    jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
                )
            )

    def body(i, carry):
        w, converged = carry
        it = i + 1  # MLlib iterations are 1-indexed
        if mini_batch_fraction < 1.0:
            sel = mask * jax.random.bernoulli(
                jax.random.fold_in(sample_key, it), mini_batch_fraction, mask.shape
            ).astype(dtype)
        else:
            sel = mask
        grad_sum, count = grad_and_count(w, sel)
        denom = jnp.maximum(count, 1.0)
        eta = step_size / jnp.sqrt(jnp.asarray(it, dtype))
        w_new = jax.tree_util.tree_map(
            lambda wl, gl: wl * (1.0 - eta * l2_reg) - eta * gl / denom, w, grad_sum
        )
        # zero sampled points → no update (MLlib warns and skips)
        w_new = jax.tree_util.tree_map(
            lambda nl, wl: jnp.where(count > 0, nl, wl), w_new, w
        )
        if convergence_tol > 0:
            delta = jnp.sqrt(norm_sq(w_new, w))
            norm_new = jnp.sqrt(
                norm_sq(w_new, jax.tree_util.tree_map(jnp.zeros_like, w_new))
            )
            # a zero-sample iteration is a skip, not convergence
            conv_now = (count > 0) & (
                delta < convergence_tol * jnp.maximum(norm_new, 1.0)
            )
        else:
            conv_now = jnp.array(False)
        w_out = jax.tree_util.tree_map(
            lambda wl, nl: jnp.where(converged, wl, nl), w, w_new
        )
        return w_out, converged | conv_now

    converged0 = jnp.array(False)
    if vary_axis:
        from ..utils.backend import pcast_varying

        to_varying = lambda x: pcast_varying(x, vary_axis)
        weights = jax.tree_util.tree_map(to_varying, weights)
        converged0 = to_varying(converged0)
    w_final, _ = lax.fori_loop(0, num_iterations, body, (weights, converged0))
    return w_final


def run_dual_loop(
    *,
    u,
    g,
    labels,
    mask,
    dtype,
    residual_fn: Callable,
    num_iterations: int,
    step_size: float,
    mini_batch_fraction: float,
    l2_reg: float,
    convergence_tol: float,
    p_prev,
    vary_axis: str | None = None,
):
    """MLlib's iteration loop in the Gram (dual) basis — the ONE dual-state
    driver both the single-device sparse step (``_gram_sgd`` below) and the
    feature-sharded step (parallel/sharding.py) call, so the parity-critical
    construction (state init, grad shape, sampling key, convergence norm)
    cannot de-synchronize between layouts.

    All row-dimensioned inputs (``u = Z·W_prev``, ``labels``, ``mask``, and
    G's rows) are GLOBAL; under a mesh the loop runs replicated on every
    shard — it is [B]-sized, and collective-free. Sampling draws ONE global
    mask with the unfolded MLlib key, bit-matching the single-device
    trajectory (the scatter loop's per-shard folded keys only match it
    statistically — ``sampling_key`` docstring). Returns the dual state
    {'c', 'alpha'}: W_new = c·W_prev + Zᵀα (write-back is layout-specific).
    """

    def grad_and_count(w, sel):
        raw = w["c"] * u + g @ w["alpha"]
        residual = residual_fn(raw, labels) * sel
        return {"c": jnp.zeros((), dtype), "alpha": residual}, jnp.sum(sel)

    return sgd_inner_loop(
        {"c": jnp.ones((), dtype), "alpha": jnp.zeros(labels.shape, dtype)},
        num_iterations=num_iterations,
        step_size=step_size,
        mini_batch_fraction=mini_batch_fraction,
        l2_reg=l2_reg,
        convergence_tol=convergence_tol,
        mask=mask,
        sample_key=sampling_key(None, mini_batch_fraction),
        grad_and_count=grad_and_count,
        norm_sq=dual_norm_sq(p_prev, u, g),
        vary_axis=vary_axis,
    )


def dual_scale_and_alpha(dual, axis_name: str, rows: int):
    """This shard's slice of the dual state for a sharded write-back:
    (c, α_local). The psum-mean of c turns the identical-everywhere scale
    into a statically-invariant value (shard_map's replicated-output check),
    and slicing α to local rows keeps the write-back scatter 1/shards."""
    alpha_local = lax.dynamic_slice_in_dim(
        dual["alpha"], lax.axis_index(axis_name) * rows, rows
    )
    c = lax.psum(dual["c"], axis_name) / _axis_size(axis_name)
    return c, alpha_local


def sampling_key(axis_name: str | None, mini_batch_fraction: float):
    """MLlib-compatible sampling key (seed 42, GradientDescent's 42+i), with
    the data-shard index folded in under shard_map so shards draw independent
    masks. Sampled subsets therefore differ between mesh layouts (as they do
    between Spark partitionings) but are statistically equivalent;
    fraction=1.0 (the default) is exact."""
    key = jax.random.PRNGKey(MLLIB_SAMPLING_SEED)
    if axis_name and mini_batch_fraction < 1.0:
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
    return key


def make_sgd_train_step(
    *,
    num_text_features: int,
    num_iterations: int,
    step_size: float,
    mini_batch_fraction: float = 1.0,
    l2_reg: float = 0.0,
    convergence_tol: float = 0.001,
    residual_fn: Callable | None = None,
    prediction_fn: Callable | None = None,
    axis_name: str | None = None,
    use_sparse: bool | None = None,
    round_predictions: bool = True,
    use_gram: bool | None = None,
    gram_int8: bool | None = None,
    quality: bool = False,
):
    """Build the fused (weights, batch) → (new_weights, StepOutput) step.

    ``residual_fn(raw, label)`` is the per-example gradient multiplier
    (identity diff for least-squares; σ(raw) − y for logistic), and
    ``prediction_fn(raw)`` maps the raw margin to the reported prediction.
    The returned function is pure and jit/shard_map-composable; wrap with
    ``jax.jit(..., donate_argnums=0)`` to keep weights HBM-resident.

    The inner loop is always the XLA-compiled ``sgd_inner_loop``. A
    VMEM-resident pallas variant exists as reference code
    (ops/pallas_sgd.py, semantics pinned by tests) but is deliberately NOT a
    knob here: at these shapes the step is micro-seconds on device for both
    implementations and the difference is unmeasurable through this build's
    dispatch transport — see BENCHMARKS.md for the full measurement story.

    In the sparse regime the iterations run in the dual (Gram) basis by
    default (ops/gram.py): one MXU matmul builds G = Z·Zᵀ per batch and the
    loop never touches the 2^18 feature space — ~25× the per-iteration
    gather/scatter formulation on a v5e chip at B=2048. With a data axis the
    batch is all-gathered once (G needs cross-shard row products), each
    shard computes its row panel of G (matmul FLOPs scale 1/shards), one
    all-gather replicates G, and the tiny dual loop runs replicated with NO
    per-iteration collectives — versus one gradient psum per iteration (50/
    batch) in the scatter loop. ``use_gram`` False forces the scatter loop
    (the differential baseline); None picks Gram whenever it applies (f32
    weights, dense counts within HBM budget — ops/gram.py ``fits_gram``).
    ``gram_int8`` pins the G build's int8 plane on/off at trace time
    (None = the module default, ops/gram.py ``GRAM_INT8_PLANE``) — threaded
    as a parameter, not a global read, so multi-shape callers (the ragged
    wire retraces per flat-buffer bucket) get ONE consistent plane.

    ``quality`` (ISSUE 8) appends the in-step quality vector
    (ops/quality.py) as ``StepOutput.quality`` — weight/update/gradient
    norms and data moments computed inside this same XLA program, riding
    the existing one-fetch StepOutput. Observation-only: weights,
    predictions, and the five reference stats are bit-identical with it on
    or off, and ``False`` (the default / ``--modelWatch off``) leaves the
    output pytree — hence the compiled program — structurally the
    pre-quality program (the leaf is None).
    """
    f_text = num_text_features
    sparse = f_text > DENSE_TEXT_FEATURE_LIMIT if use_sparse is None else use_sparse
    residual_fn = residual_fn or (lambda raw, label: raw - label)
    prediction_fn = prediction_fn or (lambda raw: raw)

    def _predict_raw(weights, batch: FeatureBatch, x_dense):
        if sparse:
            return sparse_predict(
                weights[:f_text],
                weights[f_text:],
                batch.token_idx,
                batch.token_val,
                batch.numeric.astype(weights.dtype),
            )
        return x_dense @ weights

    def _grad_sum(batch: FeatureBatch, x_dense, residual):
        if sparse:
            g_text = sparse_grad_text(
                batch.token_idx, batch.token_val, residual, f_text
            )
            g_num = residual @ batch.numeric.astype(residual.dtype)
            return jnp.concatenate([g_text, g_num])
        return x_dense.T @ residual

    def _gram_sgd(weights, row_args, local_args):
        """The sparse inner loop in the dual basis: build G (row panels
        sharded under a data axis), drive the shared ``run_dual_loop``, and
        write back — locally, or slice-local + psum under a data axis (which
        both shrinks the scatter 1/shards and gives the replicated-weights
        output the statically-invariant form shard_map requires).

        ``row_args`` are GLOBAL (the caller all-gathers the batch under a
        data axis); ``local_args`` are this shard's rows."""
        token_idx, token_val, numeric, u, mask, labels = row_args
        dtype = weights.dtype
        # G is built in f32 (the MXU accumulation type); the dual loop runs
        # in the weights dtype so the fori_loop carry stays type-stable for
        # low-precision weights. f64 weights never reach here (the auto gate
        # is f32-only — the bf16-plane G build would silently downgrade f64).
        if axis_name:
            rows = u.shape[0] // _axis_size(axis_name)
            panel = text_gram(
                token_idx,
                token_val,
                f_text,
                row_start=lax.axis_index(axis_name) * rows,
                rows=rows,
                int8_plane=gram_int8,
            )  # [B_local, B_global]: the G matmul's FLOPs scale 1/shards
            # (the count build replicates per shard — see text_gram.left)
            g_text = lax.all_gather(panel, axis_name, axis=0, tiled=True)
            g = add_numeric_block(g_text, numeric, dtype)
        else:
            g = gram_matrix(
                token_idx, token_val, numeric, f_text, dtype, int8_plane=gram_int8
            )

        dual = run_dual_loop(
            u=u,
            g=g,
            labels=labels,
            mask=mask,
            dtype=dtype,
            residual_fn=residual_fn,
            num_iterations=num_iterations,
            step_size=step_size,
            mini_batch_fraction=mini_batch_fraction,
            l2_reg=l2_reg,
            convergence_tol=convergence_tol,
            p_prev=jnp.sum(weights * weights),
            vary_axis=axis_name,
        )
        if axis_name:
            l_idx, l_val, l_num = local_args
            c, alpha_local = dual_scale_and_alpha(dual, axis_name, l_val.shape[0])
            delta_text = lax.psum(
                sparse_grad_text(l_idx, l_val, alpha_local, f_text), axis_name
            )
            w_text_new = weights[:f_text] * c + delta_text
            w_num_new = weights[f_text:] * c + lax.psum(
                l_num.T @ alpha_local, axis_name
            )
        else:
            w_text_new, w_num_new = dual_writeback(
                weights[:f_text],
                weights[f_text:],
                dual["c"],
                dual["alpha"],
                token_idx,
                token_val,
                numeric,
            )
        return jnp.concatenate([w_text_new, w_num_new])

    def train_step(weights, batch: FeatureBatch | UnitBatch | PackedBatch):
        dtype = weights.dtype
        if isinstance(batch, PackedBatch):
            # one-buffer wire format: reinterpret in-place (features/batch.py
            # PackedBatch — bit-identical arrays, transfer-count 5 → 1)
            batch = unpack_batch(batch.buffer, batch.layout)
        if isinstance(batch, RaggedUnitBatch):
            # ragged wire: the units arrive concatenated (no per-row pad
            # bytes on the transport); ops/ragged.py rebuilds the padded
            # [B, L] + ASCII fold on device — bit-identical units either way
            buf, lens = ragged_repad(
                batch.units, batch.offsets, batch.row_len, batch.mask.shape[0]
            )
            batch = UnitBatch(
                buf, lens, batch.numeric, batch.label, batch.mask
            )
        if isinstance(batch, UnitBatch):
            # on-device featurization: hash the raw code units inside this
            # same XLA program (ops/text_hash.py); per-occurrence 1.0 values
            # scatter/gather to the identical features host hashing ships
            token_idx, token_val = hash_bigrams_device(
                batch.units, batch.length, f_text, dtype
            )
            batch = FeatureBatch(
                token_idx, token_val, batch.numeric, batch.label, batch.mask
            )
        # tokens arrive in a compact wire dtype (batch.compact_tokens);
        # upcast once on device before any gather/scatter
        batch = batch._replace(
            token_idx=batch.token_idx.astype(jnp.int32),
            token_val=batch.token_val.astype(dtype),
        )
        mask = batch.mask.astype(dtype)
        labels = batch.label.astype(dtype)
        x_dense = None
        if not sparse:
            x_dense = jnp.concatenate(
                [
                    densify_text(batch.token_idx, batch.token_val, f_text),
                    batch.numeric.astype(dtype),
                ],
                axis=1,
            )

        # ---- predict + stats with pre-update weights --------------------
        raw = _predict_raw(weights, batch, x_dense)
        preds = prediction_fn(raw)
        if round_predictions:
            preds = jnp_round_half_up(preds)
        stats = batch_stats(labels, preds, mask, axis_name)

        def _quality(w_new):
            # the ISSUE-8 side channel against the post-update weights;
            # None (plane off) keeps the output pytree the HEAD program's
            if not quality:
                return None
            return quality_vector(
                weights, w_new,
                residual=residual_fn(raw, labels) * mask,
                preds=preds, labels=labels, mask=mask,
                numeric=batch.numeric, token_idx=batch.token_idx,
                token_val=batch.token_val, axis_name=axis_name,
            )

        # ---- numIterations of mini-batch SGD ----------------------------
        b_global = batch.mask.shape[0] * (_axis_size(axis_name) if axis_name else 1)
        gram = (
            sparse
            and dtype == jnp.float32  # see dtype note in _gram_sgd
            and fits_gram(b_global, f_text, num_iterations)
            if use_gram is None
            else use_gram
        )
        if gram:
            numeric = batch.numeric.astype(dtype)
            # ``raw`` above is u = Z·W_prev — the dual loop starts from it
            local_args = (batch.token_idx, batch.token_val, numeric)
            row_args = local_args + (raw, mask, labels)
            if axis_name:
                # ONE all-gather of the batch; the loop runs replicated and
                # collective-free (vs a gradient psum per iteration below)
                row_args = tuple(
                    lax.all_gather(a, axis_name, axis=0, tiled=True)
                    for a in row_args
                )
            w_new = _gram_sgd(weights, row_args, local_args)
            return w_new, StepOutput(
                predictions=preds, quality=_quality(w_new), **stats
            )

        def grad_and_count(w, sel):
            residual = residual_fn(_predict_raw(w, batch, x_dense), labels) * sel
            grad_sum = _grad_sum(batch, x_dense, residual)
            count = jnp.sum(sel)
            if axis_name:
                grad_sum = lax.psum(grad_sum, axis_name)
                count = lax.psum(count, axis_name)
            return grad_sum, count

        w_final = sgd_inner_loop(
            weights,
            num_iterations=num_iterations,
            step_size=step_size,
            mini_batch_fraction=mini_batch_fraction,
            l2_reg=l2_reg,
            convergence_tol=convergence_tol,
            mask=mask,
            sample_key=sampling_key(axis_name, mini_batch_fraction),
            grad_and_count=grad_and_count,
        )
        return w_final, StepOutput(
            predictions=preds, quality=_quality(w_final), **stats
        )

    return train_step


def zero_weights(num_text_features: int, dtype=jnp.float32):
    """MLlib initial weights: zeros(numFeatures) (LinearRegression.scala:32)."""
    return jnp.zeros((num_text_features + NUM_NUMBER_FEATURES,), dtype=dtype)


class StreamingSGDModel:
    """Shared surface of the streaming SGD learners (linear/logistic):
    device-resident weight state, fused jit step with donated weights, conf
    plumbing, and DStream-style ``train_on`` registration. Subclasses set the
    three gradient knobs (``residual_fn``, ``prediction_fn``,
    ``round_predictions``) and a default step size."""

    residual_fn = None  # least-squares when None
    prediction_fn = None  # identity when None
    round_predictions = True
    default_step_size = 0.1
    # single-device steps unpack the one-buffer wire in-program; sharded
    # models don't (a packed buffer has no row sharding), so the app-side
    # pack opt-in keys off this capability (apps/common.py)
    accepts_packed = True

    def __init__(
        self,
        num_text_features: int = 1000,
        num_iterations: int = 50,
        step_size: float | None = None,
        mini_batch_fraction: float = 1.0,
        l2_reg: float = 0.0,
        convergence_tol: float = 0.001,
        dtype=jnp.float32,
        use_sparse: bool | None = None,
        use_gram: bool | None = None,
        gram_int8: bool | None = None,
        quality: bool = False,
    ) -> None:
        self.num_text_features = num_text_features
        self.dtype = dtype
        self._weights = zero_weights(num_text_features, dtype)
        step = make_sgd_train_step(
            num_text_features=num_text_features,
            num_iterations=num_iterations,
            step_size=self.default_step_size if step_size is None else step_size,
            mini_batch_fraction=mini_batch_fraction,
            l2_reg=l2_reg,
            convergence_tol=convergence_tol,
            residual_fn=type(self).residual_fn,
            prediction_fn=type(self).prediction_fn,
            round_predictions=self.round_predictions,
            use_sparse=use_sparse,
            use_gram=use_gram,  # None=auto; False is the scatter-loop escape hatch
            gram_int8=gram_int8,
            quality=quality,  # --modelWatch: the in-step quality side channel
        )
        # donate weights: the update happens in-place in HBM
        self._train_step = step
        self._step = jax.jit(step, donate_argnums=0)
        self._scan_step = None  # built on first step_many

    @classmethod
    def from_conf(cls, conf, **overrides):
        kwargs = dict(
            num_text_features=conf.numTextFeatures,
            num_iterations=conf.numIterations,
            step_size=conf.stepSize,
            mini_batch_fraction=conf.miniBatchFraction,
            l2_reg=conf.l2Reg,
            convergence_tol=conf.convergenceTol,
            dtype=jnp.dtype(conf.dtype),
            quality=getattr(conf, "modelWatch", "off") == "on",
        )
        kwargs.update(overrides)
        return cls(**kwargs)

    def set_initial_weights(self, weights) -> "StreamingSGDModel":
        self._weights = jnp.asarray(weights, dtype=self.dtype)
        return self

    def reset(self) -> "StreamingSGDModel":
        """Back to MLlib's initial state: zero weights (LinearRegression.scala:32)."""
        self._weights = zero_weights(self.num_text_features, self.dtype)
        return self

    @property
    def latest_weights(self):
        import numpy as np

        return np.asarray(self._weights)

    def step(self, batch: FeatureBatch | UnitBatch | PackedBatch) -> StepOutput:
        """Fused predict-then-train on one micro-batch; advances the model.

        Accepts the one-buffer wire format too (``pack_batch``) — bit-
        identical unpack inside the jit step. On the lean RAGGED wire the
        packed form is the shipped default (+11.4% paired, r3 — per-array
        request overhead stops hiding once the wire is lean; the app paths
        pack via the fetch pipeline, apps/common.py); on the padded wire it
        stays an opt-in (measured neutral there — BENCHMARKS.md)."""
        self._weights, out = self._step(self._weights, batch)
        return out

    def step_many(
        self, stacked: FeatureBatch | UnitBatch | RaggedUnitBatch | PackedBatch
    ) -> StepOutput:
        """K micro-batch steps as ONE dispatch — ``lax.scan`` over a stacked
        batch (every array carries a leading [K] axis; ``stack_batches``
        builds one from K same-shape batches, the ragged wire included —
        its [K, N] units buffer scans like any leaf, with row_len static).
        A stacked batch may also arrive PACKED (``pack_batch`` of the
        stacked pytree): the scan program unpacks it in-place first, same
        bitcast contract as ``step``.

        The scan body IS ``step``'s program and the weights chain through it
        exactly as K sequential ``step`` calls would — identical final
        weights, and the returned StepOutput holds each micro-batch's
        predictions/stats along axis 0, so predict-then-train ordering and
        per-batch telemetry are preserved verbatim. What changes is the
        wire: one transfer of K batches (tunnel bandwidth improves with
        size) and one dispatch instead of K — the superbatch ingest mode
        for replay/bench regimes where the stream is ahead of the device.
        """
        if self._scan_step is None:
            inner = self._train_step

            def scanned(weights, wire):
                if isinstance(wire, PackedBatch):
                    wire = unpack_batch(wire.buffer, wire.layout)
                return lax.scan(inner, weights, wire)

            self._scan_step = jax.jit(scanned, donate_argnums=0)
        self._weights, outs = self._scan_step(self._weights, stacked)
        return outs

    def train_on(self, stream) -> None:
        """Register the fused step as a stream output (DStream.trainOn analog;
        the reference registers stats first, then training —
        LinearRegression.scala:53,86 — the fused step preserves that order
        internally)."""
        stream.foreach_batch(lambda batch, _time: self.step(batch))
