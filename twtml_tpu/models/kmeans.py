"""Streaming k-means with exponential forgetting.

TPU-native equivalent of MLlib's ``StreamingKMeans``/``StreamingKMeansModel``
as the reference's experimental entry configures it (KMeans.scala:69-73:
setK(3).setHalfLife(5, "batches").setRandomCenters(2, 0.0); manual per-batch
``latestModel.update(scaledData, decayFactor, timeUnit)`` at KMeans.scala:105).

MLlib update rule, reproduced inside one jit program:
  discount = decayFactor                  (timeUnit = batches)
           = decayFactor^numPoints        (timeUnit = points)
  n_j ← n_j·discount
  c_j ← (c_j·n_j + Σ_{x→j} x) / (n_j + count_j)
  n_j ← n_j + count_j
plus the dying-cluster rule: when the smallest cluster weight falls below
1e-8× the largest, the largest is split in two (±1e-14 perturbation) and the
smallest is replaced.

Assignment uses a [B,k] distance matrix and a one-hot matmul for the per-center
sums — k is small, B is the batch, both land on the MXU.

Data-parallel on a device mesh (``mesh=`` arg): batch rows are sharded over
the ``data`` axis and the per-center sums/counts/num_points become ``psum``s
over ICI — the same treeAggregate→psum translation as the SGD models
(parallel/sharding.py); centers/weights stay replicated, so the decay and
dying-cluster arithmetic is computed identically on every shard.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

BATCHES = "batches"
POINTS = "points"


def _sq_dists(points, centers):
    """[B,k] squared distances via the expanded form — one [B,D]×[D,k]
    matmul (MXU) instead of a [B,k,D] broadcast."""
    return (
        jnp.sum(points * points, axis=1, keepdims=True)
        - 2.0 * points @ centers.T
        + jnp.sum(centers * centers, axis=1)[None, :]
    )


def _update_step(centers, weights, points, mask, decay_factor, time_unit,
                 axis_name=None):
    """One streaming k-means batch update. centers [k,D], weights [k],
    points [B,D], mask [B]. Under shard_map, ``axis_name`` globalizes the
    batch reductions with psum; everything downstream of them is replicated
    arithmetic."""
    k = centers.shape[0]
    assign = jnp.argmin(_sq_dists(points, centers), axis=1)  # [B]
    onehot = jax.nn.one_hot(assign, k, dtype=points.dtype) * mask[:, None]  # [B,k]
    counts = jnp.sum(onehot, axis=0)  # [k]
    sums = onehot.T @ points  # [k, D]

    num_points = jnp.sum(mask)
    if axis_name:
        counts = lax.psum(counts, axis_name)
        sums = lax.psum(sums, axis_name)
        num_points = lax.psum(num_points, axis_name)
    if time_unit == BATCHES:
        discount = jnp.asarray(decay_factor, points.dtype)
    else:
        discount = jnp.asarray(decay_factor, points.dtype) ** num_points
    # an all-padding batch must be a STATE NO-OP: single-host callers skip
    # empty batches before update (apps/kmeans.py, KMeans.scala semantics),
    # but multi-host lockstep DISPATCHES them for collective alignment
    # (streaming/context.py) — no decay, no dying-cluster split
    discount = jnp.where(num_points > 0, discount, 1.0)

    n = weights * discount
    denom = jnp.maximum(n + counts, 1e-16)
    new_centers = (centers * n[:, None] + sums) / denom[:, None]
    # centers with no mass and no history keep their position
    new_centers = jnp.where((n + counts)[:, None] > 0, new_centers, centers)
    new_weights = n + counts

    # dying-cluster rule (MLlib StreamingKMeansModel.update tail)
    largest = jnp.argmax(new_weights)
    smallest = jnp.argmin(new_weights)
    max_w = new_weights[largest]
    min_w = new_weights[smallest]
    dying = (min_w < 1e-8 * max_w) & (num_points > 0)

    half = (max_w + min_w) / 2.0
    c_large = new_centers[largest]
    p = 1e-14 * jnp.maximum(jnp.abs(c_large), 1.0)
    split_centers = new_centers.at[largest].set(c_large + p).at[smallest].set(c_large - p)  # lawcheck: disable=TW004 -- 2-row update over K centers (tiny domain), the MLlib dying-cluster rule
    split_weights = new_weights.at[largest].set(half).at[smallest].set(half)  # lawcheck: disable=TW004 -- 2-row update over K centers (tiny domain), the MLlib dying-cluster rule

    new_centers = jnp.where(dying, split_centers, new_centers)
    new_weights = jnp.where(dying, split_weights, new_weights)
    return new_centers, new_weights, assign


class StreamingKMeans:
    def __init__(
        self,
        k: int = 2,
        decay_factor: float = 1.0,
        time_unit: str = BATCHES,
        mesh=None,
    ):
        self.k = k
        self.decay_factor = decay_factor
        self.time_unit = time_unit
        self.mesh = mesh
        self.num_data = 1 if mesh is None else mesh.shape[mesh.axis_names[0]]
        self.centers: jnp.ndarray | None = None
        self.cluster_weights: jnp.ndarray | None = None
        self._step = None
        self._step_config: tuple | None = None

    def _get_step(self):
        """(Re)build the jitted update when builder methods changed config."""
        cfg = (self.decay_factor, self.time_unit)
        if self._step is None or self._step_config != cfg:
            from functools import partial

            if self.mesh is None:
                self._step = jax.jit(
                    partial(_update_step, decay_factor=cfg[0], time_unit=cfg[1])
                )
            else:
                data_axis = self.mesh.axis_names[0]
                body = partial(
                    _update_step,
                    decay_factor=cfg[0], time_unit=cfg[1], axis_name=data_axis,
                )
                from ..utils import shard_map

                self._step = jax.jit(shard_map()(
                    body,
                    mesh=self.mesh,
                    # centers/weights replicated; rows sharded over 'data'
                    in_specs=(P(), P(), P(data_axis, None), P(data_axis)),
                    out_specs=(P(), P(), P(data_axis)),
                ))
            self._step_config = cfg
        return self._step

    # -- MLlib builder surface (KMeans.scala:69-73) --------------------------
    def set_k(self, k: int) -> "StreamingKMeans":
        self.k = k
        return self

    def set_decay_factor(self, a: float) -> "StreamingKMeans":
        self.decay_factor = a
        return self

    def set_half_life(self, half_life: float, time_unit: str) -> "StreamingKMeans":
        """decayFactor = exp(ln(0.5)/halfLife) — MLlib setHalfLife."""
        self.decay_factor = math.exp(math.log(0.5) / half_life)
        self.time_unit = time_unit
        return self

    def set_random_centers(
        self, dim: int, weight: float, seed: int = 0
    ) -> "StreamingKMeans":
        key = jax.random.PRNGKey(seed)
        self.centers = jax.random.normal(key, (self.k, dim), dtype=jnp.float32)
        self.cluster_weights = jnp.full((self.k,), weight, dtype=jnp.float32)
        return self

    def set_initial_centers(self, centers, weights) -> "StreamingKMeans":
        self.centers = jnp.asarray(centers, dtype=jnp.float32)
        self.cluster_weights = jnp.asarray(weights, dtype=jnp.float32)
        return self

    # -- streaming update ----------------------------------------------------
    def update(self, points, mask=None) -> np.ndarray:
        """One batch update; returns per-point cluster assignments."""
        points = jnp.asarray(points, dtype=jnp.float32)
        if mask is None:
            mask = jnp.ones((points.shape[0],), dtype=jnp.float32)
        else:
            mask = jnp.asarray(mask, dtype=jnp.float32)
        if self.centers is None:
            raise ValueError("call set_random_centers or set_initial_centers first")
        if points.shape[0] % self.num_data:
            raise ValueError(
                f"batch rows {points.shape[0]} not divisible by data shards "
                f"{self.num_data}; pad rows to a multiple of the mesh's data axis"
            )
        self.centers, self.cluster_weights, assign = self._get_step()(
            self.centers, self.cluster_weights, points, mask
        )
        if (
            isinstance(assign, jax.Array)
            and not assign.is_fully_addressable
        ):
            # multi-host mesh: each host gets ITS rows' assignments (the
            # rows it contributed — process-aligned data axis), in global
            # row order; per-row telemetry never crosses hosts
            from ..parallel.distributed import local_rows

            return local_rows(assign)
        return np.asarray(assign)

    def predict(self, points) -> np.ndarray:
        points = jnp.asarray(points, dtype=jnp.float32)
        return np.asarray(jnp.argmin(_sq_dists(points, self.centers), axis=1))

    @property
    def latest_centers(self) -> np.ndarray:
        return np.asarray(self.centers)
