"""Shared learner contracts.

A streaming learner holds device-resident state (weights in HBM — unlike the
reference, which re-serializes driver weights into every batch closure,
LinearRegression.scala:57) and exposes one fused, jit-compiled
predict-then-train step per micro-batch: the incoming batch is scored with the
*pre-update* weights (progressive validation, the reference's explicit
ordering at LinearRegression.scala:85-86), per-batch statistics are reduced
on device, and the SGD iterations run inside the same XLA program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class StepOutput(NamedTuple):
    """Device results of one micro-batch step. ``predictions`` keeps the full
    padded [B] vector (with ``mask`` deciding validity) so telemetry can ship
    the real-vs-pred series like the reference does to Lightning
    (SessionStats.scala:31-33); the scalars are the dashboard stats."""

    predictions: jnp.ndarray  # [B] rounded predictions (pre-update weights)
    count: jnp.ndarray  # scalar — valid rows in this batch (global if psum)
    mse: jnp.ndarray  # scalar — mean((y - round(ŷ))²) over valid rows
    real_stdev: jnp.ndarray  # scalar — population stdev of labels
    pred_stdev: jnp.ndarray  # scalar — population stdev of rounded preds
