"""Shared learner contracts.

A streaming learner holds device-resident state (weights in HBM — unlike the
reference, which re-serializes driver weights into every batch closure,
LinearRegression.scala:57) and exposes one fused, jit-compiled
predict-then-train step per micro-batch: the incoming batch is scored with the
*pre-update* weights (progressive validation, the reference's explicit
ordering at LinearRegression.scala:85-86), per-batch statistics are reduced
on device, and the SGD iterations run inside the same XLA program.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp


class StepOutput(NamedTuple):
    """Device results of one micro-batch step. ``predictions`` keeps the full
    padded [B] vector (with ``mask`` deciding validity) so telemetry can ship
    the real-vs-pred series like the reference does to Lightning
    (SessionStats.scala:31-33); the scalars are the dashboard stats.

    ``quality`` (ISSUE 8, ``--modelWatch``) is the in-step model/data
    quality vector (ops/quality.QUALITY_FIELDS) — [Q] per batch, [M, Q]
    stacked on the tenant plane, [K, Q] under a superbatch scan. It is a
    telemetry side channel riding the existing one-fetch-per-tick
    StepOutput transfer; ``None`` (an empty pytree — the default, and the
    ``--modelWatch off`` state) keeps the step program structurally
    identical to the pre-quality program."""

    predictions: jnp.ndarray  # [B] rounded predictions (pre-update weights)
    count: jnp.ndarray  # scalar — valid rows in this batch (global if psum)
    mse: jnp.ndarray  # scalar — mean((y - round(ŷ))²) over valid rows
    real_stdev: jnp.ndarray  # scalar — population stdev of labels
    pred_stdev: jnp.ndarray  # scalar — population stdev of rounded preds
    quality: Optional[jnp.ndarray] = None  # [QUALITY_WIDTH] side channel
