"""Streaming linear regression with SGD — the flagship model.

TPU-native equivalent of MLlib's ``StreamingLinearRegressionWithSGD`` as the
reference configures it (LinearRegression.scala:28-32: numIterations,
stepSize, miniBatchFraction, zero initial weights over numFeatures dims).
State is a single weight vector resident in device HBM; each micro-batch runs
one fused jit program that scores the batch with pre-update weights
(progressive validation) and then applies the full inner SGD loop
(models/sgd.py). Least-squares gradient (MLlib LeastSquaresGradient) and
HALF_UP-rounded predictions for the reported metrics
(LinearRegression.scala:57, Utils.scala:4-6).
"""

from __future__ import annotations

from .sgd import StreamingSGDModel


class StreamingLinearRegressionWithSGD(StreamingSGDModel):
    residual_fn = None  # least-squares: residual = w·x − y
    prediction_fn = None  # identity link
    round_predictions = True
    default_step_size = 0.005  # reference.conf:4
