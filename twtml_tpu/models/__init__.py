from .base import StepOutput
from .linear import StreamingLinearRegressionWithSGD
from .logistic import StreamingLogisticRegressionWithSGD
from .kmeans import StreamingKMeans

__all__ = [
    "StepOutput",
    "StreamingLinearRegressionWithSGD",
    "StreamingLogisticRegressionWithSGD",
    "StreamingKMeans",
]
