"""Wire schema for the telemetry API — byte-compatible with the reference.

The reference serializes two case classes with json4s ``ShortTypeHints``,
which adds a ``jsonClass`` discriminator field (spark/.../web/ApiTypes.scala:5-17,
WebClient.scala:11; consumed by the browser at js/index.js:9-16 and the cache
at ApiCache.scala:19-20,41-48). The exact same JSON shape is kept so the
reference's dashboards and ours are interchangeable:

  {"jsonClass":"Config","id":"...","host":"...","viz":["..."]}
  {"jsonClass":"Stats","count":0,"batch":0,"mse":0,"realStddev":0,"predStddev":0}
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field


@dataclass
class Config:
    id: str = ""
    host: str = ""
    viz: list[str] = field(default_factory=list)

    json_class = "Config"


@dataclass
class Stats:
    count: int = 0
    batch: int = 0
    mse: int = 0
    realStddev: int = 0
    predStddev: int = 0

    json_class = "Stats"


@dataclass
class Series:
    """Per-batch real/predicted value series — an ADDITIVE message type (no
    reference equivalent; the reference ships these points to the external
    Lightning server only, SessionStats.scala:31-33). Carried on the same
    jsonClass-discriminated wire so legacy dashboards simply ignore it; the
    built-in dashboard renders it as the live chart."""

    real: list[float] = field(default_factory=list)
    pred: list[float] = field(default_factory=list)
    realStddev: float = 0.0
    predStddev: float = 0.0

    json_class = "Series"


@dataclass
class Metrics:
    """Pipeline metrics snapshot — an ADDITIVE message type (no reference
    equivalent) carrying the process-local registry (telemetry/metrics.py)
    and the tunnel-health summary to the dashboard's observability panel.
    Rides the jsonClass-discriminated wire like Series, so legacy dashboards
    ignore it. ``counters``/``gauges`` are flat name→value maps; ``health``
    is TunnelHealthMonitor.summary() (phase, rtt_ms, transitions,
    observations); ``histograms`` (r8) maps name → derived
    count/mean/p50/p95/p99 (the latency tile — raw buckets stay
    registry-side)."""

    counters: dict = field(default_factory=dict)
    gauges: dict = field(default_factory=dict)
    health: dict = field(default_factory=dict)
    histograms: dict = field(default_factory=dict)

    json_class = "Metrics"


@dataclass
class Hosts:
    """Per-host lockstep telemetry view — an ADDITIVE message type (no
    reference equivalent; the reference is single-process). One row per
    host from the sideband matrix that rides the cadence allgather
    (telemetry/sideband.py), plus the straggler attributor's verdict:
    which host gated this tick, which bottleneck-ladder stage, and the
    tick skew. Legacy dashboards ignore it like Series/Metrics."""

    hosts: list = field(default_factory=list)
    straggler: int = -1
    stage: str = ""
    skewMs: float = 0.0
    # elastic membership (r16): current epoch (-1 = not elastic), live
    # member count, and cumulative departed/rejoined hosts — decode
    # defaults keep legacy frames valid
    epoch: int = -1
    liveHosts: int = 0
    departed: int = 0
    rejoined: int = 0
    # r20: the CURRENT lead's uid — uid 0 at launch, moves only at a won
    # election (streaming/membership.py); -1 when the run is not elastic
    leadUid: int = -1

    json_class = "Hosts"


@dataclass
class Tenants:
    """Per-tenant model-plane view — an ADDITIVE message type (no reference
    equivalent; the reference trains ONE model). One row per tenant from
    the stacked StepOutput the pipeline already fetched (telemetry/
    tenants.py), plus the gating tenant (most rows this tick — where the
    shared row bucket binds first) and the active-tenant count. Legacy
    dashboards ignore it like Series/Metrics/Hosts."""

    tenants: list = field(default_factory=list)
    gating: int = -1
    active: int = 0

    json_class = "Tenants"


@dataclass
class ModelHealth:
    """Model & data quality view — an ADDITIVE message type (no reference
    equivalent; the reference has no model-health signal at all). Derived
    by telemetry/modelwatch.py from the in-step quality vector the
    pipeline already fetched (zero added fetches, the PR 1/5 law):
    graduated health level (ok/warn/alert), the max drift z-score and
    loss-trend slope, the weight/update/gradient norms, a rolling mse
    window (the dashboard's loss sparkline), and per-tenant rows on the
    multi-tenant plane. Legacy dashboards ignore it like
    Series/Metrics/Hosts/Tenants."""

    level: str = "ok"
    driftScore: float = 0.0
    lossTrend: float = 0.0
    weightNorm: float = 0.0
    updateNorm: float = 0.0
    gradNorm: float = 0.0
    mse: list = field(default_factory=list)
    tenants: list = field(default_factory=list)
    episodes: int = 0

    json_class = "ModelHealth"


@dataclass
class Serving:
    """Serving-plane view — an ADDITIVE message type (no reference
    equivalent; the reference never served its model). QPS/latency over the
    rolling serve window, the active snapshot (step + its checkpoint
    quality level), cumulative request/row/error totals, and per-tenant
    served-row counts on the multi-tenant plane (serving/plane.py
    ``stats()``). Legacy dashboards ignore it like the other additive
    types."""

    qps: float = 0.0
    rowsPerSec: float = 0.0
    p50Ms: float = 0.0
    p95Ms: float = 0.0
    p99Ms: float = 0.0
    # serving staleness (ISSUE 16): seconds since the active snapshot was
    # installed (-1 before the first install); decode default keeps legacy
    # frames valid
    snapshotAgeS: float = -1.0
    snapshotStep: int = -1
    level: str = ""
    requests: int = 0
    rows: int = 0
    errors: int = 0
    tenants: list = field(default_factory=list)
    # champion/challenger slice (ISSUE 11, serving/abtest.py): -1 /[] on a
    # plain single-model plane; a fleet router reads the champion from this
    # view through the health check it already makes
    champion: int = -1
    shadows: list = field(default_factory=list)
    promotions: int = 0
    refusedPromotions: int = 0

    json_class = "Serving"


@dataclass
class Freshness:
    """End-to-end freshness view — an ADDITIVE message type (no reference
    equivalent). Derived by telemetry/freshness.py from per-batch lineage
    records stamped at the existing pipeline seams (zero added fetches,
    zero added collectives — the PR 1/5/8 law): event-time lag percentiles
    from tweet ``created_at_ms`` to fetch delivery and to stats publish,
    the rolling low-watermark sparkline, the dominant critical-path edge
    with its per-edge tick counts, and the ``--freshnessSloMs`` breach
    state. Legacy dashboards ignore it like the other additive types."""

    batches: int = 0
    rows: int = 0
    eventLagMs: float = -1.0
    eventLagP50Ms: float = -1.0
    eventLagP95Ms: float = -1.0
    eventLagP99Ms: float = -1.0
    publishLagP95Ms: float = -1.0
    watermarkLagMs: float = -1.0
    watermark: list = field(default_factory=list)
    critical: str = ""
    criticalTicks: dict = field(default_factory=dict)
    sloMs: float = 0.0
    breachRun: int = 0
    breaches: int = 0

    json_class = "Freshness"


@dataclass
class Fleet:
    """Read-fleet view — an ADDITIVE message type (no reference equivalent;
    the reference is one process end to end). Published by the fleet
    router (serving/fleet.py ``stats()``): per-replica health/latency/
    traffic tiles, the routing policy, the router's retry/ejection story,
    and the fleet-wide champion tenant on the champion/challenger plane.
    Legacy dashboards ignore it like the other additive types."""

    policy: str = ""
    replicas: list = field(default_factory=list)
    requests: int = 0
    retries: int = 0
    ejections: int = 0
    champion: int = -1

    json_class = "Fleet"


@dataclass
class History:
    """Telemetry-historian view — an ADDITIVE message type (no reference
    equivalent). Published by telemetry/historian.py from its in-memory
    tail ring (the durable segments never get read on the hot path): the
    long-horizon RSS / fetch-RTT / per-tick stage-cost sparklines, the
    least-squares RSS slope (the soak estimator, live), the current
    health phase, historian disk usage, and the perfGuard regression
    count. Legacy dashboards ignore it like the other additive types."""

    samples: int = 0
    runId: int = 0
    phase: str = ""
    rssMb: float = 0.0
    rssSlopeMbPerMin: float = 0.0
    rttMs: float = 0.0
    diskMb: float = 0.0
    regressions: int = 0
    rss: list = field(default_factory=list)
    rtt: list = field(default_factory=list)
    stageMs: list = field(default_factory=list)

    json_class = "History"


TYPES = {"Config": Config, "Stats": Stats, "Series": Series,
         "Metrics": Metrics, "Hosts": Hosts, "Tenants": Tenants,
         "ModelHealth": ModelHealth, "Serving": Serving, "Fleet": Fleet,
         "Freshness": Freshness, "History": History}


def encode(obj: Config | Stats) -> str:
    payload = {"jsonClass": obj.json_class}
    payload.update(asdict(obj))
    return json.dumps(payload)


def decode(text: str) -> Config | Stats:
    """Dispatch on the jsonClass hint (ApiCache.scala:41-48); raises on
    unknown types like the reference logs-and-drops."""
    payload = json.loads(text)
    kind = payload.pop("jsonClass", None)
    cls = TYPES.get(kind)
    if cls is None:
        raise ValueError(f"json not recognized: {text!r}")
    fields = {k: payload[k] for k in cls.__dataclass_fields__ if k in payload}
    return cls(**fields)
