"""Per-batch lineage records for the freshness plane (ISSUE 16).

Every batch that enters the host pipeline gets ONE record stamped at the
existing seams — no new seams, no device work, no fetches:

  open      FeatureStream._process / _run_batch_aligned, right before
            featurize: captures a ``stage_seconds()`` snapshot, one
            ``now_ms()`` read (the TWTML_NOW_MS seam), and the event-time
            span of the batch (min/max ``created_at_ms``).
  dispatch  the four dispatch sites in apps/common (FetchPipeline,
            SuperBatcher group + partial singles, per_batch): moves the
            oldest open record into the in-flight FIFO.
  delivery  FreshnessGuard (outermost delivery wrapper): pops the oldest
            in-flight record and diffs the stage clock against the open
            snapshot — the per-stage deltas name the dominant edge.

Two FIFOs instead of a dict keyed on batch identity because SuperBatcher's
``prepare()`` wrapper hands the handler a DIFFERENT object than the one
``_process`` opened; deliveries are strictly in dispatch order (FetchPipeline
resolves futures FIFO), so positional matching is exact. Dispatches with no
open record (serving-plane predictions, warmup, tests driving a bare
pipeline) push a blank so the FIFOs stay aligned; both deques are bounded so
leaked records (shutdown, shed batches) cannot grow host state.

Module is jax-free and every entry point is a cheap no-op until
``configure(True)`` — ``--freshness off`` never touches the deques, which is
what makes the off arm bit-identical to HEAD.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils.clock import now_ms
from . import sideband as _sideband

# seam-to-seam edges eligible for critical-path attribution (stage-clock
# keys; cumulative wall seconds, diffed open -> delivery per batch)
EDGES = ("source_read", "parse", "featurize", "wire_pack", "dispatch", "fetch")

# bounded FIFOs: deeper than any fetch-pipeline depth * superbatch K we run,
# shallow enough that leaked records are noise, not a leak
MAX_RECORDS = 4096

_LOCK = threading.Lock()
_ON = False
_PREP: deque = deque(maxlen=MAX_RECORDS)
_INFLIGHT: deque = deque(maxlen=MAX_RECORDS)


def configure(on: bool) -> None:
    global _ON
    with _LOCK:
        _ON = bool(on)


def enabled() -> bool:
    return _ON


def _numeric_span(numeric) -> tuple[int, int]:
    """Vectorized span over a ParsedBlock's int64 created_at column."""
    if getattr(numeric, "shape", (0,))[0] == 0:
        return 0, 0
    col = numeric[:, 4]
    col = col[col > 0]
    if col.size == 0:
        return 0, 0
    return int(col.min()), int(col.max())


def _event_span(statuses) -> tuple[int, int]:
    """(min_ms, max_ms) of ``created_at_ms`` over a Status list, a
    ParsedBlock, or a list of ParsedBlocks; zeros mean unknown."""
    numeric = getattr(statuses, "numeric", None)
    if numeric is not None:
        return _numeric_span(numeric)
    lo = hi = 0
    for item in statuses:
        n = getattr(item, "numeric", None)
        if n is not None:
            item_lo, item_hi = _numeric_span(n)
        else:
            ms = getattr(item, "created_at_ms", 0)
            item_lo = item_hi = ms if ms > 0 else 0
        if item_lo > 0 and (lo == 0 or item_lo < lo):
            lo = item_lo
        if item_hi > hi:
            hi = item_hi
    return lo, hi


def _rows(statuses) -> int:
    rows = getattr(statuses, "rows", None)
    if rows is not None:
        return int(rows)
    try:
        return sum(
            int(getattr(item, "rows", 1)) for item in statuses
        )
    except TypeError:
        return 0


def open_batch(statuses) -> None:
    """Stamp a lineage record as the batch enters featurize."""
    if not _ON:
        return
    lo, hi = _event_span(statuses)
    rec = {
        "t_open": time.perf_counter(),
        "opened_ms": now_ms(),
        "stages": _sideband.stage_seconds(),
        "event_min_ms": lo,
        "event_max_ms": hi,
        "rows": _rows(statuses),
    }
    with _LOCK:
        _PREP.append(rec)


def drop_newest() -> None:
    """The just-opened batch was shed before dispatch (skip_empty)."""
    if not _ON:
        return
    with _LOCK:
        if _PREP:
            _PREP.pop()


def mark_dispatch(n: int = 1) -> None:
    """Move the n oldest open records to the in-flight FIFO (called at the
    actual dispatch site). Blank records keep the FIFO aligned when a
    dispatch had no matching open (serving, warmup, bare-pipeline tests)."""
    if not _ON:
        return
    with _LOCK:
        for _ in range(n):
            _INFLIGHT.append(_PREP.popleft() if _PREP else None)


def pop_delivery() -> dict | None:
    """Pop the oldest in-flight record at fetch delivery and enrich it with
    the stage-clock deltas since open. None when the FIFO is empty or the
    record was a blank."""
    if not _ON:
        return None
    with _LOCK:
        rec = _INFLIGHT.popleft() if _INFLIGHT else None
    if rec is None:
        return None
    cur = _sideband.stage_seconds()
    base = rec.get("stages") or {}
    edges = {
        s: max(0.0, (cur.get(s, 0.0) - base.get(s, 0.0)) * 1e3) for s in EDGES
    }
    rec["edges_ms"] = edges
    rec["delivered_ms"] = now_ms()
    rec["e2e_ms"] = (time.perf_counter() - rec["t_open"]) * 1e3
    critical = max(edges, key=edges.get)
    rec["critical"] = critical if edges[critical] > 0.0 else ""
    return rec


def open_event_floor() -> int:
    """Oldest event-time still in flight (min event_min over both FIFOs);
    0 when nothing with a known event time is open — the low-watermark
    input for the current tick."""
    if not _ON:
        return 0
    floor = 0
    with _LOCK:
        for rec in (*_PREP, *_INFLIGHT):
            if rec is None:
                continue
            lo = rec.get("event_min_ms", 0)
            if lo > 0 and (floor == 0 or lo < floor):
                floor = lo
    return floor


def depths() -> tuple[int, int]:
    with _LOCK:
        return len(_PREP), len(_INFLIGHT)


def reset_for_tests() -> None:
    global _ON
    with _LOCK:
        _ON = False
        _PREP.clear()
        _INFLIGHT.clear()
